# Native components: RecordIO library (ctypes-loaded by the Python io
# pipeline) and data packing tools. Parity targets: the reference's
# Makefile builds libcxxnet wrappers + im2bin/im2rec tools
# (/root/reference/Makefile:1-160).

CXX ?= g++
CXXFLAGS = -O3 -fPIC -std=c++17 -Wall
OPENCV_CFLAGS := $(shell pkg-config --cflags opencv4 2>/dev/null)
OPENCV_LIBS := $(shell pkg-config --libs opencv4 2>/dev/null)

PY_CFLAGS := $(shell python3-config --includes 2>/dev/null)
PY_LIBDIR := $(shell python3 -c "import sysconfig; print(sysconfig.get_config_var('LIBDIR'))" 2>/dev/null)
PY_VER := $(shell python3 -c "import sysconfig; print(sysconfig.get_config_var('LDVERSION'))" 2>/dev/null)

LIB = lib/libcxxnet_io.so
WRAPLIB = lib/libcxxnet_wrapper.so
TOOLS = bin/im2rec bin/rec2idx bin/im2bin bin/bin2rec

# the Python-embedding wrapper needs python3 dev headers; skip when absent
ifneq ($(PY_CFLAGS),)
all: $(LIB) $(TOOLS) $(WRAPLIB)
else
all: $(LIB) $(TOOLS)
endif

lib bin:
	mkdir -p $@

$(LIB): src/io/recordio.cc src/io/recordio.h | lib
	$(CXX) $(CXXFLAGS) -shared -o $@ src/io/recordio.cc

$(WRAPLIB): wrapper/cxxnet_wrapper.cc wrapper/cxxnet_wrapper.h | lib
	$(CXX) $(CXXFLAGS) $(PY_CFLAGS) -shared -o $@ \
		wrapper/cxxnet_wrapper.cc \
		-L$(PY_LIBDIR) -Wl,-rpath,$(PY_LIBDIR) -lpython$(PY_VER) -ldl

bin/im2rec: tools/im2rec.cc src/io/recordio.cc src/io/recordio.h | bin
	$(CXX) $(CXXFLAGS) $(OPENCV_CFLAGS) -o $@ tools/im2rec.cc \
		src/io/recordio.cc $(OPENCV_LIBS)

bin/rec2idx: tools/rec2idx.cc src/io/recordio.cc src/io/recordio.h | bin
	$(CXX) $(CXXFLAGS) -o $@ tools/rec2idx.cc src/io/recordio.cc

bin/im2bin: tools/im2bin.cc src/io/binpage.h | bin
	$(CXX) $(CXXFLAGS) -o $@ tools/im2bin.cc

bin/bin2rec: tools/bin2rec.cc src/io/binpage.h src/io/recordio.cc \
		src/io/recordio.h | bin
	$(CXX) $(CXXFLAGS) -o $@ tools/bin2rec.cc src/io/recordio.cc

# smoke for the Matlab mex wrapper: no Matlab in CI, so a functional
# stub mex.h/mxArray stands in for $(MATLAB)/extern (catches
# syntax/type/symbol errors; a real build just swaps the include path)
mex-smoke: lib/cxxnet_mex_smoke.so
lib/cxxnet_mex_smoke.so: wrapper/matlab/cxxnet_mex.cpp \
		wrapper/matlab/mex_stub/mex.h \
		wrapper/matlab/mex_stub/mex_stub.cc \
		wrapper/cxxnet_wrapper.h | lib
	$(CXX) $(CXXFLAGS) -Iwrapper/matlab/mex_stub -shared -o $@ \
		wrapper/matlab/cxxnet_mex.cpp \
		wrapper/matlab/mex_stub/mex_stub.cc

# C host that EXECUTES the mex dispatch table against the functional
# stub + the real embedded-CPython wrapper lib (the CI stand-in for
# running example.m inside Matlab)
mex-driver: bin/mex_driver
bin/mex_driver: wrapper/matlab/mex_driver.cc \
		wrapper/matlab/cxxnet_mex.cpp \
		wrapper/matlab/mex_stub/mex.h \
		wrapper/matlab/mex_stub/mex_stub.cc \
		wrapper/cxxnet_wrapper.h $(WRAPLIB) | bin
	$(CXX) $(CXXFLAGS) -Iwrapper/matlab/mex_stub -o $@ \
		wrapper/matlab/mex_driver.cc \
		wrapper/matlab/cxxnet_mex.cpp \
		wrapper/matlab/mex_stub/mex_stub.cc \
		-Llib -Wl,-rpath,$(abspath lib) -lcxxnet_wrapper

# ---- release bar -----------------------------------------------------
# `make check` is THE release gate: the FULL suite including the e2e
# accuracy gates (MNIST MLP, two ~20min MNIST conv gates, BN/concat
# inception held-out gates). Wall time per round is recorded in
# README.md (r5: 62min, 236 tests, on this 1-core host); `make check-fast`
# (~25min) skips only the MNIST e2e gates and is NOT sufficient for a
# release.
check: all
	python -m pytest tests/ -q

check-fast: all
	python -m pytest tests/ -q --ignore=tests/test_mnist_e2e.py

# cxxlint: the framework-aware static-analysis suite
# (doc/static_analysis.md). Exit 0 clean / 1 findings / 2 usage; also
# enforced inside tier-1 by tests/test_lint.py::test_tree_is_lint_clean.
lint:
	python -m cxxnet_tpu.lint cxxnet_tpu/ tools/ --format json

clean:
	rm -rf lib bin

.PHONY: all clean mex-smoke mex-driver check check-fast lint

"""Net-graph configuration: the ``layer[a->b] = type:name`` DSL.

TPU-native re-implementation of the reference's ``NetConfig``
(``/root/reference/src/nnet/nnet_config.h:26-410``): parses the ordered
config-pair stream into a DAG of named nodes and layers, routing
layer-scoped parameters positionally, with support for

- ``layer[+1]`` / ``layer[+1:tag]`` / ``layer[+0]`` auto-chaining
- ``layer[src->dst]`` with comma-separated multi-node lists
- self-loop layers (``layer[3->3] = softmax``) — loss / in-place layers
- shared layers (``layer[a->b] = share[tag]``) — weight tying
- ``label_vec[a,b) = name`` multi-label field ranges
- ``extra_data_num`` / ``extra_data_shape[i]`` auxiliary inputs

The graph is a plain declarative structure; all tensor work happens in the
functional net built from it (``cxxnet_tpu/nnet/net.py``).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .utils.config import ConfigError, ConfigPairs

_RE_PLUS = re.compile(r"^layer\[\+(\d+)(?::([^\]]+))?\]$")
_RE_ARROW = re.compile(r"^layer\[([^\]>]+)->([^\]]+)\]$")
_RE_LABEL_VEC = re.compile(r"^label_vec\[(\d+),(\d+)\)$")
_RE_SHARE = re.compile(r"^share\[([^\]]+)\]$")


@dataclass
class LayerInfo:
    """One connection in the net DAG (reference ``LayerInfo``, nnet_config.h:34-76)."""
    type: str                      # layer type string, e.g. 'fullc'; 'share' for shared
    name: str = ""                 # optional layer name (finetune matching key)
    nindex_in: List[int] = field(default_factory=list)
    nindex_out: List[int] = field(default_factory=list)
    primary_layer_index: int = -1  # for shared layers: index of the primary layer

    def structure_equal(self, other: "LayerInfo") -> bool:
        return (self.type == other.type and self.name == other.name
                and self.nindex_in == other.nindex_in
                and self.nindex_out == other.nindex_out
                and self.primary_layer_index == other.primary_layer_index)


# layer type strings that act as losses (self-loop, produce gradients)
LOSS_LAYER_TYPES = ("softmax", "lp_loss", "l2_loss", "multi_logistic")


class NetGraph:
    """Parsed network structure + per-layer config + global net params."""

    def __init__(self) -> None:
        self.node_names: List[str] = []
        self.node_name_map: Dict[str, int] = {}
        self.layers: List[LayerInfo] = []
        self.layercfg: List[ConfigPairs] = []
        self.layer_name_map: Dict[str, int] = {}
        self.defcfg: ConfigPairs = []          # global (default) layer params
        self.input_shape: Tuple[int, int, int] = (0, 0, 0)   # (ch, y, x)
        self.extra_data_num: int = 0
        self.extra_shape: List[Tuple[int, int, int]] = []
        self.label_range: List[Tuple[int, int]] = []
        self.label_name_map: Dict[str, int] = {}
        self.updater_type: str = "sgd"
        self.batch_size: int = 0
        self._initialized = False

    # -- public ---------------------------------------------------------

    @property
    def num_nodes(self) -> int:
        return len(self.node_names)

    def layer_index(self, name: str) -> int:
        if name not in self.layer_name_map:
            raise ConfigError("unknown layer name %r" % name)
        return self.layer_name_map[name]

    def node_index(self, name: str) -> int:
        if name not in self.node_name_map:
            raise ConfigError("unknown node name %r" % name)
        return self.node_name_map[name]

    def label_field_index(self, name: str) -> int:
        """Index of a named label field; 'label' is the implicit full range."""
        if name in self.label_name_map:
            return self.label_name_map[name]
        raise ConfigError("unknown label field %r" % name)

    def label_slices(self) -> List[Tuple[str, int, int]]:
        """(name, begin, end) column ranges into the label matrix.

        When no label_vec was configured there is a single field 'label'
        covering column 0..label_width (mirrors nnet.h LabelInfo usage).
        """
        if not self.label_range:
            return [("label", 0, 1)]
        out = []
        inv = {v: k for k, v in self.label_name_map.items()}
        for i, (a, b) in enumerate(self.label_range):
            out.append((inv.get(i, "label"), a, b))
        return out

    def configure(self, cfg: ConfigPairs) -> None:
        """Consume an ordered config stream (reference Configure, nnet_config.h:205-286).

        May be called again after load (structure equality is then checked
        and only per-layer / global params are re-applied).
        """
        first_time = not self._initialized
        if first_time:
            self.node_names = ["in"]
            self.node_name_map = {"in": 0, "0": 0}
        # a re-configure with NO netconfig block (a pred/extract conf
        # against a loaded model — the reference reads layer params from
        # the model file, nnet_config.h:150-189) keeps the saved
        # per-layer params AND in-net defaults instead of wiping them
        has_netconfig = any(n == "netconfig" for n, _ in cfg)
        if first_time or has_netconfig:
            self.defcfg = []
            if not first_time:
                self.layercfg = [[] for _ in self.layers]

        netcfg_mode = 0     # 0: outside, 1: in netconfig, 2: after a layer line
        cfg_top_node = 0
        cfg_layer_index = 0

        for name, val in cfg:
            if name == "extra_data_num":
                num = int(val)
                for i in range(num):
                    nm = "in_%d" % (i + 1)
                    if nm not in self.node_name_map:
                        self.node_names.append(nm)
                        self.node_name_map[nm] = len(self.node_names) - 1
                self.extra_data_num = num
            if name.startswith("extra_data_shape["):
                z, y, x = (int(t) for t in val.split(","))
                self.extra_shape.append((z, y, x))
            if first_time and name == "input_shape":
                z, y, x = (int(t) for t in val.split(","))
                self.input_shape = (z, y, x)
            if name == "batch_size":
                self.batch_size = int(val)
            if netcfg_mode != 2:
                self._set_global_param(name, val)
            if name == "netconfig" and val == "start":
                netcfg_mode = 1
            if name == "netconfig" and val == "end":
                netcfg_mode = 0
            if name.startswith("layer["):
                info = self._parse_layer_line(name, val, cfg_top_node,
                                              cfg_layer_index)
                netcfg_mode = 2
                if first_time:
                    assert len(self.layers) == cfg_layer_index
                    self.layers.append(info)
                    self.layercfg.append([])
                else:
                    if cfg_layer_index >= len(self.layers):
                        raise ConfigError("config layer index exceeds bound")
                    if not info.structure_equal(self.layers[cfg_layer_index]):
                        raise ConfigError(
                            "config setting does not match existing network "
                            "structure at layer %d" % cfg_layer_index)
                cfg_top_node = (info.nindex_out[0]
                                if len(info.nindex_out) == 1 else -1)
                cfg_layer_index += 1
                continue
            if netcfg_mode == 2:
                if self.layers[cfg_layer_index - 1].type == "share":
                    raise ConfigError(
                        "do not set parameters in a shared layer; set them "
                        "in the primary layer")
                self.layercfg[cfg_layer_index - 1].append((name, val))
            else:
                self.defcfg.append((name, val))
        self._initialized = True
        self._validate()

    # -- structure (de)serialization ------------------------------------

    def to_dict(self) -> dict:
        """Serializable structure (reference SaveNet, nnet_config.h:126-143)."""
        return {
            "node_names": list(self.node_names),
            "layers": [{
                "type": l.type, "name": l.name,
                "nindex_in": list(l.nindex_in),
                "nindex_out": list(l.nindex_out),
                "primary_layer_index": l.primary_layer_index,
            } for l in self.layers],
            "layer_name_map": dict(self.layer_name_map),
            "layercfg": [[list(p) for p in lc] for lc in self.layercfg],
            "defcfg": [list(p) for p in self.defcfg],
            "input_shape": list(self.input_shape),
            "extra_data_num": self.extra_data_num,
            "extra_shape": [list(s) for s in self.extra_shape],
            "label_range": [list(r) for r in self.label_range],
            "label_name_map": dict(self.label_name_map),
            "updater_type": self.updater_type,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "NetGraph":
        g = cls()
        g.node_names = list(d["node_names"])
        g.node_name_map = {n: i for i, n in enumerate(g.node_names)}
        g.node_name_map["0"] = 0
        g.layers = [LayerInfo(type=l["type"], name=l["name"],
                              nindex_in=list(l["nindex_in"]),
                              nindex_out=list(l["nindex_out"]),
                              primary_layer_index=l["primary_layer_index"])
                    for l in d["layers"]]
        g.layercfg = [[tuple(p) for p in lc]
                      for lc in d.get("layercfg",
                                      [[] for _ in d["layers"]])]
        g.defcfg = [tuple(p) for p in d.get("defcfg", [])]
        g.layer_name_map = dict(d["layer_name_map"])
        g.input_shape = tuple(d["input_shape"])
        g.extra_data_num = d.get("extra_data_num", 0)
        g.extra_shape = [tuple(s) for s in d.get("extra_shape", [])]
        g.label_range = [tuple(r) for r in d.get("label_range", [])]
        g.label_name_map = dict(d.get("label_name_map", {}))
        g.updater_type = d.get("updater_type", "sgd")
        g._initialized = True
        return g

    # -- internals ------------------------------------------------------

    def _set_global_param(self, name: str, val: str) -> None:
        if name == "updater":
            self.updater_type = val
        m = _RE_LABEL_VEC.match(name)
        if m:
            a, b = int(m.group(1)), int(m.group(2))
            self.label_range.append((a, b))
            self.label_name_map[val] = len(self.label_range) - 1

    def _get_node_index(self, tag: str, alloc_unknown: bool) -> int:
        if tag in self.node_name_map:
            return self.node_name_map[tag]
        if not alloc_unknown:
            raise ConfigError("unknown input node name %r" % tag)
        self.node_names.append(tag)
        idx = len(self.node_names) - 1
        self.node_name_map[tag] = idx
        return idx

    def _parse_node_list(self, spec: str, alloc_unknown: bool) -> List[int]:
        return [self._get_node_index(t.strip(), alloc_unknown)
                for t in spec.split(",")]

    def _parse_layer_line(self, name: str, val: str, top_node: int,
                          cfg_layer_index: int) -> LayerInfo:
        info = LayerInfo(type="")
        m = _RE_PLUS.match(name)
        if m:
            inc = int(m.group(1))
            tag = m.group(2)
            if top_node < 0:
                raise ConfigError(
                    "layer[+%d] used but previous layer has multiple "
                    "outputs; use layer[in->out] instead" % inc)
            info.nindex_in = [top_node]
            if tag is not None and inc == 1:
                info.nindex_out = [self._get_node_index(tag, True)]
            elif inc == 0:
                info.nindex_out = [top_node]
            else:
                auto = "!node-after-%d" % top_node
                info.nindex_out = [self._get_node_index(auto, True)]
        else:
            m = _RE_ARROW.match(name)
            if not m:
                raise ConfigError("invalid layer format %r" % name)
            info.nindex_in = self._parse_node_list(m.group(1), False)
            info.nindex_out = self._parse_node_list(m.group(2), True)

        # value: "type" | "type:name" | "share[tag]" | "share[tag]:name"
        ltype, _, lname = val.partition(":")
        ms = _RE_SHARE.match(ltype)
        if ms:
            info.type = "share"
            stag = ms.group(1)
            if stag not in self.layer_name_map:
                raise ConfigError(
                    "shared layer tag %r not defined before" % stag)
            info.primary_layer_index = self.layer_name_map[stag]
        else:
            info.type = ltype
            if lname:
                if lname in self.layer_name_map:
                    if self.layer_name_map[lname] != cfg_layer_index:
                        raise ConfigError(
                            "layer name %r does not match the name stored "
                            "in the model" % lname)
                else:
                    self.layer_name_map[lname] = cfg_layer_index
                info.name = lname
        return info

    def _validate(self) -> None:
        for li, info in enumerate(self.layers):
            if info.type == "share":
                p = self.layers[info.primary_layer_index]
                if p.type == "share":
                    raise ConfigError("shared layer cannot share a shared layer")
            for ni in info.nindex_in + info.nindex_out:
                if ni < 0 or ni >= len(self.node_names):
                    raise ConfigError(
                        "layer %d references invalid node %d" % (li, ni))

    def node_consumers(self) -> Dict[int, List[int]]:
        """node index -> layer indices reading it (graph adjacency for
        the fusion/layout passes in nnet/net.py: out-degree-1 checks
        decide where BN folds into its conv and where channel padding
        provably fuses away)."""
        cons: Dict[int, List[int]] = {}
        for li, info in enumerate(self.layers):
            for ni in info.nindex_in:
                cons.setdefault(ni, []).append(li)
        return cons

    def effective_type(self, layer_index: int) -> str:
        """Resolve shared layers to their primary layer's type."""
        info = self.layers[layer_index]
        if info.type == "share":
            return self.layers[info.primary_layer_index].type
        return info.type

    def param_layer_index(self, layer_index: int) -> int:
        """Index of the layer owning the parameters (self, or primary if shared)."""
        info = self.layers[layer_index]
        return (info.primary_layer_index if info.type == "share"
                else layer_index)

    def layer_key(self, layer_index: int) -> str:
        """Stable pytree key for a layer's parameters."""
        info = self.layers[layer_index]
        return info.name if info.name else "layer%d" % layer_index

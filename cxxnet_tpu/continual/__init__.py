"""Continual train-while-serve: the production loop in one process.

``task = continual`` composes the subsystems that until now only ran
one-shot — the trainer (nnet), the crash-safe checkpoint writer
(nnet/checkpoint), sealed-artifact export (artifact/bundle), and the
fleet front end with its hot-swap watcher (serve/frontend, serve/swap)
— into one long-lived supervisor: train on a looping iterator while
the fleet serves live traffic, and every ``continual_export_every``
updates run the generation pipeline (eval gate -> verified snapshot ->
sealed bundle -> watcher ``notify()`` -> zero-downtime flip),
continuously for N generations instead of once. See doc/continual.md.
"""

from .loop import ContinualConfig, ContinualLoop, GenerationExporter

__all__ = ["ContinualConfig", "ContinualLoop", "GenerationExporter"]

"""The continual supervisor: train -> gate -> export -> hot-swap, xN.

One process owns both halves of the production loop (doc/continual.md):

- the **trainer** runs on a looping data iterator (epochs stream
  back-to-back; round telemetry keeps its per-epoch shape), driven in
  ``dispatch_period`` windows exactly like the ``task = train`` loop;
- the **fleet front end** (:class:`~cxxnet_tpu.serve.frontend.
  FleetServer`) serves live traffic from ``model_dir`` the whole time,
  hot-swapping through its :class:`~cxxnet_tpu.serve.swap.
  SnapshotWatcher`.

Every ``continual_export_every`` applied updates the loop runs one
**generation attempt**:

1. **eval gate** — a full eval pass; the gated metric must be
   non-worsening against the best deployed generation
   (``continual_gate = min|max``, slack ``continual_gate_eps``). A
   failed gate skips the snapshot AND the export — the fleet keeps
   serving the old generation, training continues, and the attempt is
   recorded (``generation`` record, ``action = "gate_skipped"``).
2. **snapshot** — a digest-verified atomic commit through the
   :class:`~cxxnet_tpu.nnet.checkpoint.CheckpointManager` (the
   background writer is drained before export reads the file back).
3. **export** — the ``task = export`` pipeline sealed in-process by
   :class:`GenerationExporter`: the first generation compiles the
   bucket-ladder executables once, later generations reload weights
   in place (:meth:`~cxxnet_tpu.nnet.trainer.NetTrainer.
   load_weights_inplace` — the executables are weight-agnostic) and
   re-seal with zero new compiles.
4. **flip** — ``FleetServer.notify_watchers()`` wakes the poll thread
   the instant the bundle commits; the watcher shadow-boots the
   bundle (deserialized executables: zero compile events on a
   matching runtime) and flips with zero failed requests. The first
   generation *boots* the fleet instead (there is nothing to swap
   from yet).

The loop honors the CLI's preemption contract: ``should_stop`` is
checked at every dispatch and pipeline boundary, and a preempted run
commits an emergency snapshot, drains the fleet, and reports
``preempted`` so ``main`` can exit 75 (EX_TEMPFAIL).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..artifact.bundle import default_bundle_path, export_bundle
from ..nnet.checkpoint import CheckpointManager
from ..serve import FleetServer, ServeConfig, build_engine

_GATE_MODES = ("min", "max", "off")
_TASKS = ("train", "finetune")


class ContinualConfig:
    """Parsed ``continual_*`` keys (doc/continual.md):

    - ``continual_generations`` — deployed generations to run before a
      clean exit (>= 1).
    - ``continual_export_every`` — applied updates between generation
      attempts (required > 0; boundaries land on dispatch windows, so
      an attempt may run up to ``dispatch_period - 1`` updates late).
    - ``continual_task`` — the loop's training mode: ``train`` (fresh
      init, or resume ``model_in``) or ``finetune`` (remap-aware
      bootstrap from a snapshot/bundle ``model_in``).
    - ``continual_eval`` — eval block name the gate reads (default:
      the first eval block).
    - ``continual_metric`` — metric tag the gate compares (default:
      the first configured metric, e.g. ``error``).
    - ``continual_gate`` — ``min`` (smaller is better: error, logloss
      — the default), ``max`` (larger is better: rec@k), or ``off``
      (every attempt exports).
    - ``continual_gate_eps`` — slack: ``min`` passes while
      ``value <= best + eps`` (``max``: ``value >= best - eps``).
    - ``continual_swap_timeout_s`` — how long to wait for the watcher
      flip before recording ``swap_timeout`` (the bundle stays
      committed; the watcher flips it on a later poll).
    - ``continual_linger_s`` — serve-only window after the last
      generation before the clean drain (lets in-flight client load
      finish against the final generation).
    - ``continual_max_updates`` — safety bound on total applied
      updates (0 = unbounded); a gate that never passes ends the run
      here instead of looping forever.
    - ``continual_index_rows`` — when > 0, capture the first N valid
      training rows as a retrieval corpus and re-embed + rebuild the
      embedding index with every generation's weights, sealed into the
      generation bundle beside them (doc/retrieval.md) — the hot-swap
      flips model and index as one unit. 0 (default) exports
      index-less bundles.
    """

    def __init__(self, cfg: Sequence[Tuple[str, str]]):
        self.generations = 3
        self.export_every = 0
        self.task = "train"
        self.eval_name = ""
        self.metric = ""
        self.gate = "min"
        self.gate_eps = 0.0
        self.swap_timeout_s = 120.0
        self.linger_s = 0.0
        self.max_updates = 0
        self.index_rows = 0
        for name, val in cfg:
            if name == "continual_generations":
                self.generations = int(val)
            if name == "continual_export_every":
                self.export_every = int(val)
            if name == "continual_task":
                if val not in _TASKS:
                    raise ValueError(
                        "continual_task must be train|finetune, got %r"
                        % val)
                self.task = val
            if name == "continual_eval":
                self.eval_name = val
            if name == "continual_metric":
                self.metric = val
            if name == "continual_gate":
                if val not in _GATE_MODES:
                    raise ValueError(
                        "continual_gate must be min|max|off, got %r"
                        % val)
                self.gate = val
            if name == "continual_gate_eps":
                self.gate_eps = float(val)
            if name == "continual_swap_timeout_s":
                self.swap_timeout_s = float(val)
            if name == "continual_linger_s":
                self.linger_s = float(val)
            if name == "continual_max_updates":
                self.max_updates = int(val)
            if name == "continual_index_rows":
                self.index_rows = int(val)
        if self.generations < 1:
            raise ValueError("continual_generations must be >= 1")
        if self.export_every < 1:
            raise ValueError(
                "task=continual requires continual_export_every >= 1 "
                "(applied updates between generation attempts)")

    def passes(self, value: float, best: Optional[float]) -> bool:
        if self.gate == "off" or best is None:
            return True
        if self.gate == "min":
            return value <= best + self.gate_eps
        return value >= best - self.gate_eps

    def ratchet(self, value: float, best: Optional[float]) -> float:
        """The new best after a deploy: the BEST value ever deployed,
        not the last — with eps slack, comparing against the last
        value would let the metric drift one eps per generation
        without ever failing the gate."""
        if best is None or self.gate == "off":
            return value
        return min(best, value) if self.gate == "min" \
            else max(best, value)


class GenerationExporter:
    """Per-generation ``task = export`` without per-generation
    compiles: the first :meth:`export` builds and warms a bucket
    engine from the snapshot (the one compile window of the whole
    loop); later calls reload weights in place — the AOT executables
    take weights as *arguments*, so identical avals mean the sealed
    programs stay valid — and re-seal a fresh bundle. The engine and
    the training trainer never share device state: serving contracts
    (bucket mesh, frozen serve tree) stay isolated from the live
    update path."""

    def __init__(self, cfg: Sequence[Tuple[str, str]], monitor=None):
        self.cfg = list(cfg)
        self.sc = ServeConfig(self.cfg)
        self._mon = monitor
        self.engine = None
        self.compiled_programs = 0       # gen-1 warmup compiles
        self.index_metric = "dot"
        for name, val in self.cfg:
            if name == "index_metric":
                self.index_metric = val

    def export(self, snapshot: str, out: str,
               corpus: Optional[np.ndarray] = None) -> Dict[str, Any]:
        """Seal ``snapshot`` into a committed bundle at ``out``;
        returns the ``export`` record fields. With a ``corpus`` (raw
        host rows), re-embed it through THIS generation's weights and
        seal the rebuilt index into the same bundle — the search
        programs land in the shared registry under the same
        ``search_sig`` keys, so generation 1 pays their compiles once
        and every later rebuild re-seals the family with zero new
        compiles."""
        if self.engine is None:
            engine = build_engine(
                self.cfg, snapshot, buckets=self.sc.buckets,
                max_batch=self.sc.max_batch, node=self.sc.node,
                monitor=self._mon)
            # warm_run off: export needs the executables, not the
            # first-request latency of a live server. The engine is
            # kept only once warmup succeeds — a failed warmup must
            # not leave a half-initialized engine that every later
            # generation would reuse to seal unwarmed bundles
            self.compiled_programs = engine.warmup(warm_run=False)
            self.engine = engine
        else:
            self.engine.trainer.load_weights_inplace(snapshot)
        retrieval = None
        if corpus is not None and corpus.shape[0] > 0:
            retrieval = self._build_retrieval(corpus, out)
        return export_bundle(self.engine, out, node=self.sc.node,
                             monitor=self._mon, retrieval=retrieval)

    def _build_retrieval(self, corpus: np.ndarray, out: str):
        from ..retrieval import EmbeddingIndex, RetrievalEngine
        t0 = time.time()
        vecs = np.asarray(self.engine.run(corpus), np.float32)
        index = EmbeddingIndex.build(
            ids=np.arange(corpus.shape[0], dtype=np.int64),
            vectors=vecs.reshape(corpus.shape[0], -1),
            metric=self.index_metric, node=self.sc.node)
        spec = self.sc.search_buckets
        buckets = tuple(sorted({int(t) for t in spec.split(",")
                                if t.strip()})) \
            if spec and spec != "auto" else None
        r = RetrievalEngine(index, self.engine.trainer.programs,
                            k=self.sc.search_k or 10,
                            buckets=buckets, monitor=self._mon)
        budget = int(
            self.engine.trainer.serve_device_mem_budget * 1e6)
        r.warmup(warm_run=False, budget_bytes=budget)
        if self._mon is not None and self._mon.enabled:
            self._mon.emit(
                "index_build", out=out, rows=index.rows,
                dim=index.dim, metric=index.metric,
                node=self.sc.node, bytes=index.nbytes,
                wall_ms=(time.time() - t0) * 1e3)
        return r


class ContinualLoop:
    """The supervisor. Construct with an initialized trainer and live
    iterators (the CLI's ``_task_continual`` wires these from the
    ordinary config path), then :meth:`run`.

    ``should_stop`` is polled at every boundary (the CLI passes its
    SIGTERM/SIGINT flag); ``on_generation(record)`` fires after every
    generation attempt's record is emitted — the soak drivers
    (``tools/serve_bench.py --generations``, the tier-1 test) use it
    to coordinate client traffic with the loop's lifecycle.
    """

    def __init__(self, cfg: Sequence[Tuple[str, str]], trainer,
                 itr_train, eval_iters: Sequence[Tuple[str, Any]],
                 model_dir: str,
                 path_for: Callable[[int], str],
                 monitor=None,
                 should_stop: Optional[Callable[[], bool]] = None,
                 on_generation: Optional[Callable[[Dict], None]] = None,
                 checkpoint_async: bool = True,
                 checkpoint_fsync: bool = True,
                 keep_snapshots: int = 0,
                 start_counter: int = 1,
                 dispatch_period: int = 8):
        self.cfg = list(cfg)
        self.cc = ContinualConfig(self.cfg)
        self.trainer = trainer
        self.itr_train = itr_train
        self.eval_iters = list(eval_iters)
        self.model_dir = model_dir
        self.path_for = path_for
        self._mon = monitor
        self._should_stop = should_stop or (lambda: False)
        self._on_generation = on_generation
        self._ckpt_kw = dict(async_=bool(checkpoint_async),
                             fsync=bool(checkpoint_fsync),
                             keep=int(keep_snapshots))
        self.next_counter = max(1, int(start_counter))
        self.dispatch_period = max(1, int(dispatch_period))
        self.fleet: Optional[FleetServer] = None
        self.exporter = GenerationExporter(self.cfg, monitor=monitor)
        self._round = 0
        # retrieval corpus capture (continual_index_rows): RAW rows,
        # not embeddings — every generation re-embeds them through its
        # own weights so the sealed index always matches the bundle
        self._corpus_parts: List[np.ndarray] = []
        self._corpus_got = 0
        # (model_id, router generation) -> last observed post-warmup
        # compile count of that engine: each engine contributes its
        # LAST observation exactly once to the loop total, however
        # many attempts observe it (a swap_timeout leaves the same
        # engine current across attempts)
        self._compile_counts: Dict[Tuple[str, int], int] = {}
        if self.cc.gate != "off" and not self.eval_iters:
            raise ValueError(
                "task=continual with continual_gate=%s needs an eval "
                "iterator block (or continual_gate = off)"
                % self.cc.gate)
        if self.cc.eval_name:
            names = [n for n, _ in self.eval_iters]
            if self.cc.eval_name not in names:
                raise ValueError(
                    "continual_eval %r names no eval block (have %s)"
                    % (self.cc.eval_name, names))

    # -- telemetry helpers -----------------------------------------------

    def _mon_on(self) -> bool:
        return self._mon is not None and self._mon.enabled

    def _emit(self, event: str, **fields) -> None:
        if self._mon_on():
            self._mon.emit(event, **fields)

    def _line(self, text: str) -> None:
        if self._mon is not None:
            self._mon.line(text)
        else:
            print(text)

    # -- training drive --------------------------------------------------

    def _stream(self):
        """Infinite batch stream with per-epoch round bookkeeping —
        the 'looping iterator' half of the loop. Epoch boundaries keep
        the round telemetry shape of ``task = train`` (round_start /
        round_end with examples/sec), and the monotone-round invariant
        of the step records holds across generations."""
        t = self.trainer
        while True:
            t.start_round(self._round)
            self._emit("round_start", round=self._round)
            n = 0
            for batch in self.itr_train:
                n += 1
                yield batch
            if n == 0:
                # an empty pass would spin this loop at full speed
                # (unbounded round records, next() never returning)
                raise ValueError(
                    "task=continual: the training iterator produced "
                    "no batches in a full pass — check the data "
                    "block (round_batch may be dropping the only "
                    "partial batch)")
            t.end_round()
            self._emit("round_end", round=self._round,
                       examples=t.last_round_examples,
                       wall_s=t.last_round_wall_s,
                       examples_per_sec=t.last_round_examples_per_sec)
            self._round += 1

    def _capture_corpus(self, stream):
        """Tee the first ``continual_index_rows`` valid training rows
        off the batch stream as the retrieval corpus (host copies —
        the iterator/transform may hand back recycled or device
        arrays)."""
        want = self.cc.index_rows
        for batch in stream:
            if self._corpus_got < want:
                n = min(batch.batch_size - batch.num_batch_padd,
                        want - self._corpus_got)
                if n > 0:
                    self._corpus_parts.append(np.array(
                        np.asarray(batch.data)[:n], np.float32))
                    self._corpus_got += n
            yield batch

    def _corpus_rows(self) -> Optional[np.ndarray]:
        if not self._corpus_parts:
            return None
        if len(self._corpus_parts) > 1:
            self._corpus_parts = [
                np.concatenate(self._corpus_parts, axis=0)]
        return self._corpus_parts[0]

    def _train_until(self, stream, target_updates: int) -> bool:
        """Advance the trainer to ``target_updates`` applied updates in
        dispatch windows; False when preempted mid-way. Boundaries
        land on window edges, so the attempt may overshoot by up to
        ``dispatch_period - 1`` updates — never undershoot."""
        t = self.trainer
        k = self.dispatch_period
        while t.update_counter < target_updates:
            if self._should_stop():
                return False
            window = [next(stream) for _ in range(k)]
            if k == 1:
                t.update(window[0])
            else:
                t.update_many(window)
        return True

    # -- the generation pipeline -----------------------------------------

    def _gate_value(self) -> Tuple[str, str, float]:
        """(eval block name, metric tag, value) of the gated metric
        for this attempt — one full eval pass (the same pass also
        lands in the stream as an ``eval`` record)."""
        if not self.eval_iters:
            return "", "", -1.0
        name, itr = self.eval_iters[0]
        if self.cc.eval_name:
            name, itr = next((n, it) for n, it in self.eval_iters
                             if n == self.cc.eval_name)
        line, vals = self.trainer.evaluate_metrics(itr, name)
        if not vals:
            if self.cc.gate == "off":
                return name, "", -1.0    # ungated, nothing to record
            raise ValueError(
                "task=continual: no metrics configured — the eval "
                "gate needs at least one metric[...] key "
                "(or continual_gate = off)")
        tag = self.cc.metric or next(iter(vals))
        if tag not in vals:
            raise ValueError(
                "continual_metric %r is not among the configured "
                "metrics %s" % (tag, sorted(vals)))
        self._line("[gen %d]%s" % (self.next_counter, line))
        return name, tag, vals[tag]

    def _note_engine_compiles(self) -> None:
        """Record the CURRENT engines' post-warmup compile counters —
        called before each swap retires an engine and again at close.
        Keyed by (model, router generation), so the same engine
        observed across attempts (a swap_timeout keeps it current)
        just updates its entry instead of double-counting."""
        if self.fleet is None:
            return
        for mid in self.fleet.router.ids():
            e = self.fleet.router.resolve(mid)
            snap = e.session.engine.counters_snapshot()
            self._compile_counts[(e.model_id, e.generation)] = \
                int(snap["compile_events"])

    def _serve_compile_total(self) -> int:
        return sum(self._compile_counts.values())

    def _start_fleet(self) -> None:
        cfg = list(self.cfg)
        if not any(k == "serve_models" for k, _ in cfg):
            # default the fleet onto the loop's own model_dir (the
            # cfg's model_in — the finetune source — must NOT become
            # a pinned serve source)
            cfg.append(("serve_models", "default=%s" % self.model_dir))
        self.fleet = FleetServer(cfg, monitor=self._mon)
        self.fleet.start()
        self._line(
            "continual: fleet listening http=%s binary=%s, models: %s"
            % (self.fleet.http_port, self.fleet.binary_port,
               ", ".join("%s@%04d" % (d["model"], d["counter"])
                         for d in self.fleet.describe())))

    def _await_swap(self, counter: int) -> Tuple[bool, float]:
        """Wait for the watcher flip to ``counter`` (kicked via
        ``notify_watchers``); (flipped, wall_s)."""
        mid = self.fleet.router.default_id
        t0 = time.monotonic()
        deadline = t0 + self.cc.swap_timeout_s
        while time.monotonic() < deadline:
            if self.fleet.router.resolve(mid).counter >= counter:
                return True, time.monotonic() - t0
            if self._should_stop():
                break
            time.sleep(0.02)
        return False, time.monotonic() - t0

    def _attempt(self, stream, best: Optional[float]
                 ) -> Tuple[str, Optional[float], Dict[str, Any]]:
        """One generation attempt after its training window:
        gate -> snapshot -> export -> flip. Returns (action, new best,
        record)."""
        t0 = time.perf_counter()
        counter = self.next_counter
        eval_name, tag, value = self._gate_value()
        rec: Dict[str, Any] = {
            "generation": counter, "counter": counter,
            "metric": tag, "value": value, "eval": eval_name,
            "train_updates": int(self.trainer.update_counter),
            "path": "",
        }
        if not self.cc.passes(value, best):
            # failed gate: no snapshot, no export — the fleet keeps
            # serving the old generation and training continues
            rec.update(action="gate_skipped", gate_best=best,
                       wall_ms=(time.perf_counter() - t0) * 1e3)
            self._line(
                "continual: generation %d gate FAILED (%s %g vs best "
                "%g + eps %g) — keeping generation %d serving"
                % (counter, tag, value, best, self.cc.gate_eps,
                   counter - 1))
            return "gate_skipped", best, rec
        ckpt = self._ckpt
        ckpt.save(counter)
        ckpt.wait()                      # export reads the file back
        snap = self.path_for(counter)
        out = default_bundle_path(snap)
        try:
            stats = self.exporter.export(snap, out,
                                         corpus=self._corpus_rows())
        except Exception as e:
            # failing to *upgrade* must never take down what works:
            # warn, keep serving, keep training (the committed
            # snapshot is still a valid swap target for the watcher,
            # at shadow-build compile cost instead of zero)
            if self._mon is not None:
                self._mon.warn_once(
                    "continual_export_failed:%04d" % counter,
                    "generation %d export failed (%s); the fleet "
                    "keeps serving the previous generation" %
                    (counter, e))
            rec.update(action="export_failed", gate_best=best,
                       wall_ms=(time.perf_counter() - t0) * 1e3)
            # advance past the committed-but-unexported snapshot: the
            # watcher may flip to it meanwhile (at shadow-build
            # compile cost), and a retry at the SAME counter would
            # make _await_swap see "already flipped" and record a
            # deployment whose bundle is not actually serving
            self.next_counter += 1
            return "export_failed", best, rec
        self._emit("export", **stats)
        rec["path"] = out
        if self.fleet is None:
            self._start_fleet()
            rec.update(boot=True, swapped=False, swap_wall_s=0.0)
        else:
            self._note_engine_compiles()  # last look at the retiring
            #                               engine's counters
            self.fleet.notify_watchers()
            flipped, swap_wall = self._await_swap(counter)
            rec.update(boot=False, swapped=flipped,
                       swap_wall_s=round(swap_wall, 3))
            if not flipped:
                rec.update(action="swap_timeout", gate_best=best,
                           wall_ms=(time.perf_counter() - t0) * 1e3)
                self._line(
                    "continual: generation %d exported but the swap "
                    "did not land within %gs (the watcher flips it "
                    "on a later poll)"
                    % (counter, self.cc.swap_timeout_s))
                # the artifact IS deployed-pending; counters advance
                # so the next generation does not collide
                self.next_counter += 1
                return "swap_timeout", self.cc.ratchet(value, best), rec
        # the swapped-in engine's compile counter right after the
        # flip: the zero-compile acceptance surface of the soak
        mid = self.fleet.router.default_id
        snap_c = self.fleet.router.resolve(mid) \
            .session.engine.counters_snapshot()
        rec.update(action="deployed", gate_best=best,
                   swap_compile_events=int(snap_c["compile_events"]),
                   export_programs=int(stats.get("programs", 0)),
                   wall_ms=(time.perf_counter() - t0) * 1e3)
        self._line(
            "continual: generation %d deployed (%s, %s) in %.1fs"
            % (counter,
               "%s %g" % (tag, value) if tag else "ungated",
               "fleet boot" if rec.get("boot") else
               "hot-swap %.2fs" % rec["swap_wall_s"],
               rec["wall_ms"] / 1e3))
        self.next_counter += 1
        # ratchet against the BEST deployed value, not the last —
        # consecutive comparison would drift one eps per generation
        return "deployed", self.cc.ratchet(value, best), rec

    # -- run ---------------------------------------------------------------

    def run(self) -> Dict[str, Any]:
        cc = self.cc
        t_start = time.time()
        updates0 = int(self.trainer.update_counter)
        deployed = skipped = failed = 0
        best: Optional[float] = None
        preempted = False
        stream = self._stream()
        if cc.index_rows > 0:
            stream = self._capture_corpus(stream)
        self._ckpt = CheckpointManager(
            self.trainer, self.path_for, model_dir=self.model_dir,
            monitor=self._mon, **self._ckpt_kw)
        try:
            while deployed < cc.generations:
                if cc.max_updates and (self.trainer.update_counter
                                       - updates0) >= cc.max_updates:
                    self._line(
                        "continual: continual_max_updates=%d reached "
                        "with %d/%d generations deployed — stopping"
                        % (cc.max_updates, deployed, cc.generations))
                    break
                target = self.trainer.update_counter + cc.export_every
                if not self._train_until(stream, target):
                    preempted = True
                    break
                if self._should_stop():
                    preempted = True
                    break
                action, best, rec = self._attempt(stream, best)
                self._emit("generation", **rec)
                if self._on_generation is not None:
                    self._on_generation(rec)
                if action == "deployed":
                    deployed += 1
                elif action == "gate_skipped":
                    skipped += 1
                else:
                    failed += 1
            if preempted:
                # emergency snapshot at the boundary we stopped on —
                # resume (continue = 1) picks it up; it never gated,
                # so it deliberately carries NO bundle (the watcher
                # only flips artifacts a generation attempt sealed)
                self._ckpt.save(self.next_counter, emergency=True)
            elif cc.linger_s > 0:
                # serve-only tail: in-flight client load finishes
                # against the final generation before the drain
                deadline = time.monotonic() + cc.linger_s
                while time.monotonic() < deadline \
                        and not self._should_stop():
                    time.sleep(0.05)
        finally:
            self._ckpt.close()
            self._note_engine_compiles()  # the final engines
            fleet_summary: Dict[str, Any] = {}
            if self.fleet is not None:
                fleet_summary = self.fleet.close()
        updates = int(self.trainer.update_counter) - updates0
        wall = time.time() - t_start
        req = fleet_summary.get("requests", {})
        swaps = int(fleet_summary.get("swaps", 0))
        summary = {
            "generations": deployed + skipped + failed,
            "deployed": deployed, "gate_skipped": skipped,
            "export_failed": failed, "updates": updates,
            "swaps": swaps, "wall_s": round(wall, 3),
            "serve_compile_events": self._serve_compile_total(),
            "requests": int(req.get("requests", 0)),
            "request_errors": int(req.get("error", 0)
                                  + req.get("closed", 0)),
            "preempted": preempted,
        }
        self._emit("continual", **summary)
        self._line(
            "continual: %d generation(s) deployed (%d gate-skipped, "
            "%d failed), %d updates, %d hot-swaps, %d serve requests "
            "(%d errors), %d post-warmup serve compiles, %ld sec"
            % (deployed, skipped, failed, updates, swaps,
               summary["requests"], summary["request_errors"],
               summary["serve_compile_events"], int(wall)))
        return summary

"""Record vocabulary and validation for the monitor event stream.

One place defines what each event must carry, so the smoke test, the
bench capture, and any downstream consumer of ``BENCH_r*.json``
throughput fields all check against the same contract. Validation is
deliberately structural (required keys, value sanity) rather than a
full JSON-Schema dependency: the container must not grow new packages.

Cross-record invariants checked by :func:`validate_records`:

- every record carries ``event`` (known type) and a float ``t``
- all ``*_ms`` / ``*_s`` timings and ``examples_per_sec`` are
  non-negative finite numbers
- ``step`` records carry a strictly-increasing step counter and a
  non-decreasing round
"""

from __future__ import annotations

import json
import math
from typing import Any, Dict, Iterable, List

# required payload keys per event type (beyond "event"/"t")
REQUIRED: Dict[str, tuple] = {
    "run_start": ("task", "config_hash", "jax_version", "platform",
                  "process_count", "device_count", "mesh"),
    "round_start": ("round",),
    "step": ("step", "round", "dispatch", "n_batches", "examples",
             "wall_ms", "data_wait_ms", "examples_per_sec",
             "update_counter", "lr", "compile"),
    "compile": ("kind", "wall_ms", "signature"),
    "memory": ("round", "available", "devices"),
    "io_wait": ("round", "count", "total_ms", "max_ms", "p50_ms",
                "p99_ms", "buckets"),
    # per-round input-pipeline health: zero-copy assembly reuse +
    # prefetch H2D overlap (doc/observability.md)
    "pipeline": ("round", "buffer_reuse_rate", "h2d_overlap_ratio",
                 "batches", "h2d_ms", "consumer_wait_ms"),
    # one-time AOT compile window (precompile = 1)
    "precompile": ("wall_ms", "programs"),
    # static per-model records (emitted once per init/monitor attach):
    # analytic FLOPs for MFU math + the layout/fusion pass decisions
    "model_info": ("flops_per_example", "train_flops_per_example",
                   "params", "layers"),
    "layout": ("channel_pad", "layers_padded", "input_layout",
               "bn_fuse_relu", "bn_fold_eval_pairs"),
    "eval": ("round", "name", "metrics"),
    "round_end": ("round", "examples", "wall_s", "examples_per_sec"),
    "trace_start": ("dir",),
    "trace_stop": ("dir",),
    "warning": ("code", "message"),
    "log": ("text",),
    "test_io": ("instances", "wall_s", "instances_per_sec"),
    "task_end": ("task",),
    "run_end": ("wall_s", "steps", "examples"),
    # serving telemetry (doc/serving.md): per-request outcome + waits,
    # per-micro-batch fill/pad/device split, and the close-time rollup
    "serve_request": ("status", "rows", "queue_ms", "latency_ms"),
    "serve_batch": ("batch", "status", "rows", "requests", "bucket",
                    "pad_rows", "fill_rate", "pad_fraction",
                    "queue_ms", "device_ms"),
    "serve_summary": ("requests", "rows", "batches", "rejected",
                      "timeouts", "errors", "latency_p50_ms",
                      "latency_p99_ms", "fill_rate", "pad_fraction",
                      "wall_s"),
    # fleet serving (doc/serving.md "Fleet serving"): per-request
    # protocol outcome (both HTTP and binary funnel through one core),
    # per-tenant quota sheds, and checkpoint-driven hot-swaps
    "serve_http": ("protocol", "status", "model", "tenant", "rows",
                   "latency_ms"),
    "tenant_shed": ("tenant", "model", "rows", "rate", "burst"),
    "hot_swap": ("model", "old_counter", "new_counter", "path",
                 "warmup_programs", "old_requests", "wall_ms"),
    # horizontal fleet (doc/serving.md "Horizontal fleet"): the
    # balancer's per-request routing outcome (which replica answered,
    # how many transparent retries a replica loss cost), the
    # controller's scale / replica-lifecycle actions, and the canary
    # rollout decision trail (start / promote / rollback — the
    # promote/rollback record doubles as the schema-validated decision
    # record written to canary_out)
    "fleet_route": ("protocol", "status", "model", "tenant", "rows",
                    "replica", "version", "retries", "latency_ms",
                    "coalesced", "channel", "balancer"),
    # one per coalesced super-batch forward (fleet_coalesce_ms > 0):
    # how many client requests merged, the rows they carried, which
    # replica/channel answered, and the forward wall time — the
    # balancer-side twin of serve_batch (doc/serving.md "Fleet data
    # path")
    "fleet_batch": ("model", "replica", "status", "requests", "rows",
                    "channel", "retries", "latency_ms", "balancer"),
    "fleet_scale": ("action", "replicas", "ready", "reason"),
    # sharded front tier (doc/serving.md "Sharded front tier"): one
    # record per quota-share rebalance on a door — which tenants'
    # fractions moved toward observed demand, over what window. The
    # fleet-wide over-admission bound is "configured rate x one such
    # window" (tests/test_fleet_front_tier.py pins it)
    "quota_rebalance": ("balancer", "tenants", "window_s", "shares"),
    "canary": ("phase", "baseline_version", "canary_version",
               "fraction", "reason"),
    # crash-safe checkpointing (doc/checkpointing.md): per-snapshot
    # commit accounting (phase split shows the training thread paid
    # only gather_ms when async), retention GC, the validated-resume
    # decision, preemption exits, and recovered remote-read retries
    "checkpoint": ("path", "counter", "status", "bytes", "digest",
                   "gather_ms", "serialize_ms", "write_ms", "fsync_ms",
                   "async_write", "emergency"),
    "checkpoint_gc": ("removed", "kept"),
    "resume": ("source", "counter", "scanned", "quarantined"),
    "preempt": ("signal", "round", "exit_code"),
    "stream_retry": ("uri", "what", "attempts"),
    # low-precision inference (doc/perf_profile.md "Low-precision
    # inference"): the task=quantize calibration+parity rollup, and the
    # per-load activation record a trainer emits when serve_dtype turns
    # a calibrated snapshot into a quantized graph
    "quantize": ("dtype", "batches", "layers", "fallback_layers",
                 "parity_max_abs", "parity_mean_abs", "agree_rate",
                 "out", "wall_ms"),
    "quantized_model": ("dtype", "layers", "fallback_layers", "native"),
    # device-resident serve weights (doc/serving.md "Device memory
    # accounting"): emitted at freeze — per-model resident device
    # bytes (tree + retained masters, buffer-deduplicated), the
    # one-time quantize/fold wall time, and how many layers hoisted
    # their per-dispatch weight work into the freeze
    "weight_residency": ("bytes", "tree_bytes", "master_bytes",
                         "quantize_ms", "layers", "dtype", "active"),
    # sealed model artifacts (doc/artifacts.md): the task=export
    # rollup, and the honest per-boot accounting of a bundle load —
    # hits (executables deserialized, never re-lowered) vs rebuilds
    # (fingerprint mismatch / bad blob: those keys re-lower+compile
    # on demand); hits + rebuilds always equals the bundle's program
    # count
    "export": ("out", "snapshot", "programs", "members", "bytes",
               "wall_ms"),
    "artifact_load": ("path", "fingerprint_match", "hits", "rebuilds",
                      "wall_ms"),
    # multi-host SPMD training (doc/distributed.md): the input/mesh
    # topology a dist (or dryrun) run trains under, the per-round
    # per-host input-shard accounting (rows_per_host sums exactly to
    # the round's real rows — the exactly-once invariant, counted),
    # the elastic world-size-change handoff a resumed run detects,
    # and the recovered process-group collective retries
    "dist_topology": ("hosts", "local_devices", "world_devices",
                      "dryrun", "mesh", "global_batch"),
    "dist_shard": ("round", "hosts", "rows_per_host", "batches"),
    "dist_resize": ("old_hosts", "new_hosts", "counter",
                    "start_record"),
    "dist_retry": ("what", "attempts", "recovered"),
    # one per world size of the dryrun scaling sweep
    # (parallel/scaling.py, the bench.py --hosts capture path behind
    # MULTICHIP_r*.json): throughput, the data-wait share of the step
    # wall time, and the per-host consumed-row accounting
    "scaling_point": ("hosts", "local_devices", "global_batch",
                      "examples_per_sec", "data_wait_share",
                      "rows_per_host", "zero_recompiles"),
    # per-step time/byte split under a grad_sync mode
    # (parallel/gradsync.py, emitted per scaling-sweep point and by
    # bench.py --hosts): gradient-program wall, the standalone
    # group-granular reduce wall, the full dispatched step wall, the
    # hidden-reduce fraction, and the optimizer-state footprint —
    # logical (unsharded) vs distinct bytes resident per host (the
    # ZeRO-1 optim_shard win, ~1/hosts) plus the lr_mult=0 groups
    # whose state allocation was skipped (doc/distributed.md
    # "Overlapped gradient sync")
    "step_breakdown": ("hosts", "grad_sync", "optim_shard", "groups",
                       "bucket_mb", "backprop_ms", "reduce_ms",
                       "step_ms", "overlap_ratio", "grad_bytes",
                       "opt_state_bytes_unsharded",
                       "opt_state_bytes_per_host", "frozen_groups"),
    # continual train-while-serve (doc/continual.md): the per-layer
    # finetune carry accounting (task=finetune and the loop's
    # bootstrap), one record per generation attempt (the gate
    # decision trail — "deployed" rows carry the gated eval value the
    # soak's monotone check reads), and the loop's close-time rollup
    "finetune": ("source", "source_digest", "carried", "remapped",
                 "fresh", "frozen_groups"),
    "generation": ("generation", "counter", "action", "metric",
                   "value", "train_updates", "path", "wall_ms"),
    "continual": ("generations", "deployed", "gate_skipped",
                  "updates", "swaps", "wall_s"),
    # embedding retrieval (doc/retrieval.md): the task=build_index
    # rollup (corpus shape, metric, source node, sealed bytes), and
    # the engine-vs-oracle spot check — "recall" is the fraction of
    # probe queries whose exact top-1 matched (1.0 for a healthy
    # exact index)
    "index_build": ("out", "rows", "dim", "metric", "node", "bytes",
                    "wall_ms"),
    "retrieval": ("queries", "k", "metric", "recall", "wall_ms"),
}

_TIMING_KEYS = ("wall_ms", "data_wait_ms", "total_ms", "max_ms",
                "mean_ms", "p50_ms", "p99_ms", "h2d_ms",
                "consumer_wait_ms", "wall_s", "examples_per_sec",
                "instances_per_sec", "queue_ms", "latency_ms",
                "device_ms", "latency_p50_ms", "latency_p99_ms",
                "rows_per_sec", "gather_ms", "serialize_ms",
                "write_ms", "fsync_ms", "quantize_ms",
                "backprop_ms", "reduce_ms", "step_ms", "window_s")

# ratio fields must sit in [0, 1]
_RATIO_KEYS = ("buffer_reuse_rate", "h2d_overlap_ratio", "fill_rate",
               "pad_fraction", "agree_rate", "data_wait_share",
               "overlap_ratio", "recall")


def validate_record(rec: Dict[str, Any]) -> List[str]:
    """Structural check of one record; returns a list of problems."""
    errs: List[str] = []
    ev = rec.get("event")
    if ev is None:
        return ["record has no 'event' field: %r" % (rec,)]
    if ev not in REQUIRED:
        return ["unknown event type %r" % ev]
    t = rec.get("t")
    if not isinstance(t, (int, float)) or t <= 0:
        errs.append("%s: bad timestamp %r" % (ev, t))
    for key in REQUIRED[ev]:
        if key not in rec:
            errs.append("%s: missing required key %r" % (ev, key))
    for key in _TIMING_KEYS:
        if key in rec:
            v = rec[key]
            if (not isinstance(v, (int, float)) or v < 0
                    or not math.isfinite(v)):
                errs.append("%s: %s must be a non-negative finite "
                            "number, got %r" % (ev, key, v))
    for key in _RATIO_KEYS:
        if key in rec:
            v = rec[key]
            if not isinstance(v, (int, float)) or not (0 <= v <= 1):
                errs.append("%s: %s must be a ratio in [0, 1], got %r"
                            % (ev, key, v))
    return errs


def validate_records(records: Iterable[Dict[str, Any]],
                     strict: bool = True) -> List[str]:
    """Validate a record stream, including cross-record invariants
    (monotonic step counter, non-decreasing round). With ``strict``
    (default) raises ValueError on the first batch of problems;
    otherwise returns them."""
    errs: List[str] = []
    last_step = 0
    last_round = None
    for i, rec in enumerate(records):
        for e in validate_record(rec):
            errs.append("record %d: %s" % (i, e))
        if rec.get("event") == "run_start":
            # a new run's counters start over (concatenated streams)
            last_step, last_round = 0, None
        if rec.get("event") == "step":
            step = rec.get("step")
            if isinstance(step, int):
                if step <= last_step:
                    errs.append(
                        "record %d: step counter not monotonic "
                        "(%s after %s)" % (i, step, last_step))
                last_step = step
            rnd = rec.get("round")
            if isinstance(rnd, int):
                if last_round is not None and rnd < last_round:
                    errs.append("record %d: round went backwards "
                                "(%s after %s)" % (i, rnd, last_round))
                last_round = rnd
    if errs and strict:
        raise ValueError("invalid monitor records:\n  "
                         + "\n  ".join(errs))
    return errs


def read_jsonl(path: str) -> List[Dict[str, Any]]:
    """Load a monitor JSONL file (skipping blank lines)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out

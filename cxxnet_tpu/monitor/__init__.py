"""Observability: structured per-step tracing and a metrics sink.

The reference surfaced exactly two signals — the round eval line
(metric.h printing format) and the ``round %8d:[%8d] %ld sec elapsed``
progress print. This subsystem keeps those lines byte-identical (they
are the *parity surface*) and adds a structured event stream beside
them, configured through the same ``key = value`` config grammar:

- ``monitor = none|stdout|jsonl`` — sink selection. ``none`` (default)
  is a true no-op: no per-step host sync, no extra device transfers,
  and stdout stays byte-identical to the unmonitored build.
- ``monitor_path`` — JSONL output file for ``monitor = jsonl``
  (default ``monitor.jsonl``; truncated per run, one JSON object per
  line — one file is one run's stream).
- ``monitor_flush_period`` — seconds between sink flushes (0 = flush
  every record).
- ``monitor_rotate_mb`` — size bound on the live JSONL file (0 =
  unbounded); crossing it atomically rotates to ``<path>.<n>`` so a
  long-lived ``task = continual`` process cannot grow one unbounded
  stream.
- ``monitor_trace_dir`` — when set, a ``jax.profiler`` trace is
  captured into this directory over a round window, so a perf trace is
  one config line away.
- ``monitor_trace_begin`` / ``monitor_trace_end`` — first/last round
  (0-based) of the trace window; both default to round 1 (skipping the
  compile-heavy round 0).

Multi-process runs gate emission on process 0 (the rabit
``IsRoot``-style gating main.py already applies to prints,
cxxnet_main.cpp:424-435): non-root ranks get a null sink so one run
produces one stream. Record vocabulary and validation live in
``cxxnet_tpu.monitor.schema``.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "Monitor", "NullSink", "StdoutSink", "JsonlSink", "MemorySink",
    "LatencyHistogram", "create_monitor", "config_hash",
    "device_memory_snapshot", "get_global", "set_global", "warn_once",
]


# -- sinks ---------------------------------------------------------------


class NullSink:
    """Drop everything. ``Monitor.enabled`` is False over this sink, so
    callers skip record assembly entirely — the monitor = none fast
    path costs one attribute check."""

    enabled = False

    def write(self, record: Dict[str, Any]) -> None:
        pass

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class StdoutSink:
    """Structured records as JSON lines on stdout, interleaved with the
    parity text lines (which print unchanged — filtering lines that
    start with ``{`` recovers the exact unmonitored output). ``log``
    records are dropped: their text was already printed verbatim by
    ``Monitor.line`` and echoing it as JSON would duplicate content."""

    enabled = True

    def write(self, record: Dict[str, Any]) -> None:
        if record.get("event") == "log":
            return
        sys.stdout.write(json.dumps(record, sort_keys=True) + "\n")

    def flush(self) -> None:
        sys.stdout.flush()

    def close(self) -> None:
        self.flush()


class JsonlSink:
    """Write records to a JSONL file, flushing every
    ``flush_period`` seconds (0 = every record). Buffering bounds the
    per-step file-system cost; ``close()`` always drains. The file is
    truncated per run — one file is one run's stream (re-running with
    the same monitor_path must not interleave runs, and the schema's
    monotonic-step check reads one run at a time); point monitor_path
    at distinct files to keep history.

    ``rotate_mb`` > 0 bounds the live file: once a record write takes
    it past the limit, the file atomically rotates to
    ``<path>.<n>`` (``os.replace`` — a reader tailing the live path
    sees the old stream or the new one, never a torn file) and a
    fresh ``<path>`` continues the run. A long-lived ``task =
    continual`` process would otherwise grow one unbounded file
    (``monitor_rotate_mb``, doc/observability.md). Rotation failure
    (read-only dir, cross-device quirk) warns once on stderr and
    keeps appending to the current file — losing the bound, never the
    records."""

    enabled = True

    def __init__(self, path: str, flush_period: float = 1.0,
                 rotate_mb: float = 0.0):
        self.path = path
        self.flush_period = max(0.0, float(flush_period))
        self.rotate_bytes = int(max(0.0, float(rotate_mb)) * 1e6)
        self.rotations = 0
        self._written = 0
        self._rotate_broken = False
        # one file set = one run: a re-run reusing this monitor_path
        # truncates the live file, so any rotated segments of a
        # previous run must go too — a stale <path>.<n> would
        # interleave two runs' streams for any consumer walking the
        # segment chain. Unconditional: a rerun with rotation OFF
        # must not inherit the rotated history either.
        n = 1
        while True:
            try:
                os.remove("%s.%d" % (path, n))
            except OSError:
                break                    # first gap ends the chain
            n += 1
        self._f = open(path, "w")
        self._last_flush = time.monotonic()
        # serve workers emit from several threads into one stream;
        # unsynchronized writes would interleave bytes mid-line
        self._wlock = threading.Lock()

    def write(self, record: Dict[str, Any]) -> None:
        line = json.dumps(record, sort_keys=True) + "\n"
        with self._wlock:
            self._f.write(line)
            self._written += len(line)
            if self.rotate_bytes and self._written >= self.rotate_bytes:
                self._rotate_locked()
            now = time.monotonic()
            if now - self._last_flush >= self.flush_period:
                self._f.flush()
                self._last_flush = now

    def _rotate_locked(self) -> None:
        """Rotate under ``_wlock``: flush, atomically rename the live
        file aside, reopen a fresh one. Record boundaries only — a
        record never splits across files. NEVER raises: a sink
        failure must not take down the run it observes (the warn_once
        discipline, but latched locally — routing through the monitor
        would re-enter this sink)."""
        if self._rotate_broken:
            return
        try:
            self._f.flush()
            target = "%s.%d" % (self.path, self.rotations + 1)
            os.replace(self.path, target)
        except OSError as e:
            self._rotate_broken = True   # warn once, keep appending
            sys.stderr.write(
                "[cxxnet_tpu monitor] warning monitor_rotate_failed: "
                "could not rotate %r (%s); the stream keeps appending "
                "to the current file without a size bound\n"
                % (self.path, e))
            return
        old = self._f
        try:
            self._f = open(self.path, "w")
        except OSError as e:
            # the rename committed but a fresh file will not open:
            # fall back to the (renamed) old handle — still a valid
            # stream, just no longer at the live path
            self._f = old
            self._rotate_broken = True
            sys.stderr.write(
                "[cxxnet_tpu monitor] warning monitor_rotate_failed: "
                "rotated %r but could not reopen it (%s); records "
                "continue into the rotated file\n" % (self.path, e))
            return
        old.close()
        self.rotations += 1
        self._written = 0

    def flush(self) -> None:
        with self._wlock:
            self._f.flush()
            self._last_flush = time.monotonic()

    def close(self) -> None:
        with self._wlock:
            if not self._f.closed:
                self._f.flush()
                self._f.close()


class MemorySink:
    """In-process record list — the test/bench sink (bench.py reads
    its throughput from these records instead of re-derived timers)."""

    enabled = True

    def __init__(self):
        self.records: List[Dict[str, Any]] = []

    def write(self, record: Dict[str, Any]) -> None:
        self.records.append(record)

    def clear(self) -> None:
        self.records = []

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


# -- latency histogram ---------------------------------------------------


class LatencyHistogram:
    """Power-of-two millisecond buckets for host-side wait latencies
    (batch fetch in the prefetch chain). observe() is two float ops and
    an int increment — cheap enough for the per-batch path, and only
    attached at all when monitoring is on."""

    # bucket upper bounds in ms; last bucket is open-ended
    BOUNDS = (0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0,
              256.0, 512.0, 1024.0)

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self.counts = [0] * (len(self.BOUNDS) + 1)
        self.n = 0
        self.total_ms = 0.0
        self.max_ms = 0.0

    def observe(self, seconds: float) -> None:
        ms = seconds * 1e3
        self.n += 1
        self.total_ms += ms
        if ms > self.max_ms:
            self.max_ms = ms
        for i, b in enumerate(self.BOUNDS):
            if ms <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def percentile(self, q: float) -> float:
        """Estimate the q-th percentile (q in [0, 1]) from the bucket
        counts: linear interpolation inside the bucket the rank lands
        in, capped by the observed max. Bucketed estimation keeps
        observe() O(1); the power-of-two bounds give <=2x resolution,
        plenty for 'did the tail collapse' comparisons."""
        if self.n == 0:
            return 0.0
        rank = q * self.n
        seen = 0
        lo = 0.0
        for i, hi in enumerate(self.BOUNDS):
            c = self.counts[i]
            if seen + c >= rank and c > 0:
                frac = (rank - seen) / c
                return min(lo + (hi - lo) * frac, self.max_ms)
            seen += c
            lo = hi
        return self.max_ms               # rank in the open-ended bucket

    def snapshot(self) -> Dict[str, Any]:
        buckets = {}
        for i, b in enumerate(self.BOUNDS):
            if self.counts[i]:
                buckets["<=%gms" % b] = self.counts[i]
        if self.counts[-1]:
            buckets[">%gms" % self.BOUNDS[-1]] = self.counts[-1]
        mean = self.total_ms / self.n if self.n else 0.0
        return {"count": self.n, "total_ms": round(self.total_ms, 3),
                "mean_ms": round(mean, 3),
                "max_ms": round(self.max_ms, 3),
                "p50_ms": round(self.percentile(0.50), 3),
                "p99_ms": round(self.percentile(0.99), 3),
                "buckets": buckets}


# -- monitor -------------------------------------------------------------


class Monitor:
    """Event logger over one sink.

    ``line(text)`` is the parity channel: the text prints to stdout
    exactly as the unmonitored code did (callers keep their own
    silent/is_root gating), and enabled sinks additionally record it as
    a ``log`` event. ``emit(event, **fields)`` is the structured
    channel; it is a no-op over a null sink.
    """

    def __init__(self, sink=None, trace_dir: str = "",
                 trace_begin: int = 1, trace_end: Optional[int] = None):
        self.sink = sink if sink is not None else NullSink()
        self.trace_dir = trace_dir
        self.trace_begin = trace_begin
        self.trace_end = trace_begin if trace_end is None else trace_end
        self._tracing = False
        self._trace_started = False
        self._trace_round = trace_begin
        # the warn-once latch is touched from worker threads (serve,
        # checkpoint writer, prefetch) as well as the main thread
        self._warn_lock = threading.Lock()
        self._warned = set()

    @property
    def enabled(self) -> bool:
        return self.sink.enabled

    def emit(self, event: str, **fields: Any) -> None:
        if not self.sink.enabled:
            return
        record = {"event": event, "t": time.time()}
        record.update(fields)
        self.sink.write(record)

    def line(self, text: str) -> None:
        """Print a parity stdout line; record it when enabled."""
        print(text)
        if self.sink.enabled:
            self.emit("log", text=text)

    def warn_once(self, code: str, message: str) -> None:
        """Once-per-run structured warning; also surfaces on stderr so
        a silent fallback (e.g. distributed metric reduction failing)
        is visible even with monitor = none.

        NEVER raises: warn_once is called from fallback/cleanup paths
        that were infallible before they warned (shard autodetect, dir
        fsync on the checkpoint writer thread), and a dead sink must
        not turn a warning into a crash — or flip a successful async
        commit into a recorded failure."""
        with self._warn_lock:
            if code in self._warned:
                return
            self._warned.add(code)
        sys.stderr.write("[cxxnet_tpu monitor] warning %s: %s\n"
                         % (code, message))
        try:
            self.emit("warning", code=code, message=message)
        except Exception:
            pass  # cxxlint: disable=CXL006 -- the stderr line above already delivered the warning; a dead sink must not make warn_once raise

    # -- profiler trace window ------------------------------------------

    def maybe_start_trace(self, round_idx: int) -> None:
        """Start at the first observed round >= trace_begin (not only
        on exact equality: a resumed run may begin past the window,
        and a silent no-trace would be worse than a late one)."""
        if (not self.trace_dir or self._tracing
                or round_idx < self.trace_begin):
            return
        try:
            import jax
            jax.profiler.start_trace(self.trace_dir)
        except Exception as e:  # profiler backend is best-effort
            self.warn_once("trace_start_failed",
                           "jax.profiler.start_trace failed: %s" % e)
            return
        self._tracing = True
        self._trace_started = True
        self._trace_round = round_idx
        self.emit("trace_start", dir=self.trace_dir, round=round_idx)

    def maybe_stop_trace(self, round_idx: int,
                         force: bool = False) -> None:
        if not self._tracing:
            return
        if not force and round_idx < self.trace_end:
            self._trace_round = round_idx    # last round seen tracing
            return
        if force:
            # close-time stop (run ended inside the window): attribute
            # the stop to the last traced round, not the caller's 0
            round_idx = max(round_idx, self._trace_round)
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as e:
            # no trace was written: warn (and stop retrying), but do
            # NOT emit trace_stop — the stream must not claim a trace
            # that does not exist
            self._tracing = False
            self.warn_once("trace_stop_failed",
                           "jax.profiler.stop_trace failed: %s" % e)
            return
        self._tracing = False
        self.emit("trace_stop", dir=self.trace_dir, round=round_idx)

    def close(self) -> None:
        self.maybe_stop_trace(0, force=True)
        if self.trace_dir and not self._trace_started:
            # trace requested but the run never reached trace_begin —
            # say so instead of leaving an empty dir with no diagnostic
            self.warn_once(
                "trace_never_started",
                "monitor_trace_dir was set but no round >= "
                "monitor_trace_begin (%d) ran; no trace captured"
                % self.trace_begin)
        self.sink.close()


# -- construction --------------------------------------------------------


def config_hash(cfg) -> str:
    """Stable digest of the full ordered (name, value) config stream —
    ties every record stream back to the exact run configuration."""
    text = "\n".join("%s=%s" % (k, v) for k, v in cfg)
    return hashlib.sha1(text.encode()).hexdigest()[:12]


def create_monitor(cfg, root: Optional[bool] = None) -> Monitor:
    """Build a Monitor from ``key = value`` config pairs.

    Non-root processes always get a null sink (process-0 gating, the
    same rule main.py applies to prints) — pass ``root`` explicitly to
    override, e.g. in single-process library use before jax init.
    """
    mode = "none"
    path = "monitor.jsonl"
    flush_period = 1.0
    rotate_mb = 0.0
    trace_dir = ""
    trace_begin, trace_end = 1, None
    for name, val in cfg:
        if name == "monitor":
            if val not in ("none", "stdout", "jsonl"):
                raise ValueError(
                    "monitor must be none|stdout|jsonl, got %r" % val)
            mode = val
        if name == "monitor_path":
            path = val
        if name == "monitor_flush_period":
            flush_period = float(val)
        if name == "monitor_rotate_mb":
            rotate_mb = float(val)
        if name == "monitor_trace_dir":
            trace_dir = val
        if name == "monitor_trace_begin":
            trace_begin = int(val)
        if name == "monitor_trace_end":
            trace_end = int(val)
    if root is None:
        from ..parallel import is_root
        root = is_root()
    if not root:
        # process-0 gating: one run, one record stream, one trace —
        # non-root ranks must not race on the trace dir or duplicate
        # the close-time trace warnings
        mode = "none"
        trace_dir = ""
    if mode == "stdout":
        sink = StdoutSink()
    elif mode == "jsonl":
        sink = JsonlSink(path, flush_period, rotate_mb=rotate_mb)
    else:
        sink = NullSink()
    return Monitor(sink, trace_dir=trace_dir, trace_begin=trace_begin,
                   trace_end=trace_end)


def run_metadata(task: str, cfg, mesh=None) -> Dict[str, Any]:
    """Run-level metadata for the ``run_start`` record: mesh shape,
    process topology, backend and versions, config digest."""
    import platform as _platform

    import jax
    meta: Dict[str, Any] = {
        "task": task,
        "config_hash": config_hash(cfg),
        "jax_version": jax.__version__,
        "python_version": _platform.python_version(),
        "platform": jax.default_backend(),
        "process_count": jax.process_count(),
        "process_index": jax.process_index(),
        "device_count": len(jax.devices()),
        "device_kind": jax.devices()[0].device_kind,
        "mesh": dict(mesh.shape) if mesh is not None else None,
    }
    return meta


def device_memory_snapshot() -> Dict[str, Any]:
    """Per-device memory stats where the backend provides them
    (``Device.memory_stats()`` — TPU/GPU runtimes; CPU returns None).
    Host-side query only: no device computation, safe at round
    boundaries."""
    import jax
    devices = []
    available = False
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            available = True
            devices.append({
                "id": d.id,
                "kind": d.device_kind,
                "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                "peak_bytes_in_use": int(
                    stats.get("peak_bytes_in_use", 0)),
                "bytes_limit": int(stats.get("bytes_limit", 0)),
            })
        else:
            devices.append({"id": d.id, "kind": d.device_kind})
    return {"available": available, "devices": devices}


# -- global registry (the warn-once channel for deep call sites) ---------

class SafeEmitter:
    """Emit wrapper for worker-thread telemetry: a sink failure (full
    disk, closed file) must neither kill the emitting thread nor spam
    — the first failure prints ONE stderr line (latched under a lock:
    emitters run on several threads at once) and serving/training
    continues without records. The single implementation of the latch
    the serve batcher and fleet frontend both need."""

    def __init__(self, monitor, label: str):
        self._mon = monitor
        self._label = label
        self._lock = threading.Lock()
        self._broken = False

    def __call__(self, kind: str, **fields: Any) -> None:
        if self._mon is None or not self._mon.enabled:
            return
        try:
            self._mon.emit(kind, **fields)
        except Exception as e:
            with self._lock:
                already, self._broken = self._broken, True
            if not already:
                print("%s: telemetry emit failed (continuing without "
                      "records): %s" % (self._label, e),
                      file=sys.stderr)


_global_monitor: Optional[Monitor] = None
_fallback_warned: set = set()
_fallback_lock = threading.Lock()


def set_global(mon: Optional[Monitor]) -> None:
    """Install the run's monitor so deep call sites (utils/metric.py)
    can reach it without threading it through every signature."""
    global _global_monitor
    _global_monitor = mon


def get_global() -> Optional[Monitor]:
    return _global_monitor


def warn_once(code: str, message: str) -> None:
    """Module-level warn-once: routes through the installed monitor, or
    falls back to a bare once-per-process stderr line when no monitor
    is active (library callers outside the CLI)."""
    if _global_monitor is not None:
        _global_monitor.warn_once(code, message)
        return
    with _fallback_lock:
        if code in _fallback_warned:
            return
        _fallback_warned.add(code)
    sys.stderr.write("[cxxnet_tpu monitor] warning %s: %s\n"
                     % (code, message))

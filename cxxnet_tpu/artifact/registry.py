"""The program registry: ONE owner for every AOT executable.

Before this module, the (signature, bucket, mask-variant, dtype,
layout) key scheme and the lower+compile loop lived inside
``NetTrainer`` (``precompile`` / ``precompile_pred`` /
``_compile_programs``) and were *consumed* from four places — trainer
precompile, serve engine warmup, bench, and ``_call_pred`` — each
re-deriving dispatch signatures inline. The registry is the extraction
of that state into one object:

- **key scheme** — the module-level ``*_sig`` functions are the single
  definition of every dispatch signature. The trainer builds its
  precompile keys AND its per-dispatch lookup keys through them, so a
  scheme change cannot strand one call site on a stale scheme (the
  bug class PR 4's ``pred_sig`` unification closed for pred, now
  closed for update/update_many/run_steps too).
- **compile loop** — :meth:`ProgramRegistry.compile` is the one place
  ``(key, lower-thunk)`` pairs become executables: failure fallback,
  signature seeding and per-program compile telemetry cannot drift
  between the training and serving warmup paths.
- **serialization** — a compiled executable round-trips through
  ``jax.experimental.serialize_executable`` into the sealed artifact
  bundle (:mod:`cxxnet_tpu.artifact.bundle`), and
  :meth:`ProgramRegistry.install_serialized` loads them back at boot:
  a key satisfied from a bundle never re-lowers, and the per-key
  hit/rebuild accounting feeds the ``artifact_load`` telemetry record
  so the zero-compile cold-start claim is counted, not asserted.

Keys are tuples of primitives (strings, ints, bools, nested tuples):
``repr(key)`` is the bundle manifest's key encoding and
``ast.literal_eval`` recovers it exactly.
"""

from __future__ import annotations

import ast
import pickle
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple


class ResidencyBudgetError(RuntimeError):
    """Loading (or hot-swapping in) a model would exceed the explicit
    ``serve_device_mem_budget`` — the memory-honest alternative to
    discovering the overcommit as a device OOM mid-request. The load
    is rejected whole; whatever was serving keeps serving."""


class WeightResidency:
    """The device-resident serve weight tree and its accounting.

    One per model: the eval-transformed parameter tree every bucket
    executable of that model consumes as *arguments* (so N buckets
    share ONE device copy — the closure-constant alternative would
    bake the transformed weights into every executable). Built once at
    load/freeze by ``NetTrainer.freeze_serve_weights``:

    - ``bn_fold_eval`` weight folds applied once (no per-dispatch
      ``w * fold_scale`` pass),
    - int8/fp8 weights quantized once (no per-dispatch round/clip/cast
      of the weight tensor in the traced graph),
    - bf16 serve weights pre-cast (half the resident weight bytes),
    - per-channel dequant/shift epilogue vectors materialized as tree
      leaves instead of closure constants.

    ``tree_bytes`` is the footprint of the tree the executables see;
    ``total_bytes`` additionally counts the retained f32 masters,
    deduplicated by buffer identity (untransformed leaves alias the
    masters and are counted once) — the number budget enforcement and
    the ``weight_residency`` telemetry record report.
    """

    __slots__ = ("tree", "tree_bytes", "master_bytes", "total_bytes",
                 "quantize_ms", "layers", "dtype", "active")

    def __init__(self, tree, tree_bytes: int, master_bytes: int,
                 total_bytes: int, quantize_ms: float, layers: int,
                 dtype: str, active: bool):
        self.tree = tree
        self.tree_bytes = int(tree_bytes)
        self.master_bytes = int(master_bytes)
        self.total_bytes = int(total_bytes)
        self.quantize_ms = float(quantize_ms)
        self.layers = int(layers)
        self.dtype = dtype
        self.active = bool(active)

    def record(self) -> Dict[str, Any]:
        """The ``weight_residency`` telemetry record fields."""
        return {"bytes": self.total_bytes,
                "tree_bytes": self.tree_bytes,
                "master_bytes": self.master_bytes,
                "quantize_ms": self.quantize_ms,
                "layers": self.layers,
                "dtype": self.dtype,
                "active": self.active}

# -- the dispatch-signature scheme ----------------------------------------
#
# Every function returns the signature WITHOUT the leading kind tag;
# a full registry key is ("update",) + update_sig(...), etc. The
# trainer's per-dispatch lookups and its precompile key construction
# both call these — the single source the registry exists for.


def pred_sig(shape, dtype, mask_is_none: bool, n_extra: int,
             nodes_wanted) -> tuple:
    """The eval/pred forward signature: (batch shape, input dtype,
    mask variant, extra-input count, served node set)."""
    return (tuple(shape), str(dtype), bool(mask_is_none), int(n_extra),
            tuple(nodes_wanted))


def update_sig(data_shape, dtype, label_shape, mask_is_none: bool,
               n_extra: int, do_update: bool) -> tuple:
    """The per-batch train-step signature (static apply flag baked)."""
    return (tuple(data_shape), str(dtype), tuple(label_shape),
            bool(mask_is_none), int(n_extra), bool(do_update))


def update_many_sig(data_k_shape, dtype, labels_k_shape,
                    mask_is_none: bool, n_extra: int, window: int,
                    collect: bool) -> tuple:
    """The K-batch window signature (leading axis = scan step)."""
    return (tuple(data_k_shape), str(dtype), tuple(labels_k_shape),
            bool(mask_is_none), int(n_extra), int(window),
            bool(collect))


def run_steps_sig(data_shape, dtype, label_shape, mask_is_none: bool,
                  n_extra: int, n_steps: int) -> tuple:
    """The resident-batch scan signature (bench/test_skipread mode)."""
    return (tuple(data_shape), str(dtype), tuple(label_shape),
            bool(mask_is_none), int(n_extra), int(n_steps))


def search_sig(q_rows: int, dim: int, corpus_rows: int, k: int,
               metric: str, dtype) -> tuple:
    """The retrieval top-k signature: (query bucket, embedding dim,
    corpus rows, k, similarity metric, query dtype). The corpus matrix
    is a program *argument* (not a closure constant), so the executable
    serializes into the bundle and a generation's index swap reuses the
    same compiled program family."""
    return (int(q_rows), int(dim), int(corpus_rows), int(k),
            str(metric), str(dtype))


def parse_key(text: str) -> tuple:
    """Recover a registry key from its ``repr`` (the bundle manifest
    encoding). Keys are tuples of primitives, so ``literal_eval`` is
    exact; anything else raises ValueError."""
    key = ast.literal_eval(text)
    if not isinstance(key, tuple) or not key \
            or not isinstance(key[0], str):
        raise ValueError("not a registry key: %r" % text)
    return key


class ProgramRegistry:
    """Compiled-executable store keyed by (kind,) + signature.

    Owned by one trainer; the serve engine and bench consume it
    through the trainer. ``seen`` is the compile-event detection set
    (a dispatch whose key is not in ``seen`` paid a compile) — it
    deliberately survives :meth:`reset` the way the trainer's
    signature set always did, so a program rebuild does not erase the
    run's compile accounting.
    """

    def __init__(self):
        self.aot: Dict[tuple, Any] = {}
        self.seen: set = set()
        # sealed-artifact accounting (install_serialized)
        self.bundle_path = ""
        self.fingerprint_match = True
        self.art_hits = 0
        self.art_rebuilds = 0
        # keys whose executable was DESERIALIZED from a bundle: a
        # Loaded executable does not re-serialize faithfully (the
        # payload comes back without its compiled symbols), so
        # re-export must copy these keys' original blobs from the
        # source bundle instead of serializing the live object
        self.installed: set = set()
        # the device-resident serve weight tree (None until the owning
        # trainer freezes its serve weights); every pred executable of
        # this registry consumes it as arguments, so the tree is shared
        # across the whole bucket ladder
        self.residency: Optional[WeightResidency] = None

    # -- lookup ----------------------------------------------------------

    def get(self, key: tuple):
        """The executable for ``key``, or None (jit fallback)."""
        return self.aot.get(key)

    def __contains__(self, key: tuple) -> bool:
        return key in self.aot

    def __len__(self) -> int:
        return len(self.aot)

    def reset(self) -> None:
        """Orphan every executable (a program rebuild: new graph, new
        shardings). Bundle-installed programs go too — they were
        compiled against the replaced graph."""
        self.aot = {}
        self.bundle_path = ""
        self.fingerprint_match = True
        self.art_hits = 0
        self.art_rebuilds = 0
        self.installed = set()
        self.residency = None            # tree built for the old graph

    def install_weights(self, residency: WeightResidency,
                        budget_bytes: int = 0) -> WeightResidency:
        """Adopt a frozen serve weight tree, enforcing the explicit
        device-memory budget (0 = unlimited). Raises
        :class:`ResidencyBudgetError` — a typed rejection, not an OOM —
        when the model's resident bytes exceed the budget; nothing is
        installed in that case."""
        if budget_bytes and residency.total_bytes > budget_bytes:
            raise ResidencyBudgetError(
                "model weight tree needs %d resident bytes but "
                "serve_device_mem_budget allows %d"
                % (residency.total_bytes, budget_bytes))
        self.residency = residency
        return residency

    # -- the one compile loop --------------------------------------------

    def compile(self, programs: Sequence[Tuple[tuple, Callable]],
                warn_code: str, monitor=None) -> int:
        """AOT-compile ``(key, lower-thunk)`` pairs, skipping keys
        already present (including keys a bundle install satisfied —
        that skip IS the near-zero cold start). A failed compile warns
        once and leaves that key on the jit fallback path; per-program
        telemetry rides on ``monitor`` when one is attached. Returns
        the number of programs newly compiled."""
        compiled = 0
        for key, thunk in programs:
            if key in self.aot:
                continue
            try:
                import warnings
                t0 = time.perf_counter()
                with warnings.catch_warnings():
                    # donated pred buffers that XLA cannot alias into
                    # the (differently shaped) outputs warn per
                    # compile; donation is best-effort by design
                    warnings.filterwarnings(
                        "ignore", message=".*[Dd]onat")
                    self.aot[key] = thunk().compile()
            except Exception as e:
                from ..monitor import warn_once
                warn_once(warn_code,
                          "precompile of %r failed (falling back to "
                          "jit): %s" % (key[0], e))
                continue
            compiled += 1
            # seed the signature set: the run's first dispatch of this
            # signature is NOT a compile — it happened here, and the
            # stream records it with its own wall time
            self.seen.add(key)
            if monitor is not None and monitor.enabled:
                monitor.emit("compile", kind="precompile",
                             wall_ms=(time.perf_counter() - t0) * 1e3,
                             signature=repr(key))
        return compiled

    # -- sealed-artifact serialization -----------------------------------

    def serialize_programs(self, monitor=None
                           ) -> List[Tuple[tuple, bytes]]:
        """Serialize every freshly COMPILED executable into portable
        blobs (``jax.experimental.serialize_executable`` payload +
        arg pytrees, pickled together), round-trip-checked: each blob
        is
        deserialized once right here, because a blob that only fails
        at boot would silently degrade zero-compile to
        rebuild-everything (observed with re-serialized *Loaded*
        executables: the payload comes back without its compiled
        symbols). Keys in ``installed`` are excluded — the exporter
        copies their original bundle blobs byte-for-byte instead.
        Unserializable executables are skipped with one warning — a
        bundle with fewer programs still boots, it just re-lowers the
        missing keys."""
        from jax.experimental import serialize_executable as se
        out: List[Tuple[tuple, bytes]] = []
        for key in sorted(self.aot, key=repr):
            if key in self.installed:
                continue
            try:
                payload, in_tree, out_tree = se.serialize(self.aot[key])
                blob = pickle.dumps((payload, in_tree, out_tree),
                                    protocol=pickle.HIGHEST_PROTOCOL)
                se.deserialize_and_load(*pickle.loads(blob))
            except Exception as e:
                _warn(monitor, "artifact_serialize_failed",
                      "executable %r does not serialize round-trip "
                      "(%s); the bundle ships without it and boot "
                      "re-lowers that key" % (key[0], e))
                continue
            out.append((key, blob))
        return out

    def install_serialized(self, programs: Sequence[Tuple[tuple, bytes]],
                           path: str, fingerprint_ok: bool,
                           monitor=None) -> Dict[str, Any]:
        """Deserialize bundle executables into the store.

        With a matching runtime fingerprint every loadable program
        becomes a resident executable (a *hit*: that key will never
        lower or compile this boot). A mismatched fingerprint installs
        NOTHING — one warning, and every key re-lowers on demand (a
        *rebuild*). Per-blob deserialization failures also fall back
        per-key. Returns the ``artifact_load`` record fields; honesty
        rule: ``hits + rebuilds == len(programs)``, always.
        """
        t0 = time.perf_counter()
        hits = rebuilds = 0
        self.bundle_path = path
        self.fingerprint_match = bool(fingerprint_ok)
        if not fingerprint_ok:
            rebuilds = len(programs)
            _warn(monitor, "artifact_fingerprint_mismatch",
                  "artifact bundle %s was sealed on a different "
                  "platform/jaxlib/topology; its %d executable(s) are "
                  "unusable here — every program re-lowers and "
                  "recompiles (results are unaffected)"
                  % (path, len(programs)))
        else:
            from jax.experimental import serialize_executable as se
            for key, blob in programs:
                try:
                    payload, in_tree, out_tree = pickle.loads(blob)
                    exe = se.deserialize_and_load(payload, in_tree,
                                                  out_tree)
                except Exception as e:
                    rebuilds += 1
                    _warn(monitor, "artifact_deserialize_failed",
                          "bundle executable %r failed to load (%s); "
                          "that key re-lowers and recompiles"
                          % (key[0], e))
                    continue
                self.aot[key] = exe
                # a bundle-installed program is not a compile event:
                # the first dispatch of this signature runs a sealed
                # executable
                self.seen.add(key)
                self.installed.add(key)
                hits += 1
        self.art_hits, self.art_rebuilds = hits, rebuilds
        return {"path": path,
                "fingerprint_match": bool(fingerprint_ok),
                "hits": hits, "rebuilds": rebuilds,
                "wall_ms": (time.perf_counter() - t0) * 1e3}


def _warn(monitor, code: str, message: str) -> None:
    if monitor is not None:
        monitor.warn_once(code, message)
    else:
        from ..monitor import warn_once
        warn_once(code, message)

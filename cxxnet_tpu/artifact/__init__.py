"""Sealed model artifacts: program registry + export/boot bundles.

Two halves (doc/artifacts.md):

- :mod:`~cxxnet_tpu.artifact.registry` — the :class:`ProgramRegistry`
  every AOT executable in the system lives in, plus the single-sourced
  dispatch-signature scheme (``pred_sig`` / ``update_sig`` /
  ``update_many_sig`` / ``run_steps_sig``). The trainer owns one;
  serve/bench/pred consume it through the trainer.
- :mod:`~cxxnet_tpu.artifact.bundle` — the sealed on-disk artifact:
  verified snapshot + serialized executables + fingerprint + schema'd
  manifest, committed two-phase. ``task = export`` writes one;
  ``serve`` / ``serve_fleet`` / ``pred`` boot from one with near-zero
  cold start when the fingerprint matches.

The bundle module is imported lazily by consumers (it pulls in the
checkpoint subsystem); import it explicitly as
``from cxxnet_tpu.artifact import bundle``.
"""

from .registry import (ProgramRegistry, parse_key, pred_sig,
                       run_steps_sig, update_many_sig, update_sig)

__all__ = [
    "ProgramRegistry", "parse_key", "pred_sig", "run_steps_sig",
    "update_many_sig", "update_sig",
]

"""Sealed model artifacts: snapshot + serialized executables, verified.

A *bundle* is the deployable unit ``task = export`` writes and a serve
replica boots from (doc/artifacts.md): one directory holding

- ``snapshot.model.npz`` — a verified snapshot (the PR 5 digest
  machinery, quant/ range arrays included), re-committed under the
  bundle so the bundle is self-contained;
- ``prog-NNNN.pkl`` — one serialized compiled executable per program
  registry key (``jax.experimental.serialize_executable`` payload +
  arg pytrees, pickled), keyed in the manifest by the key's ``repr``;
- ``MANIFEST.json`` — the schema'd manifest: format version, runtime
  fingerprint (platform / jax / jaxlib / device kind+count / mesh),
  the bucket ladder and serve dtype the executables were sealed for,
  and a (name, bytes, sha256) row for EVERY member;
- ``MANIFEST.json.ok`` — the commit marker (manifest bytes +
  file_sha256), written LAST: the existing two-phase protocol. A
  bundle without its ``.ok`` is uncommitted — invisible to the
  hot-swap watcher and reported (not failed) by a model_dir scan,
  exactly like an uncommitted remote snapshot payload.

Everything goes through the stream layer, so bundles work on local
paths, remote URIs, and the ``fault://`` fault-injection scheme the
integrity tests drive.

Naming convention: exporting ``NNNN.model.npz`` defaults to
``NNNN.model.bundle`` beside it, so a watched ``model_dir`` can carry
bundles and snapshots side by side and the watcher prefers the bundle
at equal counters (a bundle flip skips the shadow-build compile time).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import time
from typing import Any, Dict, List, Tuple

from ..utils.stream import (list_stream_dir, local_path, open_stream,
                            read_stream_bytes, remove_stream,
                            stream_exists, uri_scheme)
from .registry import parse_key

BUNDLE_FORMAT_VERSION = 1
BUNDLE_KIND = "cxxnet_artifact_bundle"
MANIFEST_NAME = "MANIFEST.json"
OK_SUFFIX = ".ok"
SNAPSHOT_MEMBER = "snapshot.model.npz"

BUNDLE_RE = re.compile(r"^(\d{4})\.model\.bundle$")
_PROG_RE = re.compile(r"^prog-\d{4}\.pkl$")

_MANIFEST_REQUIRED = ("format_version", "kind", "fingerprint",
                      "buckets", "serve_dtype", "snapshot", "members",
                      "programs")


class BundleError(IOError):
    """Bundle is unreadable, uncommitted, tampered, or malformed."""


def member_uri(bundle: str, name: str) -> str:
    """URI of one member inside a bundle directory — the same join
    convention as snapshot paths (``checkpoint.snapshot_uri``),
    delegated so the two can never drift."""
    from ..nnet.checkpoint import snapshot_uri
    return snapshot_uri(bundle, name)


def _commit_member(uri: str, data: bytes) -> None:
    """Durably write one bundle member. Local paths take the snapshot
    writer's discipline (tmp-write + fsync + rename) so a power loss
    after the ``.ok`` marker lands can never expose committed-but-torn
    member bytes; remote schemes write through the stream layer (their
    durability is the store's PUT semantics, as with snapshots)."""
    if uri_scheme(uri):
        with open_stream(uri, "wb") as f:
            f.write(data)
        return
    p = local_path(uri)
    d = os.path.dirname(p)
    if d and not os.path.isdir(d):
        os.makedirs(d, exist_ok=True)
    tmp = p + ".tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass  # cxxlint: disable=CXL006 -- best-effort tmp cleanup; the write failure below is what the caller must see
        raise


def _fsync_dir(bundle: str) -> None:
    """Make the bundle directory's entries durable before (and after)
    the commit marker — the dir-fsync half of the two-phase protocol;
    refusal warns once, exactly like the snapshot writer."""
    if uri_scheme(bundle):
        return
    d = local_path(bundle)
    try:
        dfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError as e:
        from ..monitor import warn_once
        warn_once("dir_fsync_refused",
                  "directory fsync of %r failed (%s); the bundle "
                  "commit is not guaranteed durable across power "
                  "loss on this filesystem" % (d, e))


def is_bundle(path: str) -> bool:
    """True when ``path`` is a bundle directory (committed or not):
    the dispatch test ``model_in`` consumers use to tell a bundle from
    a snapshot file."""
    if not uri_scheme(path) and not os.path.isdir(local_path(path)):
        return False
    return stream_exists(member_uri(path, MANIFEST_NAME))


def default_bundle_path(model_in: str) -> str:
    """`NNNN.model.npz` -> `NNNN.model.bundle` beside it; a bundle
    ``model_in`` re-exports IN PLACE (appending another ``.bundle``
    would produce a name the watcher's ``BUNDLE_RE`` never matches —
    an export that 'succeeds' but deploys nothing); any other name
    gets ``.bundle`` appended after stripping ``.npz``."""
    if model_in.rstrip("/").endswith(".bundle"):
        return model_in.rstrip("/")
    if model_in.endswith(".model.npz"):
        return model_in[:-len(".npz")] + ".bundle"
    return re.sub(r"\.npz$", "", model_in) + ".bundle"


# -- fingerprint ----------------------------------------------------------


def runtime_fingerprint(mesh=None) -> Dict[str, Any]:
    """What a serialized executable is only valid against: backend
    platform, jax/jaxlib versions, device kind and count, process
    count, and (when known) the mesh axis sizes the programs were
    lowered over. Compared by plain dict equality — a bundle either
    matches this runtime exactly or every program rebuilds."""
    import jax
    import jaxlib
    devs = jax.devices()
    # process_count + device_count + the mesh entry below ARE the
    # physical host-topology seal (local devices per host is exactly
    # device_count / process_count): a different world size or mesh
    # shape fails the dict-equality gate and every program rebuilds
    # with one warning. The dryrun's FAKED host count is deliberately
    # absent — the SPMD programs are identical at any faked input
    # partition, so an elastic dryrun resize keeps its zero-compile
    # bundle boot (doc/distributed.md) — and no redundant key means
    # bundles sealed before this convention was written down stay
    # valid
    fp = {
        "platform": jax.default_backend(),
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "device_kind": devs[0].device_kind,
        "device_count": len(devs),
        "process_count": jax.process_count(),
    }
    if mesh is not None:
        fp["mesh"] = {str(k): int(v) for k, v in dict(mesh.shape).items()}
    return fp


def fingerprint_sha(fp: Dict[str, Any]) -> str:
    """Stable short hash of a runtime fingerprint dict — the identity
    operators and the canary comparator use to tell which runtime an
    engine's executables were built for (``/v1/models``,
    doc/serving.md "Horizontal fleet"). Sorted-key JSON so dict order
    never changes the hash."""
    blob = json.dumps(fp, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


# -- export ---------------------------------------------------------------


def export_bundle(engine, out: str, node: str = "",
                  monitor=None, retrieval=None) -> Dict[str, Any]:
    """Seal a warmed engine into a committed bundle at ``out``.

    ``engine`` is a warmed :class:`~cxxnet_tpu.serve.engine.
    InferenceEngine`: its trainer holds the verified weights and its
    program registry holds the compiled bucket-ladder executables.
    ``retrieval`` (a warmed :class:`~cxxnet_tpu.retrieval.engine.
    RetrievalEngine`, or None) additionally seals its embedding index
    as a digest-verified member beside the snapshot — model and index
    then commit, verify, and hot-swap as ONE artifact, and the search
    executables (which live in the same program registry) serialize
    with the pred ladder.
    Write order is the commit protocol: members first (each durably
    committed — local tmp+fsync+rename, see :func:`_commit_member`),
    manifest second, a directory fsync, then ``MANIFEST.json.ok``
    last — and any stale ``.ok`` (plus orphan program members) from a
    previous export at the same path is dropped FIRST, so a crash at
    any point — power loss included — leaves an *uncommitted* bundle,
    never a committed-but-torn one. Returns the ``export`` telemetry
    record fields."""
    from ..monitor import config_hash
    from ..nnet.checkpoint import _serialize
    t0 = time.perf_counter()
    trainer = engine.trainer
    # bundle-installed executables cannot be re-serialized faithfully
    # (a Loaded object's payload comes back without its compiled
    # symbols) — copy their ORIGINAL blobs from the source bundle,
    # read BEFORE anything below overwrites it (in-place re-export is
    # the default for a bundle model_in)
    passthrough = _source_blobs(trainer.programs, monitor)
    ok_uri = member_uri(out, MANIFEST_NAME + OK_SUFFIX)
    if stream_exists(ok_uri) and not remove_stream(ok_uri):
        # a marker we cannot drop means the commit protocol cannot
        # hold: a crash mid-re-export would leave old-manifest-vouched
        # torn members. Refuse rather than proceed unsafely.
        raise BundleError(
            "cannot drop the stale commit marker %s; refusing to "
            "re-export over a committed bundle" % ok_uri)
    # sweep program members of any previous export at this path: a
    # re-export with fewer programs must not leave orphan executables
    # the new manifest no longer vouches for. The index member sweeps
    # for the same reason — an index-less re-export must not leave an
    # orphan corpus the new manifest never mentions
    from ..retrieval.index import INDEX_MEMBER
    for name in list_stream_dir(out):
        if _PROG_RE.match(name) or name == INDEX_MEMBER:
            remove_stream(member_uri(out, name))
    arrays, meta = trainer.gather_snapshot()
    # serialize once and keep the bytes: the members row needs their
    # sha256, and a multi-GB snapshot must not be re-downloaded right
    # after upload just to hash it
    payload, digest = _serialize(arrays, meta)
    snap_stats = {"digest": digest}
    _commit_member(member_uri(out, SNAPSHOT_MEMBER), payload)
    members: List[Dict[str, Any]] = [{
        "name": SNAPSHOT_MEMBER, "bytes": len(payload),
        "sha256": hashlib.sha256(payload).hexdigest(),
    }]
    programs: List[Dict[str, str]] = []
    total = len(payload)
    blobs = passthrough \
        + trainer.programs.serialize_programs(monitor=monitor)
    for i, (key, blob) in enumerate(sorted(blobs, key=lambda e:
                                           repr(e[0]))):
        name = "prog-%04d.pkl" % i
        _commit_member(member_uri(out, name), blob)
        members.append({"name": name, "bytes": len(blob),
                        "sha256": hashlib.sha256(blob).hexdigest()})
        programs.append({"name": name, "key": repr(key)})
        total += len(blob)
    index_entry = None
    if retrieval is not None:
        idx_blob = retrieval.index.serialize()
        index_entry = retrieval.index.manifest_entry()
        # the served search contract: result depth + query-bucket
        # ladder, so a boot requests exactly the sealed search keys
        index_entry.update({"k": int(retrieval.k),
                            "buckets": [int(b)
                                        for b in retrieval.buckets]})
        _commit_member(member_uri(out, index_entry["member"]), idx_blob)
        members.append({
            "name": index_entry["member"], "bytes": len(idx_blob),
            "sha256": hashlib.sha256(idx_blob).hexdigest()})
        total += len(idx_blob)
    manifest = {
        "format_version": BUNDLE_FORMAT_VERSION,
        "kind": BUNDLE_KIND,
        "fingerprint": runtime_fingerprint(trainer.mesh),
        "buckets": [int(b) for b in engine.buckets],
        "nodes": [int(n) for n in engine.nodes],
        "node": node,
        "serve_dtype": trainer.serve_dtype,
        "input_dtype": str(engine.input_dtype),
        # the sealed executables' weight calling convention: 1 = pred
        # takes the frozen device-resident serve tree as arguments
        # (trainer.freeze_serve_weights), 0 = the raw master tree. A
        # boot whose trainer uses the other convention re-lowers per
        # key instead of calling with the wrong pytree
        "weight_residency": int(bool(trainer.serve_weight_residency)),
        "config_hash": config_hash(trainer.cfg),
        "content_digest": snap_stats["digest"],
        "snapshot": SNAPSHOT_MEMBER,
        "members": members,
        "programs": programs,
    }
    if index_entry is not None:
        manifest["index"] = index_entry
    man_bytes = json.dumps(manifest, sort_keys=True,
                           indent=1).encode()
    _commit_member(member_uri(out, MANIFEST_NAME), man_bytes)
    # every member durable BEFORE the marker vouches for them, and
    # the marker's own rename durable after — the .ok must never be
    # the only bytes a power loss preserved
    _fsync_dir(out)
    marker = {"format_version": BUNDLE_FORMAT_VERSION,
              "bytes": len(man_bytes),
              "file_sha256": hashlib.sha256(man_bytes).hexdigest()}
    _commit_member(ok_uri, json.dumps(marker).encode())
    _fsync_dir(out)
    return {
        "out": out,
        "snapshot": snap_stats["digest"],
        "programs": len(programs),
        # manifest member rows, the same count verify_bundle reports
        "members": len(members),
        "bytes": total + len(man_bytes),
        "wall_ms": (time.perf_counter() - t0) * 1e3,
    }


def _source_blobs(registry, monitor) -> List[Tuple[tuple, bytes]]:
    """Original serialized blobs for the registry's bundle-installed
    keys, read back from the bundle they were loaded from. A source
    that has since vanished (or lost members) warns and ships
    without those keys — the re-exported bundle still boots, those
    keys just re-lower."""
    if not registry.installed or not registry.bundle_path:
        return []
    out: List[Tuple[tuple, bytes]] = []
    try:
        man = bundle_manifest(registry.bundle_path)
        name_by_key = {p["key"]: p["name"] for p in man["programs"]}
        for key in sorted(registry.installed, key=repr):
            name = name_by_key.get(repr(key))
            if name is None:
                continue
            out.append((key, read_stream_bytes(
                member_uri(registry.bundle_path, name))))
    except (BundleError, IOError, OSError) as e:
        from .registry import _warn
        _warn(monitor, "artifact_source_unreadable",
              "source bundle %s is no longer readable (%s); re-export "
              "ships without its %d installed program(s)"
              % (registry.bundle_path, e, len(registry.installed)))
        return []
    return out


# -- verify ---------------------------------------------------------------


def verify_bundle(path: str) -> Dict[str, Any]:
    """Offline integrity report for one bundle (the
    ``tools/ckpt_verify.py`` core for bundles): commit marker, manifest
    bytes + sha, manifest schema, every member's size + sha256, and
    the snapshot's own content digest. ``ok`` is True only when every
    check passes; the first failure names itself in ``error``."""
    rep: Dict[str, Any] = {"path": path, "ok": False, "error": "",
                           "members": 0, "programs": 0,
                           "format_version": 0, "committed": False}
    rep["committed"] = stream_exists(
        member_uri(path, MANIFEST_NAME + OK_SUFFIX))
    try:
        manifest, _ = _read_manifest(path)
    except BundleError as e:
        # report-don't-raise contract: every malformation — including
        # tampered-but-parseable JSON of the wrong shape — comes back
        # as a verdict, never an exception escaping into ckpt_verify
        # or the watcher's scan
        rep["error"] = str(e)
        return rep
    rep["format_version"] = int(manifest["format_version"])
    rep["programs"] = len(manifest["programs"])
    for m in manifest["members"]:
        rep["members"] += 1
        uri = member_uri(path, m["name"])
        try:
            data = read_stream_bytes(uri)
        except (IOError, OSError) as e:
            rep["error"] = "member %s unreadable: %s" % (m["name"], e)
            return rep
        if len(data) != m.get("bytes"):
            rep["error"] = ("member %s size mismatch: manifest says "
                            "%s bytes, found %d"
                            % (m["name"], m.get("bytes"), len(data)))
            return rep
        if hashlib.sha256(data).hexdigest() != m.get("sha256"):
            rep["error"] = "member %s fails its sha256" % m["name"]
            return rep
    from ..nnet.checkpoint import verify_snapshot
    snap_rep = verify_snapshot(member_uri(path, manifest["snapshot"]))
    if not snap_rep["ok"]:
        rep["error"] = "snapshot member: %s" % snap_rep["error"]
        return rep
    rep["ok"] = True
    return rep


def _manifest_malformed(manifest) -> str:
    """Structural validation of a parsed manifest: the report-don't-
    raise contract means tampered-but-parseable JSON of any shape
    must produce a verdict string, never an attribute/key error. ""
    when well-formed."""
    if not isinstance(manifest, dict):
        return "manifest is not a JSON object"
    if manifest.get("kind") != BUNDLE_KIND:
        return "not a %s manifest" % BUNDLE_KIND
    missing = [k for k in _MANIFEST_REQUIRED if k not in manifest]
    if missing:
        return ("manifest missing required field(s): %s"
                % ", ".join(missing))
    if not isinstance(manifest["format_version"], int):
        return "manifest format_version is not an integer"
    if not isinstance(manifest["snapshot"], str):
        return "manifest snapshot field is not a member name"
    if not isinstance(manifest["fingerprint"], dict):
        return "manifest fingerprint is not an object"
    if not isinstance(manifest["serve_dtype"], str):
        return "manifest serve_dtype is not a string"
    # the serve contract consumers compute over (max(), join, ladder
    # parse) — a malformed shape must be a verdict here, not a bare
    # ValueError escaping from build_engine/serve_cfg_from_bundle
    buckets = manifest["buckets"]
    if not isinstance(buckets, list) or not buckets \
            or any(not isinstance(b, int) or b < 1 for b in buckets):
        return "manifest buckets is not a non-empty list of positive " \
               "ints"
    # per-field types, not a loose (str, int) union: an int member
    # NAME would sail through here and then TypeError inside
    # os.path.join — an exception escaping the report-don't-raise
    # contract
    for field, keys in (("members", (("name", str), ("bytes", int),
                                     ("sha256", str))),
                        ("programs", (("name", str), ("key", str)))):
        rows = manifest[field]
        if not isinstance(rows, list):
            return "manifest %s is not a list" % field
        for m in rows:
            if not isinstance(m, dict) \
                    or any(not isinstance(m.get(k), t)
                           for k, t in keys):
                return "manifest %s row is malformed: %r" % (field, m)
    # cross-field: everything the bundle claims to contain must be
    # digest-covered by a members row — a snapshot or program outside
    # the members list would verify OK and then fail to load
    names = {m["name"] for m in manifest["members"]}
    if manifest["snapshot"] not in names:
        return ("manifest snapshot %r has no members row"
                % manifest["snapshot"])
    for p in manifest["programs"]:
        if p["name"] not in names:
            return "manifest program %r has no members row" % p["name"]
    # a sealed index is optional; when declared it must be a shaped
    # object AND digest-covered by a members row — an index outside
    # the members list would verify OK here and then boot a server
    # whose /v1/search has no (or torn) corpus bytes
    idx = manifest.get("index")
    if idx is not None:
        if not isinstance(idx, dict):
            return "manifest index is not an object"
        for k, t in (("member", str), ("metric", str), ("node", str),
                     ("rows", int), ("dim", int), ("k", int)):
            if not isinstance(idx.get(k), t):
                return "manifest index field %r is malformed" % k
        ibuckets = idx.get("buckets")
        if not isinstance(ibuckets, list) or not ibuckets \
                or any(not isinstance(b, int) or b < 1
                       for b in ibuckets):
            return "manifest index buckets is not a non-empty list " \
                   "of positive ints"
        if idx["member"] not in names:
            return ("manifest index member %r has no members row"
                    % idx["member"])
    return ""


def _read_manifest(path: str) -> Tuple[Dict[str, Any], bytes]:
    """The ONE committed-manifest reader behind ``bundle_manifest``,
    ``verify_bundle`` and ``load_bundle``: commit-marker existence,
    marker shape, manifest bytes + sha cross-check, structural
    validation, format gate. Raises :class:`BundleError`; returns
    (manifest, manifest bytes)."""
    man_uri = member_uri(path, MANIFEST_NAME)
    ok_uri = man_uri + OK_SUFFIX
    if not stream_exists(ok_uri):
        raise BundleError("uncommitted bundle %s (no %s%s commit "
                          "marker)" % (path, MANIFEST_NAME, OK_SUFFIX))
    try:
        marker = json.loads(read_stream_bytes(ok_uri).decode())
        man_bytes = read_stream_bytes(man_uri)
    except (IOError, OSError, ValueError) as e:
        raise BundleError("bundle %s manifest/commit marker "
                          "unreadable: %s" % (path, e)) from e
    if not isinstance(marker, dict):
        raise BundleError("bundle %s commit marker is not a JSON "
                          "object" % path)
    if marker.get("bytes") != len(man_bytes):
        raise BundleError(
            "bundle %s manifest size mismatch: committed %s bytes, "
            "found %d" % (path, marker.get("bytes"), len(man_bytes)))
    # file_sha256 is REQUIRED: export always writes it, and accepting
    # its absence would let a consistently rewritten marker+manifest
    # pass full verification
    if marker.get("file_sha256") \
            != hashlib.sha256(man_bytes).hexdigest():
        raise BundleError("bundle %s manifest file_sha256 missing or "
                          "mismatched" % path)
    try:
        manifest = json.loads(man_bytes.decode())
    except ValueError as e:
        raise BundleError("bundle %s manifest unparseable: %s"
                          % (path, e)) from e
    err = _manifest_malformed(manifest)
    if err:
        raise BundleError("bundle %s: %s" % (path, err))
    if int(manifest["format_version"]) > BUNDLE_FORMAT_VERSION:
        raise BundleError(
            "bundle %s format_version %d is newer than this build "
            "reads (<= %d); upgrade cxxnet_tpu or re-export"
            % (path, manifest["format_version"],
               BUNDLE_FORMAT_VERSION))
    return manifest, man_bytes


# -- load -----------------------------------------------------------------


class Bundle:
    """A verified, parsed bundle ready to attach to a trainer.

    ``snapshot_raw`` carries the inner snapshot's bytes from the
    verification pass so ``load_model`` never re-reads them;
    ``programs`` holds the (already digest-checked) serialized blobs —
    deserialization into live executables is the registry's job
    (:meth:`ProgramRegistry.install_serialized`), so a fingerprint-
    mismatched boot never pays the pickle cost."""

    __slots__ = ("path", "manifest", "snapshot_uri", "snapshot_raw",
                 "programs")

    def __init__(self, path: str, manifest: Dict[str, Any],
                 snapshot_raw: bytes,
                 programs: List[Tuple[tuple, bytes]]):
        self.path = path
        self.manifest = manifest
        self.snapshot_uri = member_uri(path, manifest["snapshot"])
        self.snapshot_raw = snapshot_raw
        self.programs = programs


def bundle_manifest(path: str) -> Dict[str, Any]:
    """Parse a bundle's COMMITTED manifest (marker cross-checked,
    structure validated) WITHOUT the per-member verification — the
    cheap read config derivation uses; loading for real goes through
    :func:`load_bundle`. Raises BundleError on an uncommitted /
    unreadable / malformed manifest."""
    return _read_manifest(path)[0]


def load_bundle(path: str) -> Bundle:
    """Verify and load a bundle in ONE pass over its members: commit
    marker, manifest sha, then each member read exactly once — its
    size + sha256 checked, the snapshot's bytes and the program blobs
    kept (boot verification requires reading every member anyway; the
    inner snapshot's content digest is re-verified from the kept
    bytes by ``read_snapshot`` at load). Raises :class:`BundleError`
    on any integrity failure."""
    manifest, _ = _read_manifest(path)
    blobs: Dict[str, bytes] = {}
    for m in manifest["members"]:
        uri = member_uri(path, m["name"])
        try:
            data = read_stream_bytes(uri)
        except (IOError, OSError) as e:
            raise BundleError("bundle %s member %s unreadable: %s"
                              % (path, m["name"], e)) from e
        if len(data) != m["bytes"] \
                or hashlib.sha256(data).hexdigest() != m["sha256"]:
            raise BundleError(
                "bundle %s member %s fails verification (size/sha256 "
                "mismatch)" % (path, m["name"]))
        blobs[m["name"]] = data
    # snapshot/program membership is guaranteed by _manifest_malformed
    programs: List[Tuple[tuple, bytes]] = []
    for p in manifest["programs"]:
        try:
            key = parse_key(p["key"])
        except (ValueError, SyntaxError) as e:
            raise BundleError(
                "bundle %s program key %r is unparseable: %s"
                % (path, p.get("key"), e)) from e
        programs.append((key, blobs[p["name"]]))
    return Bundle(path, manifest, blobs[manifest["snapshot"]],
                  programs)


def read_index_member(path: str, manifest: Dict[str, Any] = None
                      ) -> bytes:
    """Digest-verified bytes of a bundle's sealed embedding index, or
    ``b""`` when the bundle seals no index. Size and sha256 are checked
    against the members row (the membership itself is guaranteed by
    ``_manifest_malformed``); a missing or torn member raises
    :class:`BundleError` — the boot-time mirror of the verify path, so
    a server can never come up on corpus bytes the manifest does not
    vouch for."""
    man = bundle_manifest(path) if manifest is None else manifest
    idx = man.get("index")
    if idx is None:
        return b""
    row = next(m for m in man["members"] if m["name"] == idx["member"])
    try:
        data = read_stream_bytes(member_uri(path, idx["member"]))
    except (IOError, OSError) as e:
        raise BundleError("bundle %s index member %s unreadable: %s"
                          % (path, idx["member"], e)) from e
    if len(data) != row["bytes"] \
            or hashlib.sha256(data).hexdigest() != row["sha256"]:
        raise BundleError(
            "bundle %s index member %s fails verification (size/"
            "sha256 mismatch)" % (path, idx["member"]))
    return data


def serve_cfg_from_bundle(path: str) -> List[Tuple[str, str]]:
    """Config pairs a conf-less boot (``serve_bench --artifact``)
    derives from the manifest: the sealed bucket ladder, serve dtype
    and node. Appended FIRST so an explicit config still wins."""
    man = bundle_manifest(path)
    pairs = [
        ("serve_buckets", ",".join(str(b) for b in man["buckets"])),
        ("serve_max_batch", str(max(man["buckets"]))),
        ("serve_dtype", man["serve_dtype"]),
    ]
    if "weight_residency" in man:
        pairs.append(("serve_weight_residency",
                      str(int(man["weight_residency"]))))
    if man.get("node"):
        pairs.append(("serve_node", man["node"]))
    return pairs


# -- model_dir scan -------------------------------------------------------


def scan_bundles(model_dir: str) -> List[Tuple[int, str]]:
    """Committed bundle candidates in ``model_dir`` as (counter,
    basename), newest first — the bundle analogue of
    ``checkpoint.scan_snapshots``. Uncommitted bundles (no ``.ok``)
    are skipped: the export may still be writing them."""
    out = []
    for n in list_stream_dir(model_dir):
        m = BUNDLE_RE.match(n)
        if not m:
            continue
        b = member_uri(model_dir, n)
        if not stream_exists(member_uri(b, MANIFEST_NAME + OK_SUFFIX)):
            continue                     # uncommitted
        out.append((int(m.group(1)), n))
    out.sort(reverse=True)
    return out

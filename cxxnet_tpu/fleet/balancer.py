"""Front-of-fleet balancer: one address over N shared-nothing replicas.

The :class:`FleetBalancer` speaks both existing protocols (HTTP/JSON
and the CXN1 binary frames — the frame grammar and status vocabulary
are imported from ``serve/frontend.py``, so every existing client
works unchanged) and routes each request to a replica process:

- **load-aware health routing** — a poller thread reads every
  replica's enriched ``GET /healthz`` (queued rows, cumulative
  request/shed/error counters, p99, resident bytes) on a fixed
  cadence; request placement picks the ready, non-draining replica
  with the least (in-flight + queued) load. A replica that fails
  ``fleet_unhealthy_after`` consecutive polls — or any forward
  attempt at transport level — is routed around until a poll
  succeeds again.
- **idempotent retries** — ``predict`` is pure, so a transport
  failure (connection refused/reset, torn reply: the signature of a
  replica dying mid-request) retries the SAME rows on another replica,
  excluding the failed one. Losing a replica mid-traffic therefore
  drops **zero** requests (pinned by tests and the
  ``serve_bench --replicas`` kill scenario). A ``closed`` reply
  (replica draining) retries the same way; a ``busy`` reply retries
  once on a less-loaded replica before shedding.
- **fleet-wide tenant quotas** — the per-tenant token buckets
  (``serve_quota``/``serve_quota_default``) are enforced HERE, before
  any replica queue; replicas are spawned with quotas stripped so one
  tenant's contract is one bucket across the whole fleet, not N.
- **canary pinning** — ``pin_canary(version, fraction)`` routes a
  deterministic fraction of requests to replicas of that version;
  per-version outcome/latency windows feed the canary comparator
  (``fleet/canary.py``).

Every request emits a schema-validated ``fleet_route`` record
(replica, version, retries); quota sheds also emit ``tenant_shed``.
"""

from __future__ import annotations

import http.client
import json
import math
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..monitor import LatencyHistogram, SafeEmitter
from ..serve.frontend import (_BinaryHandler, _FleetBinaryServer,
                              _FleetHTTPServer, _HttpHandler,
                              HTTP_STATUS, BinaryClient)
from ..serve.quota import QuotaManager, TenantQuotaError
from .config import FleetTierConfig


class ReplicaUnreachable(IOError):
    """Transport-level forward failure: the replica is gone or the
    connection died mid-exchange. Requests are idempotent, so the
    caller retries on another replica."""


class ReplicaState:
    """Balancer-side view of one replica endpoint. ``inflight`` and
    the flags are guarded by the balancer's table lock; the connection
    pool has its own leaf lock (socket I/O must not hold the table
    lock)."""

    def __init__(self, replica_id: str, host: str, http_port: int,
                 binary_port: int, version: str,
                 kind: str = "baseline"):
        self.replica_id = replica_id
        self.host = host
        self.http_port = http_port
        self.binary_port = binary_port
        self.version = version
        self.kind = kind
        self.ready = True
        self.draining = False
        self.suspect = False
        self.suspect_since = 0.0
        self.fail_polls = 0
        self.inflight = 0
        self.health: Dict[str, Any] = {}
        self._pool: List[BinaryClient] = []
        self._pool_lock = threading.Lock()

    # -- connection pool (persistent binary connections) -----------------

    def acquire(self, timeout: float) -> BinaryClient:
        with self._pool_lock:
            if self._pool:
                return self._pool.pop()
        return BinaryClient(self.host, self.binary_port,
                            timeout=timeout)

    def release(self, client: BinaryClient) -> None:
        with self._pool_lock:
            self._pool.append(client)

    def close_pool(self) -> None:
        with self._pool_lock:
            clients, self._pool = self._pool, []
        for c in clients:
            try:
                c.close()
            except OSError:
                pass  # cxxlint: disable=CXL006 -- teardown of a possibly-dead socket; there is nothing to do with a close error

    def describe(self) -> Dict[str, Any]:
        return {"replica": self.replica_id, "version": self.version,
                "kind": self.kind, "ready": self.ready,
                "draining": self.draining, "suspect": self.suspect,
                "inflight": self.inflight,
                "queue_rows": self.health.get("queue_rows", 0),
                "p99_ms": self.health.get("p99_ms", 0.0),
                "resident_bytes": self.health.get("resident_bytes",
                                                  0)}


class _VersionStats:
    """Per-bundle-version outcome window (canary comparison)."""

    __slots__ = ("ok", "errors", "lat")

    def __init__(self):
        self.ok = 0
        self.errors = 0
        self.lat = LatencyHistogram()

    def snapshot(self) -> Dict[str, Any]:
        return {"ok": self.ok, "errors": self.errors,
                "requests": self.ok + self.errors,
                "p99_ms": round(self.lat.percentile(0.99), 3),
                "p50_ms": round(self.lat.percentile(0.50), 3)}


class FleetBalancer:
    """N replica endpoints behind the two protocol listeners.

    Build from the parsed tier config plus the raw config stream (for
    the quota grammar); ``start()`` binds listeners and the health
    poller, ``close()`` stops them. Replica registration is the
    controller's job (``add_replica`` / ``drain_replica`` /
    ``remove_replica``)."""

    # forward socket timeout: generous enough for a queued request on
    # a loaded replica, finite so a wedged replica turns into a
    # retryable transport error instead of a hung client
    FORWARD_TIMEOUT_S = 60.0

    def __init__(self, tier: FleetTierConfig, cfg=(), monitor=None):
        self.tier = tier
        self.quota = QuotaManager(cfg)
        self._mon = monitor
        self._safe_emit = SafeEmitter(monitor, "cxxnet_tpu fleet")
        self._lock = threading.Lock()        # replica table
        self._reps: Dict[str, ReplicaState] = {}
        self._stats = threading.Lock()       # counters + windows
        self.counters: Dict[str, int] = {
            "requests": 0, "ok": 0, "shed": 0, "errors": 0,
            "retries": 0, "unrouted": 0}
        self._win = {"requests": 0, "ok": 0, "shed": 0, "errors": 0}
        self._win_lat = LatencyHistogram()
        self._win_t0 = time.monotonic()
        self._versions: Dict[str, _VersionStats] = {}
        self._pin_version: Optional[str] = None
        self._pin_fraction = 0.0
        self._pick_seq = 0
        self._closing = False
        self._http_server = None
        self._binary_server = None
        self._threads: List[threading.Thread] = []
        self._poll_stop = threading.Event()
        self.http_port = -1
        self.binary_port = -1

    # -- replica table ----------------------------------------------------

    def add_replica(self, replica_id: str, host: str, http_port: int,
                    binary_port: int, version: str,
                    kind: str = "baseline") -> ReplicaState:
        rep = ReplicaState(replica_id, host, http_port, binary_port,
                           version, kind)
        with self._lock:
            if replica_id in self._reps:
                raise ValueError("replica %r already registered"
                                 % replica_id)
            self._reps[replica_id] = rep
        return rep

    def remove_replica(self, replica_id: str) -> None:
        with self._lock:
            rep = self._reps.pop(replica_id, None)
        if rep is not None:
            rep.close_pool()

    def drain_replica(self, replica_id: str,
                      timeout_s: float = 30.0) -> bool:
        """Stop routing new requests to the replica, then wait for its
        in-flight forwards to finish — the zero-drop half of scale-in.
        Returns False if in-flight work remained at the timeout."""
        with self._lock:
            rep = self._reps.get(replica_id)
            if rep is None:
                return True
            rep.draining = True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if rep.inflight == 0:
                    return True
            time.sleep(0.01)
        with self._lock:
            return rep.inflight == 0

    def suspect_overdue(self, deadline_s: float) -> List[str]:
        """Replicas that have been suspect (failing polls / transport)
        for longer than ``deadline_s`` — alive-but-wedged processes
        the controller must reap, or they would hold a fleet slot
        forever while serving nothing."""
        now = time.monotonic()
        with self._lock:
            return [r.replica_id for r in self._reps.values()
                    if r.suspect and r.suspect_since
                    and now - r.suspect_since >= deadline_s]

    def replica_ids(self, kind: Optional[str] = None,
                    version: Optional[str] = None) -> List[str]:
        with self._lock:
            return [r.replica_id for r in self._reps.values()
                    if (kind is None or r.kind == kind)
                    and (version is None or r.version == version)]

    def describe_replicas(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [r.describe() for r in self._reps.values()]

    # -- canary pinning ----------------------------------------------------

    def pin_canary(self, version: str, fraction: float) -> None:
        """Route ``fraction`` of requests to replicas serving
        ``version`` (deterministic interleave, no RNG: request k goes
        canary iff floor(k*f) advanced). Also resets the per-version
        windows so the comparison covers exactly the pinned period."""
        with self._stats:
            self._versions = {}
        with self._lock:
            self._pin_version = version
            self._pin_fraction = float(fraction)
            self._pick_seq = 0

    def unpin_canary(self) -> None:
        with self._lock:
            self._pin_version = None
            self._pin_fraction = 0.0

    def set_replica_kind(self, replica_id: str, kind: str) -> None:
        """Reclassify a replica (a promoted canary joins the baseline
        pool the autoscaler manages)."""
        with self._lock:
            rep = self._reps.get(replica_id)
            if rep is not None:
                rep.kind = kind

    def version_stats(self) -> Dict[str, Dict[str, Any]]:
        with self._stats:
            return {v: s.snapshot()
                    for v, s in self._versions.items()}

    # -- the request path --------------------------------------------------

    def handle(self, model_id: str, tenant: str, rows,
               protocol: str = "http",
               timeout_ms: Optional[float] = None
               ) -> Tuple[str, Any, Dict[str, Any]]:
        """Quota -> pick replica -> forward (with idempotent retry).
        Same contract as ``FleetServer.handle`` — never raises, so
        both protocol handlers plug in unchanged."""
        t0 = time.monotonic()
        nrows = 0
        replica_id, version, retries = "", "", 0
        try:
            arr = np.asarray(rows, dtype=np.float32)  # cxxlint: disable=CXL003 -- protocol decode on the network tier: client rows arrive as host bytes/JSON lists, there is no device value to keep resident
            if arr.ndim == 0:
                raise ValueError("rows must be an array, got a scalar")
            nrows = int(arr.shape[0]) if arr.ndim > 1 else 1
            try:
                self.quota.admit(tenant, nrows)
            except TenantQuotaError as e:
                self._emit("tenant_shed", tenant=tenant,
                           model=model_id, rows=nrows, rate=e.rate,
                           burst=e.burst,
                           retry_after_s=round(e.retry_after_s, 3))
                raise
            status, result, extra, replica_id, version, retries = \
                self._route(model_id, tenant, arr, timeout_ms)
        except TenantQuotaError as e:
            status, result = "over_quota", str(e)
            extra = {"retry_after_s": e.retry_after_s}
        except (ValueError, TypeError) as e:
            status, result, extra = "bad_request", str(e), {}
        except Exception as e:   # a balancer bug must answer, not hang
            status, result, extra = "error", str(e), {}
        self._record(protocol, status, model_id, tenant, nrows,
                     replica_id, version, retries, t0)
        return status, result, extra

    def _route(self, model_id: str, tenant: str, arr: np.ndarray,
               timeout_ms: Optional[float]):
        excluded: set = set()
        retries = 0
        last: Optional[Tuple[str, Any, str, str]] = None
        for attempt in range(self.tier.retries + 1):
            rep = self._pick(excluded)
            if rep is None:
                break
            with self._lock:
                rep.inflight += 1
            try:
                status, result = self._forward(rep, model_id, tenant,
                                               arr, timeout_ms)
            except ReplicaUnreachable:
                # the replica died (or its socket did) mid-request:
                # mark it suspect so new requests route around it, and
                # retry these idempotent rows elsewhere
                with self._lock:
                    if not rep.suspect:
                        rep.suspect = True
                        rep.suspect_since = time.monotonic()
                excluded.add(rep.replica_id)
                retries += 1
                continue
            finally:
                with self._lock:
                    rep.inflight -= 1
            if status == "closed" and not self._closing:
                # replica draining/shut down between pick and forward
                excluded.add(rep.replica_id)
                retries += 1
                last = (status, result, rep.replica_id, rep.version)
                continue
            if status == "busy" and attempt == 0 \
                    and self._ready_count() > 1:
                # one overloaded replica is not fleet overload: give a
                # less-loaded replica one chance before shedding
                excluded.add(rep.replica_id)
                retries += 1
                last = (status, result, rep.replica_id, rep.version)
                continue
            return status, result, {}, rep.replica_id, rep.version, \
                retries
        if last is not None:
            status, result, rid, ver = last
            return status, result, {}, rid, ver, retries
        with self._stats:
            self.counters["unrouted"] += 1
        return ("closed", "no ready replicas", {}, "", "", retries)

    def _ready_count(self) -> int:
        with self._lock:
            return sum(1 for r in self._reps.values()
                       if r.ready and not r.draining
                       and not r.suspect)

    def _pick(self, excluded: set) -> Optional[ReplicaState]:
        """Least-loaded ready replica (in-flight forwards + last
        polled queue depth), honoring the canary pin."""
        with self._lock:
            cands = [r for r in self._reps.values()
                     if r.ready and not r.draining and not r.suspect
                     and r.replica_id not in excluded]
            if not cands:
                # desperation pass: every healthy replica is excluded
                # or suspect — a suspect replica may have recovered,
                # and answering beats returning "no replicas"
                cands = [r for r in self._reps.values()
                         if r.ready and not r.draining
                         and r.replica_id not in excluded]
            if not cands:
                return None
            if self._pin_version is not None:
                self._pick_seq += 1
                f = self._pin_fraction
                want_canary = (math.floor(self._pick_seq * f)
                               > math.floor((self._pick_seq - 1) * f))
                pool = [r for r in cands
                        if (r.version == self._pin_version)
                        == want_canary]
                if pool:
                    cands = pool
            return min(cands, key=lambda r: (
                r.inflight + r.health.get("queue_rows", 0),
                r.replica_id))

    def _forward(self, rep: ReplicaState, model_id: str, tenant: str,
                 arr: np.ndarray,
                 timeout_ms: Optional[float]) -> Tuple[str, Any]:
        """One binary-protocol exchange with the replica over a pooled
        persistent connection. Any socket/framing failure raises
        :class:`ReplicaUnreachable` (connection discarded)."""
        # a client that declared a deadline LONGER than the default
        # forward timeout gets the socket window to match — otherwise
        # a legitimately slow request could never succeed through the
        # balancer and would burn duplicate device work via retries
        sock_timeout = self.FORWARD_TIMEOUT_S
        if timeout_ms:
            sock_timeout = max(sock_timeout, timeout_ms / 1e3 + 5.0)
        try:
            client = rep.acquire(sock_timeout)
        except OSError as e:
            raise ReplicaUnreachable(
                "replica %s unreachable: %s" % (rep.replica_id, e))
        try:
            client.sock.settimeout(sock_timeout)
            status, result = client.predict(
                arr, model=model_id, tenant=tenant,
                timeout_ms=timeout_ms if timeout_ms else 0.0)
        except OSError as e:
            try:
                client.close()
            except OSError:
                pass  # cxxlint: disable=CXL006 -- the transport already failed; close is best-effort cleanup
            raise ReplicaUnreachable(
                "replica %s failed mid-request: %s"
                % (rep.replica_id, e))
        rep.release(client)
        return status, result

    # -- telemetry / accounting -------------------------------------------

    def _emit(self, kind: str, **fields) -> None:
        self._safe_emit(kind, **fields)

    def _record(self, protocol: str, status: str, model: str,
                tenant: str, rows: int, replica_id: str, version: str,
                retries: int, t0: float) -> None:
        latency_s = time.monotonic() - t0
        shed = status in ("busy", "over_quota")
        with self._stats:
            self.counters["requests"] += 1
            self.counters["retries"] += retries
            self._win["requests"] += 1
            if status == "ok":
                self.counters["ok"] += 1
                self._win["ok"] += 1
                self._win_lat.observe(latency_s)
            elif shed:
                self.counters["shed"] += 1
                self._win["shed"] += 1
            else:
                self.counters["errors"] += 1
                self._win["errors"] += 1
            if version:
                vs = self._versions.get(version)
                if vs is None:
                    vs = self._versions[version] = _VersionStats()
                if status == "ok":
                    vs.ok += 1
                    vs.lat.observe(latency_s)
                elif not shed:
                    vs.errors += 1
        self._emit("fleet_route", protocol=protocol, status=status,
                   model=model, tenant=tenant, rows=rows,
                   replica=replica_id, version=version,
                   retries=retries, latency_ms=latency_s * 1e3)

    def take_window(self) -> Dict[str, Any]:
        """Counters since the last call plus the CURRENT fleet load —
        the autoscaler's input. Swapping the window out keeps rates
        honest without unbounded history."""
        now = time.monotonic()
        with self._stats:
            w = self._win
            lat = self._win_lat
            self._win = {"requests": 0, "ok": 0, "shed": 0,
                         "errors": 0}
            self._win_lat = LatencyHistogram()
            t0, self._win_t0 = self._win_t0, now
        with self._lock:
            ready = [r for r in self._reps.values()
                     if r.ready and not r.draining and not r.suspect]
            queue_rows = sum(r.health.get("queue_rows", 0)
                             for r in ready)
            max_batch = max(
                (m.get("max_batch", 0)
                 for r in ready
                 for m in r.health.get("model_health", [])),
                default=0)
            total = len(self._reps)
        return {
            "requests": w["requests"], "ok": w["ok"],
            "shed": w["shed"], "errors": w["errors"],
            "p99_ms": round(lat.percentile(0.99), 3),
            "queue_rows": queue_rows, "max_batch": max_batch,
            "ready": len(ready), "replicas": total,
            "window_s": now - t0,
        }

    # -- health polling ----------------------------------------------------

    def _poll_once(self, rep: ReplicaState) -> None:
        try:
            conn = http.client.HTTPConnection(
                rep.host, rep.http_port,
                timeout=max(1.0, self.tier.health_poll_s * 4))
            try:
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                payload = json.loads(resp.read())
                ok = resp.status == 200 and payload.get("ok")
            finally:
                conn.close()
        except (OSError, ValueError):
            ok, payload = False, None
        with self._lock:
            if ok:
                rep.health = payload
                rep.fail_polls = 0
                rep.suspect = False
                rep.suspect_since = 0.0
            else:
                rep.fail_polls += 1
                if rep.fail_polls >= self.tier.unhealthy_after \
                        and not rep.suspect:
                    rep.suspect = True
                    rep.suspect_since = time.monotonic()

    def _poll_loop(self) -> None:
        while not self._poll_stop.wait(self.tier.health_poll_s):
            with self._lock:
                reps = list(self._reps.values())
            for rep in reps:
                self._poll_once(rep)

    # -- own health / status ----------------------------------------------

    def health_snapshot(self) -> Dict[str, Any]:
        with self._stats:
            c = dict(self.counters)
        reps = self.describe_replicas()
        ready = sum(1 for r in reps
                    if r["ready"] and not r["draining"]
                    and not r["suspect"])
        with self._lock:
            pin = {"version": self._pin_version,
                   "fraction": self._pin_fraction} \
                if self._pin_version else None
        return {"ok": ready > 0, "tier": "balancer",
                "ready": ready, "replicas": reps,
                "requests": c["requests"], "shed": c["shed"],
                "errors": c["errors"], "retries": c["retries"],
                "canary": pin,
                "queue_rows": sum(r["queue_rows"] for r in reps),
                "resident_bytes": sum(r["resident_bytes"]
                                      for r in reps)}

    def models_snapshot(self) -> Dict[str, Any]:
        """``GET /v1/models`` at the balancer: the model table proxied
        from one ready replica (they all serve the same contract),
        annotated with the per-version replica split."""
        with self._lock:
            cands = [r for r in self._reps.values()
                     if r.ready and not r.suspect]
        models: List[Dict[str, Any]] = []
        for rep in cands:
            try:
                conn = http.client.HTTPConnection(
                    rep.host, rep.http_port, timeout=5.0)
                try:
                    conn.request("GET", "/v1/models")
                    resp = conn.getresponse()
                    if resp.status == 200:
                        models = json.loads(resp.read())["models"]
                        break
                finally:
                    conn.close()
            except (OSError, ValueError):
                continue          # a dead replica: try the next one
        versions: Dict[str, int] = {}
        with self._lock:
            for r in self._reps.values():
                versions[r.version] = versions.get(r.version, 0) + 1
        return {"models": models, "replica_versions": versions}

    # -- listeners ---------------------------------------------------------

    def start(self) -> None:
        t = self.tier
        if t.http_port >= 0:
            self._http_server = _FleetHTTPServer(
                (t.host, t.http_port), _BalancerHttpHandler, self)
            self.http_port = self._http_server.server_address[1]
            th = threading.Thread(
                target=self._http_server.serve_forever,
                name="fleet-http", daemon=True)
            th.start()
            self._threads.append(th)
        if t.binary_port >= 0:
            self._binary_server = _FleetBinaryServer(
                (t.host, t.binary_port), _BinaryHandler, self)
            self.binary_port = self._binary_server.server_address[1]
            th = threading.Thread(
                target=self._binary_server.serve_forever,
                name="fleet-binary", daemon=True)
            th.start()
            self._threads.append(th)
        poller = threading.Thread(target=self._poll_loop,
                                  name="fleet-health", daemon=True)
        poller.start()
        self._threads.append(poller)

    def close(self) -> Dict[str, Any]:
        self._closing = True
        self._poll_stop.set()
        for srv in (self._http_server, self._binary_server):
            if srv is not None:
                srv.shutdown()
                srv.server_close()
        for th in self._threads:
            th.join(timeout=30)
        with self._lock:
            reps = list(self._reps.values())
            self._reps = {}
        for rep in reps:
            rep.close_pool()
        with self._stats:
            return dict(self.counters)


# -- balancer HTTP protocol ------------------------------------------------
#
# Reuses the fleet front end's JSON plumbing (_send_json, keep-alive,
# no access log); only the introspection payloads differ — requests go
# through FleetBalancer.handle, which shares FleetServer.handle's
# contract, so the POST body/reply grammar is identical on purpose.


class _BalancerHttpHandler(_HttpHandler):

    def do_GET(self):
        bal = self.server.fleet
        if self.path == "/healthz":
            self._send_json(200, bal.health_snapshot())
        elif self.path == "/v1/models":
            self._send_json(200, bal.models_snapshot())
        else:
            self._send_json(404, {"error": "not_found",
                                  "message": "unknown path %r"
                                  % self.path})

    def do_POST(self):
        bal = self.server.fleet
        if self.path != "/v1/predict":
            self._send_json(404, {"error": "not_found",
                                  "message": "POST /v1/predict"})
            return
        t0 = time.monotonic()
        try:
            length = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(length) or b"{}")
            model = str(req.get("model", ""))
            tenant = str(req.get("tenant", ""))
            timeout_ms = req.get("timeout_ms")
            rows = req["rows"]
        except (ValueError, KeyError, TypeError) as e:
            bal._record("http", "bad_request", "", "", 0, "", "", 0,
                        t0)
            self._send_json(400, {"error": "bad_request",
                                  "message": "body must be JSON with "
                                  "'rows': %s" % e})
            return
        status, result, extra = bal.handle(
            model, tenant, rows, protocol="http",
            timeout_ms=timeout_ms)
        code = HTTP_STATUS[status]
        if status == "ok":
            flat = np.asarray(result)
            self._send_json(code, {
                "model": model,
                "rows": int(flat.shape[0]),
                "result": flat.reshape(flat.shape[0], -1).tolist()})
            return
        headers = {}
        if status in ("busy", "over_quota"):
            headers["Retry-After"] = "%d" % max(
                1, int(extra.get("retry_after_s", 1) + 0.999))
        self._send_json(code, dict(
            {"error": status, "message": result}, **extra),
            headers=headers)

"""Front-of-fleet balancer: one address over N shared-nothing replicas.

The :class:`FleetBalancer` speaks both existing protocols (HTTP/JSON
and the CXN1 binary frames — the frame grammar and status vocabulary
are imported from ``serve/frontend.py``, so every existing client
works unchanged) and routes each request to a replica process:

- **load-aware health routing** — a poller thread reads every
  replica's enriched ``GET /healthz`` (queued rows, cumulative
  request/shed/error counters, p99, resident bytes) on a fixed
  cadence; request placement picks the ready, non-draining replica
  with the least (in-flight + queued) load. A replica that fails
  ``fleet_unhealthy_after`` consecutive polls — or any forward
  attempt at transport level — is routed around until a poll
  succeeds again.
- **idempotent retries** — ``predict`` is pure, so a transport
  failure (connection refused/reset, torn reply: the signature of a
  replica dying mid-request) retries the SAME rows on another replica,
  excluding the failed one. Losing a replica mid-traffic therefore
  drops **zero** requests (pinned by tests and the
  ``serve_bench --replicas`` kill scenario). A ``closed`` reply
  (replica draining) retries the same way; a ``busy`` reply retries
  once on a less-loaded replica before shedding.
- **fleet-wide tenant quotas** — the per-tenant token buckets
  (``serve_quota``/``serve_quota_default``) are enforced HERE, before
  any replica queue; replicas are spawned with quotas stripped so one
  tenant's contract is one bucket across the whole fleet, not N.
- **canary pinning** — ``pin_canary(version, fraction)`` routes a
  deterministic fraction of requests to replicas of that version;
  per-version outcome/latency windows feed the canary comparator
  (``fleet/canary.py``).
- **multiplexed data path** (doc/serving.md "Fleet data path") —
  forwards ride ``fleet_channels_per_replica`` persistent protocol-v2
  connections per replica (:class:`ReplicaChannel`: a writer queue +
  a reader thread resolving in-flight futures by correlation id), so
  per-replica concurrency is true pipelining over a handful of
  sockets instead of one blocking round trip per pooled connection.
  With ``fleet_coalesce_ms`` set, same-model requests merge into
  forwarded super-batches split by row offset on reply
  (:class:`_Coalescer`, completion-driven: idle traffic forwards
  immediately, load itself sets the batch size, the window is only
  the backstop); binary-path client row bytes relay into the forward
  frame as validated buffers — no decode→float32→re-encode on the
  hot path.

Every request emits a schema-validated ``fleet_route`` record
(replica, version, retries, coalesce/channel accounting); coalesced
forwards emit ``fleet_batch``; quota sheds also emit ``tenant_shed``.
"""

from __future__ import annotations

import http.client
import json
import math
import queue
import socket
import threading
import time
from concurrent.futures import Future, InvalidStateError
from concurrent.futures import TimeoutError as FutureTimeout
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..monitor import LatencyHistogram, SafeEmitter
from ..serve.frontend import (_BinaryHandler, _FleetBinaryServer,
                              _FleetHTTPServer, _HttpHandler,
                              _REQ_HEADER_V2, BIN_MAGIC_V2,
                              HTTP_STATUS, BinaryClient, pack_ping_v2,
                              read_reply_tagged)
from ..serve.quota import TenantQuotaError
from .config import FleetTierConfig
from .quota_shares import QuotaShareManager


class ReplicaUnreachable(IOError):
    """Transport-level forward failure: the replica is gone or the
    connection died mid-exchange. Requests are idempotent, so the
    caller retries on another replica."""


class ReplicaV1Only(Exception):
    """The connect-time negotiation probe (a v2 ping) was answered
    with a v1 frame: the replica predates protocol v2. The balancer
    falls back to the pooled one-round-trip-per-connection path for
    it — old replicas keep working, just without pipelining."""


def _row_buffers(arr) -> Tuple[List[Any], int, int]:
    """``(buffers, nrows, elems)`` for relaying ``arr`` as a v2 frame
    payload. A C-contiguous little-endian float32 array — exactly what
    the binary ingress path hands through — is passed as ONE buffer
    view (zero-copy relay: the writer streams it straight onto the
    socket); anything else (the HTTP path's admission-converted rows)
    pays its one conversion here and never again."""
    a = np.ascontiguousarray(arr, dtype="<f4")
    if a.ndim == 1:
        a = a[None, :]
    nrows = int(a.shape[0])
    elems = int(a.size // nrows) if nrows else int(
        np.prod(a.shape[1:], dtype=np.int64)) or 1
    return [memoryview(a).cast("B")], nrows, elems


class _Inflight:
    __slots__ = ("future", "deadline")

    def __init__(self, window_s: float):
        self.future: Future = Future()
        self.deadline = time.monotonic() + window_s


class ReplicaChannel:
    """One persistent **multiplexed** v2 connection to a replica.

    Submitting threads enqueue framed requests on a writer queue and
    get a Future; a writer thread streams frames onto the socket
    (relaying client row buffers without re-encoding) and a reader
    thread resolves in-flight futures by correlation id as replies
    arrive — out of order, so a handful of sockets carry many
    concurrent requests with no head-of-line blocking (doc/serving.md
    "Fleet data path"). Any transport failure breaks the WHOLE
    channel: every in-flight future fails with
    :class:`ReplicaUnreachable` (requests are idempotent; callers
    retry elsewhere) and the owner reconnects lazily."""

    def __init__(self, host: str, port: int, index: int = 0,
                 connect_timeout: float = 5.0,
                 io_timeout: float = 3600.0):
        self.index = index
        self._sock = socket.create_connection(
            (host, port), timeout=connect_timeout)
        # frames go out as header + body segments: without NODELAY,
        # Nagle holds the body for the replica's delayed ACK (~40ms
        # added to EVERY channel exchange)
        self._sock.setsockopt(socket.IPPROTO_TCP,
                              socket.TCP_NODELAY, 1)
        self._rfile = self._sock.makefile("rb")
        self._lock = threading.Lock()
        self._inflight: Dict[int, _Inflight] = {}
        self._next_corr = 0
        self._broken: Optional[BaseException] = None
        self.max_depth = 0
        # negotiate: a v1-only server answers the v2 ping with a v1
        # bad_request frame (unknown magic) and drops the connection
        try:
            self._sock.sendall(pack_ping_v2(0))
            corr, _, _ = read_reply_tagged(self._rfile)
        except (OSError, ValueError) as e:
            self._close_sock()
            raise ReplicaUnreachable(
                "channel probe to %s:%d failed: %s" % (host, port, e))
        if corr is None:
            self._close_sock()
            raise ReplicaV1Only(
                "replica at %s:%d speaks protocol v1 only"
                % (host, port))
        self._sock.settimeout(io_timeout)
        self._send_lock = threading.Lock()
        self._wq: "queue.Queue" = queue.Queue()
        self._writer = threading.Thread(
            target=self._writer_loop, daemon=True,
            name="fleet-chan-w%d" % index)
        self._reader = threading.Thread(
            target=self._reader_loop, daemon=True,
            name="fleet-chan-r%d" % index)
        self._writer.start()
        self._reader.start()

    # -- submit side -------------------------------------------------------

    def submit(self, model: str, tenant: str, buffers: List[Any],
               nrows: int, elems: int, timeout_ms: float,
               window_s: float, blocking: bool = True) -> Future:
        """Frame one request; the Future resolves to
        ``(status_name, payload)`` or fails with ReplicaUnreachable.
        A ``blocking`` caller (a request handler thread that will wait
        on the future anyway) sends inline under the send lock — no
        thread hop; ``blocking=False`` (the coalescer's completion
        callbacks, which must never block a channel reader) rides the
        writer queue instead."""
        m, t = model.encode(), tenant.encode()
        if len(m) > 255 or len(t) > 255:
            raise ValueError(
                "model/tenant ids are limited to 255 bytes")
        ent = _Inflight(window_s)
        now = time.monotonic()
        with self._lock:
            if self._broken is not None:
                raise ReplicaUnreachable(
                    "channel broken: %s" % self._broken)
            # sweep entries whose waiter gave up long ago and whose
            # reply never came, so a wedged replica cannot grow the
            # map without bound
            stale = [c for c, e in self._inflight.items()
                     if now > e.deadline + 5.0]
            for c in stale:
                del self._inflight[c]
            self._next_corr += 1
            corr = self._next_corr
            self._inflight[corr] = ent
            depth = len(self._inflight)
            if depth > self.max_depth:
                self.max_depth = depth
        head = _REQ_HEADER_V2.pack(BIN_MAGIC_V2, corr, len(m), len(t),
                                   nrows, elems,
                                   float(timeout_ms or 0.0)) + m + t
        if not blocking:
            self._wq.put((head, buffers))
            return ent.future
        try:
            with self._send_lock:
                self._sock.sendall(head)
                for b in buffers:
                    self._sock.sendall(b)
        except OSError as e:
            self._break(e)
        return ent.future

    def depth(self) -> int:
        with self._lock:
            return len(self._inflight)

    def broken(self) -> bool:
        with self._lock:
            return self._broken is not None

    # -- worker loops ------------------------------------------------------

    def _writer_loop(self) -> None:
        while True:
            item = self._wq.get()
            if item is None:
                return
            head, buffers = item
            try:
                with self._send_lock:
                    self._sock.sendall(head)
                    for b in buffers:
                        self._sock.sendall(b)
            except OSError as e:
                self._break(e)
                return

    def _reader_loop(self) -> None:
        try:
            while True:
                try:
                    corr, status, payload = \
                        read_reply_tagged(self._rfile)
                except (OSError, ValueError) as e:
                    self._break(e)
                    return
                if corr is None:
                    self._break(IOError("v1 frame on a v2 channel"))
                    return
                with self._lock:
                    ent = self._inflight.pop(corr, None)
                if ent is None:
                    continue   # waiter expired and retried elsewhere
                if not ent.future.done():
                    try:
                        ent.future.set_result((status, payload))
                    except InvalidStateError:
                        pass  # cxxlint: disable=CXL006 -- the waiter cancelled first; the reply has no recipient
        finally:
            # the reader owns the buffered rfile: closing it from
            # another thread would deadlock on the buffer lock while
            # a read is parked in recv
            try:
                self._rfile.close()
            except OSError:
                pass  # cxxlint: disable=CXL006 -- teardown of a possibly-dead socket; there is nothing to do with a close error

    def _break(self, exc: BaseException) -> None:
        with self._lock:
            already = self._broken is not None
            if not already:
                self._broken = exc
            pending = list(self._inflight.values())
            self._inflight = {}
        if already and not pending:
            return
        err = ReplicaUnreachable("replica channel failed: %s" % exc)
        for ent in pending:
            if not ent.future.done():
                try:
                    ent.future.set_exception(err)
                except InvalidStateError:
                    pass  # cxxlint: disable=CXL006 -- the waiter cancelled first; nothing is owed an answer
        self._close_sock()
        self._wq.put(None)   # release the writer

    def _close_sock(self) -> None:
        # shutdown (not just close) unblocks a reader parked in recv;
        # the buffered rfile is closed by the reader thread itself —
        # closing it here would deadlock on its buffer lock
        for closer in (lambda: self._sock.shutdown(socket.SHUT_RDWR),
                       self._sock.close):
            try:
                closer()
            except OSError:
                pass  # cxxlint: disable=CXL006 -- teardown of a possibly-dead socket; there is nothing to do with a close error

    def close(self) -> None:
        self._break(IOError("channel closed"))


class ReplicaState:
    """Balancer-side view of one replica endpoint. ``inflight`` and
    the flags are guarded by the balancer's table lock; the connection
    pool has its own leaf lock (socket I/O must not hold the table
    lock)."""

    def __init__(self, replica_id: str, host: str, http_port: int,
                 binary_port: int, version: str,
                 kind: str = "baseline"):
        self.replica_id = replica_id
        self.host = host
        self.http_port = http_port
        self.binary_port = binary_port
        self.version = version
        self.kind = kind
        self.ready = True
        self.draining = False
        self.suspect = False
        self.suspect_since = 0.0
        self.fail_polls = 0
        self.inflight = 0
        self.health: Dict[str, Any] = {}
        # freshness + provenance of ``health``: a multi-balancer tier
        # partitions polling, so state may arrive from a peer's gossip
        # view instead of a direct poll
        self.health_ts = 0.0
        self.health_src = ""
        self.v1_only = False
        self._pool: List[BinaryClient] = []
        self._pool_lock = threading.Lock()
        self._channels: List[Optional[ReplicaChannel]] = []
        self._ch_rr = 0

    # -- multiplexed channels (protocol v2) -------------------------------

    def channel(self, nch: int,
                io_timeout: float) -> Optional[ReplicaChannel]:
        """Round-robin over up to ``nch`` persistent multiplexed
        channels, (re)connecting broken slots lazily. Returns None
        when the replica negotiated v1-only (caller falls back to the
        pooled path); raises :class:`ReplicaUnreachable` when the
        replica refuses the connection."""
        if self.v1_only or nch <= 0:
            return None
        with self._pool_lock:
            if len(self._channels) < nch:
                self._channels.extend(
                    [None] * (nch - len(self._channels)))
            self._ch_rr += 1
            i = self._ch_rr % nch
            ch = self._channels[i]
            if ch is not None and not ch.broken():
                return ch
            # connect under the leaf lock: localhost connects are
            # cheap, and a refused connect fails fast for everyone
            try:
                ch = ReplicaChannel(self.host, self.binary_port,
                                    index=i, io_timeout=io_timeout)
            except ReplicaV1Only:
                self.v1_only = True
                return None
            except OSError as e:
                raise ReplicaUnreachable(
                    "replica %s unreachable: %s"
                    % (self.replica_id, e))
            self._channels[i] = ch
            return ch

    def channel_depth(self) -> int:
        """In-flight requests across this replica's live channels —
        the pipelining-depth telemetry in the balancer window."""
        with self._pool_lock:
            chans = [c for c in self._channels if c is not None]
        return sum(c.depth() for c in chans if not c.broken())

    # -- connection pool (v1 fallback: one round trip per conn) ----------

    def acquire(self, timeout: float) -> BinaryClient:
        with self._pool_lock:
            if self._pool:
                return self._pool.pop()
        return BinaryClient(self.host, self.binary_port,
                            timeout=timeout)

    def release(self, client: BinaryClient) -> None:
        with self._pool_lock:
            self._pool.append(client)

    def close_pool(self) -> None:
        with self._pool_lock:
            clients, self._pool = self._pool, []
            chans, self._channels = \
                [c for c in self._channels if c is not None], []
        for c in clients:
            try:
                c.close()
            except OSError:
                pass  # cxxlint: disable=CXL006 -- teardown of a possibly-dead socket; there is nothing to do with a close error
        for ch in chans:
            ch.close()

    def describe(self) -> Dict[str, Any]:
        return {"replica": self.replica_id, "version": self.version,
                "kind": self.kind, "ready": self.ready,
                "draining": self.draining, "suspect": self.suspect,
                "inflight": self.inflight,
                "queue_rows": self.health.get("queue_rows", 0),
                "p99_ms": self.health.get("p99_ms", 0.0),
                "resident_bytes": self.health.get("resident_bytes",
                                                  0)}


class _MergeJob:
    """One client request riding a coalesce window; the Future
    resolves to the full per-request outcome tuple
    ``(status, result, extra, replica_id, version, retries,
    coalesced, channel)``."""

    __slots__ = ("arr", "nrows", "timeout_ms", "future")

    def __init__(self, arr, nrows: int,
                 timeout_ms: Optional[float]):
        self.arr = arr
        self.nrows = nrows
        self.timeout_ms = timeout_ms
        self.future: Future = Future()


class _Coalescer:
    """Balancer-side request coalescing (``fleet_coalesce_ms``) —
    **completion-driven**: a request for an idle model forwards
    IMMEDIATELY (an unloaded fleet pays zero added latency); while
    forward slots (ready replicas x channels) are occupied, arriving
    requests queue, and each completing forward splits the queue
    EVENLY across the free slots as merged super-batches, split back
    by row offset on reply. Load itself sets the batch size — PR 4's
    dispatcher economics applied one tier up, so single-row clients
    stop forcing a per-request forward (and its per-frame replica
    work) at high concurrency.

    ``fleet_coalesce_ms`` is the BACKSTOP: a queued window older than
    the window is force-flushed by the flusher thread even with every
    slot busy (a stalled forward must not become every request's
    wait), and ``fleet_coalesce_rows`` caps merged-batch size the
    same way. Forwarding is non-blocking
    (``FleetBalancer._forward_merged``), so one slow super-batch
    never delays the other models' queues."""

    def __init__(self, balancer: "FleetBalancer", window_s: float,
                 max_rows: int):
        self._bal = balancer
        self._window_s = window_s
        self._max_rows = max(1, int(max_rows))
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        # (model_id, elems_per_row) -> [inflight_forwards,
        # window | None]; window = [t_open, jobs, rows]. Keying on
        # the row WIDTH too matters for correctness: a merged frame
        # declares one elems for all its row buffers, so requests of
        # different widths (one client's shape bug) must never share
        # a frame — each width bounces or succeeds on its own, like
        # the unmerged path
        self._st: Dict[Tuple[str, int], list] = {}
        self._closed = False
        self._flusher = threading.Thread(target=self._flush_loop,
                                         name="fleet-coalesce",
                                         daemon=True)
        self._flusher.start()

    def _cap(self) -> int:
        """Forward-slot bound per model: one outstanding super-batch
        per channel (ready replicas x channels) keeps every replica's
        pipeline fed — send of batch N+1 overlaps compute of batch N —
        while everything beyond that merges."""
        return max(1, self._bal._ready_count()
                   * max(1, self._bal.tier.channels_per_replica))

    def _split(self, st, force: bool = False) -> List[List[_MergeJob]]:
        """Cut the queued window into up-to-free-slot groups of
        roughly equal rows (each under ``fleet_coalesce_rows``) and
        claim their slots — called under the lock. Even groups matter:
        flushing the whole queue at one replica while freed slots
        idle gave a convoy (one giant batch + trailing singles) and
        its p99 with it."""
        jobs = st[1][1]
        st[1] = None
        free = self._cap() - st[0]
        if force and free < 1:
            free = 1
        total = sum(j.nrows for j in jobs)
        target = max(1, -(-total // max(1, free)))   # ceil
        target = min(target, self._max_rows)
        groups: List[List[_MergeJob]] = [[]]
        rows = 0
        for j in jobs:
            if rows >= target and groups[-1]:
                groups.append([])
                rows = 0
            groups[-1].append(j)
            rows += j.nrows
        st[0] += len(groups)
        return groups

    def _launch(self, key: Tuple[str, int],
                groups: List[List[_MergeJob]]) -> None:
        for jobs in groups:
            self._bal._forward_merged(
                key[0], jobs,
                on_done=lambda k=key: self._forward_done(k))

    def add(self, model_id: str, arr, nrows: int, elems: int,
            timeout_ms: Optional[float]) -> Future:
        job = _MergeJob(arr, nrows, timeout_ms)
        groups: List[List[_MergeJob]] = []
        key = (model_id, elems)
        with self._lock:
            if self._closed:
                job.future.set_result((
                    "closed", "balancer shutting down", {}, "", "",
                    0, 1, -1))
                return job.future
            st = self._st.setdefault(key, [0, None])
            if st[0] == 0 and st[1] is None:
                # idle model: forward NOW — coalescing adds zero
                # latency until there is actual load to merge
                st[0] = 1
                groups = [[job]]
            else:
                if st[1] is None:
                    st[1] = [time.monotonic(), [], 0]
                    self._wake.notify_all()  # new backstop deadline
                st[1][1].append(job)
                st[1][2] += nrows
                if st[1][2] >= self._max_rows \
                        and st[0] < self._cap():
                    groups = self._split(st)   # size cap: flush early
        self._launch(key, groups)
        return job.future

    def _forward_done(self, key: Tuple[str, int]) -> None:
        """One merged forward settled (any status): free its slot and
        flush the queue behind it across the free slots. Runs on a
        channel reader thread — submission is non-blocking."""
        groups: List[List[_MergeJob]] = []
        with self._lock:
            st = self._st.get(key)
            if st is None:
                return
            st[0] -= 1
            if st[1] is not None and st[0] < self._cap():
                groups = self._split(st)
            elif st[0] <= 0 and st[1] is None:
                del self._st[key]        # idle model: drop the entry
        self._launch(key, groups)

    def _flush_loop(self) -> None:
        """The backstop: force-flush windows older than the coalesce
        window even when every slot is busy (a stalled forward must
        not become every queued request's wait)."""
        while True:
            due = []
            with self._lock:
                while not self._closed:
                    now = time.monotonic()
                    deadline = min(
                        (st[1][0] + self._window_s
                         for st in self._st.values()
                         if st[1] is not None), default=None)
                    if deadline is not None and deadline <= now:
                        break
                    self._wake.wait(
                        None if deadline is None else deadline - now)
                now = time.monotonic()
                for key in list(self._st):
                    st = self._st[key]
                    if st[1] is not None and (
                            self._closed
                            or st[1][0] + self._window_s <= now):
                        due.append((key, self._split(st, force=True)))
                drained = self._closed and all(
                    st[1] is None for st in self._st.values())
            for key, groups in due:
                self._launch(key, groups)
            if drained:
                return

    def close(self) -> None:
        """Flush-forward everything still queued (zero-drop
        shutdown), then stop the flusher."""
        with self._lock:
            self._closed = True
            self._wake.notify_all()
        self._flusher.join(timeout=30)


class _VersionStats:
    """Per-bundle-version outcome window (canary comparison)."""

    __slots__ = ("ok", "errors", "lat")

    def __init__(self):
        self.ok = 0
        self.errors = 0
        self.lat = LatencyHistogram()

    def snapshot(self) -> Dict[str, Any]:
        return {"ok": self.ok, "errors": self.errors,
                "requests": self.ok + self.errors,
                "p99_ms": round(self.lat.percentile(0.99), 3),
                "p50_ms": round(self.lat.percentile(0.50), 3)}


class FleetBalancer:
    """N replica endpoints behind the two protocol listeners.

    Build from the parsed tier config plus the raw config stream (for
    the quota grammar); ``start()`` binds listeners and the health
    poller, ``close()`` stops them. Replica registration is the
    controller's job (``add_replica`` / ``drain_replica`` /
    ``remove_replica``)."""

    # forward socket timeout: generous enough for a queued request on
    # a loaded replica, finite so a wedged replica turns into a
    # retryable transport error instead of a hung client
    FORWARD_TIMEOUT_S = 60.0
    # channel socket recv backstop: request-level failure is governed
    # by each waiter's forward window (result timeout -> retryable),
    # and a dead replica surfaces as EOF/RST — this only reclaims a
    # reader parked on a silently-blackholed connection, so it sits
    # far ABOVE any legitimate client deadline (a 120 s tripwire here
    # would break the channel, and every in-flight request with it,
    # under a declared-slow request)
    CHANNEL_IO_TIMEOUT_S = 3600.0

    def __init__(self, tier: FleetTierConfig, cfg=(), monitor=None):
        self.tier = tier
        self.balancer_id = tier.balancer_id
        self.balancer_index = tier.balancer_index
        # a share manager even at balancers=1: the single-door case is
        # bit-identical to the plain QuotaManager (pinned by test), so
        # every existing quota contract exercises the shared code path
        self.quota = QuotaShareManager(cfg,
                                       balancer_id=tier.balancer_id,
                                       balancers=tier.balancers)
        self._mon = monitor
        self._safe_emit = SafeEmitter(monitor, "cxxnet_tpu fleet")
        self._lock = threading.Lock()        # replica table
        self._reps: Dict[str, ReplicaState] = {}
        self._stats = threading.Lock()       # counters + windows
        self.counters: Dict[str, int] = {
            "requests": 0, "ok": 0, "shed": 0, "errors": 0,
            "retries": 0, "unrouted": 0}
        self._win = {"requests": 0, "ok": 0, "shed": 0, "errors": 0,
                     "forwards": 0, "forward_requests": 0,
                     "forward_rows": 0}
        self._win_lat = LatencyHistogram()
        self._win_t0 = time.monotonic()
        self._versions: Dict[str, _VersionStats] = {}
        self._pin_version: Optional[str] = None
        self._pin_fraction = 0.0
        self._pick_seq = 0
        self._pick_rr = 0
        self._inflight_reqs = 0
        # intra-tier state: peer doors (balancer_id, host, http_port)
        # and their last gossip views (demand rates for rebalancing)
        self._peers: List[Tuple[str, str, int]] = []
        self._peer_views: Dict[str, Dict[str, Any]] = {}
        self._closing = False
        self._coal: Optional[_Coalescer] = None
        if tier.coalesce_ms > 0:
            self._coal = _Coalescer(self, tier.coalesce_ms / 1e3,
                                    tier.coalesce_rows)
        self._http_server = None
        self._binary_server = None
        self._threads: List[threading.Thread] = []
        self._poll_stop = threading.Event()
        self.http_port = -1
        self.binary_port = -1

    # -- replica table ----------------------------------------------------

    def add_replica(self, replica_id: str, host: str, http_port: int,
                    binary_port: int, version: str,
                    kind: str = "baseline") -> ReplicaState:
        rep = ReplicaState(replica_id, host, http_port, binary_port,
                           version, kind)
        with self._lock:
            if replica_id in self._reps:
                raise ValueError("replica %r already registered"
                                 % replica_id)
            self._reps[replica_id] = rep
        return rep

    def remove_replica(self, replica_id: str) -> None:
        with self._lock:
            rep = self._reps.pop(replica_id, None)
        if rep is not None:
            rep.close_pool()

    def has_replica(self, replica_id: str) -> bool:
        with self._lock:
            return replica_id in self._reps

    def set_replica_draining(self, replica_id: str,
                             draining: bool) -> bool:
        """Flip the draining flag (registry-driven; an external door
        learns drains from the controller's registry writes, not a
        direct call). Returns True when the flag changed."""
        with self._lock:
            rep = self._reps.get(replica_id)
            if rep is None or rep.draining == bool(draining):
                return False
            rep.draining = bool(draining)
            return True

    # -- intra-tier peers (sharded front tier) -----------------------------

    def set_tier_peers(self, peers: List[Tuple[str, str, int]]) -> bool:
        """The OTHER doors of this tier as ``(balancer_id, host,
        http_port)`` — gossip partners and the divisor of the poll
        partition. Returns True when the set changed."""
        peers = sorted(peers)
        with self._lock:
            if peers == self._peers:
                return False
            self._peers = peers
            live = {p[0] for p in peers}
            for bid in list(self._peer_views):
                if bid not in live:
                    del self._peer_views[bid]
            return True

    def tier_peers(self) -> List[Tuple[str, str, int]]:
        with self._lock:
            return list(self._peers)

    def drain_replica(self, replica_id: str,
                      timeout_s: float = 30.0) -> bool:
        """Stop routing new requests to the replica, then wait for its
        in-flight forwards to finish — the zero-drop half of scale-in.
        Returns False if in-flight work remained at the timeout."""
        with self._lock:
            rep = self._reps.get(replica_id)
            if rep is None:
                return True
            rep.draining = True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if rep.inflight == 0:
                    return True
            time.sleep(0.01)
        with self._lock:
            return rep.inflight == 0

    def suspect_overdue(self, deadline_s: float) -> List[str]:
        """Replicas that have been suspect (failing polls / transport)
        for longer than ``deadline_s`` — alive-but-wedged processes
        the controller must reap, or they would hold a fleet slot
        forever while serving nothing."""
        now = time.monotonic()
        with self._lock:
            return [r.replica_id for r in self._reps.values()
                    if r.suspect and r.suspect_since
                    and now - r.suspect_since >= deadline_s]

    def replica_ids(self, kind: Optional[str] = None,
                    version: Optional[str] = None) -> List[str]:
        with self._lock:
            return [r.replica_id for r in self._reps.values()
                    if (kind is None or r.kind == kind)
                    and (version is None or r.version == version)]

    def describe_replicas(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [r.describe() for r in self._reps.values()]

    # -- canary pinning ----------------------------------------------------

    def pin_canary(self, version: str, fraction: float) -> None:
        """Route ``fraction`` of requests to replicas serving
        ``version`` (deterministic interleave, no RNG: request k goes
        canary iff floor(k*f) advanced). Also resets the per-version
        windows so the comparison covers exactly the pinned period."""
        with self._stats:
            self._versions = {}
        with self._lock:
            self._pin_version = version
            self._pin_fraction = float(fraction)
            self._pick_seq = 0

    def unpin_canary(self) -> None:
        with self._lock:
            self._pin_version = None
            self._pin_fraction = 0.0

    def set_replica_kind(self, replica_id: str, kind: str) -> None:
        """Reclassify a replica (a promoted canary joins the baseline
        pool the autoscaler manages)."""
        with self._lock:
            rep = self._reps.get(replica_id)
            if rep is not None:
                rep.kind = kind

    def version_stats(self) -> Dict[str, Dict[str, Any]]:
        with self._stats:
            return {v: s.snapshot()
                    for v, s in self._versions.items()}

    # -- the request path --------------------------------------------------

    def handle(self, model_id: str, tenant: str, rows,
               protocol: str = "http",
               timeout_ms: Optional[float] = None
               ) -> Tuple[str, Any, Dict[str, Any]]:
        """Quota -> pick replica -> forward (with idempotent retry).
        Same contract as ``FleetServer.handle`` — never raises, so
        both protocol handlers plug in unchanged."""
        t0 = time.monotonic()
        nrows = 0
        replica_id, version, retries = "", "", 0
        coalesced, channel = 1, -1
        with self._stats:
            self._inflight_reqs += 1
        try:
            if isinstance(rows, np.ndarray) \
                    and rows.dtype == np.dtype("<f4") \
                    and rows.ndim >= 1 \
                    and rows.flags["C_CONTIGUOUS"]:
                arr = rows   # binary ingress: relay the bytes as-is
            else:
                # HTTP/JSON (or odd dtypes): ONE conversion here at
                # admission; everything downstream relays the buffer
                arr = np.asarray(rows, dtype=np.float32)  # cxxlint: disable=CXL003 -- protocol decode on the network tier: client rows arrive as host bytes/JSON lists, there is no device value to keep resident
            if arr.ndim == 0:
                raise ValueError("rows must be an array, got a scalar")
            nrows = int(arr.shape[0]) if arr.ndim > 1 else 1
            try:
                self.quota.admit(tenant, nrows)
            except TenantQuotaError as e:
                self._emit("tenant_shed", tenant=tenant,
                           model=model_id, rows=nrows, rate=e.rate,
                           burst=e.burst,
                           balancer=self.balancer_id,
                           retry_after_s=round(e.retry_after_s, 3))
                raise
            if self._coal is not None:
                elems = int(arr.size // nrows) if nrows else 0
                fut = self._coal.add(model_id, arr, nrows, elems,
                                     timeout_ms)
                window = (self.FORWARD_TIMEOUT_S
                          + self.tier.coalesce_ms / 1e3 + 10.0) \
                    * (self.tier.retries + 1)
                if timeout_ms:
                    window = max(window, timeout_ms / 1e3 + 10.0)
                try:
                    (status, result, extra, replica_id, version,
                     retries, coalesced, channel) = fut.result(window)
                except FutureTimeout:
                    status, result, extra = \
                        "error", "fleet forward timed out", {}
            else:
                (status, result, extra, replica_id, version, retries,
                 channel) = self._route(model_id, tenant, arr,
                                        timeout_ms)
        except TenantQuotaError as e:
            status, result = "over_quota", str(e)
            extra = {"retry_after_s": e.retry_after_s}
        except (ValueError, TypeError) as e:
            status, result, extra = "bad_request", str(e), {}
        except Exception as e:   # a balancer bug must answer, not hang
            status, result, extra = "error", str(e), {}
        finally:
            with self._stats:
                self._inflight_reqs -= 1
        self._record(protocol, status, model_id, tenant, nrows,
                     replica_id, version, retries, t0,
                     coalesced=coalesced, channel=channel)
        return status, result, extra

    def _route(self, model_id: str, tenant: str, arr: np.ndarray,
               timeout_ms: Optional[float]):
        excluded: set = set()
        retries = 0
        last: Optional[Tuple[str, Any, str, str]] = None
        for attempt in range(self.tier.retries + 1):
            rep = self._pick(excluded)
            if rep is None:
                break
            with self._lock:
                rep.inflight += 1
            try:
                status, result, channel = self._forward(
                    rep, model_id, tenant, arr, timeout_ms)
            except ReplicaUnreachable:
                # the replica died (or its socket did) mid-request:
                # mark it suspect so new requests route around it, and
                # retry these idempotent rows elsewhere
                self._mark_suspect(rep)
                excluded.add(rep.replica_id)
                retries += 1
                continue
            finally:
                with self._lock:
                    rep.inflight -= 1
            if status == "closed" and not self._closing:
                # replica draining/shut down between pick and forward
                excluded.add(rep.replica_id)
                retries += 1
                last = (status, result, rep.replica_id, rep.version)
                continue
            if status == "busy" and attempt == 0 \
                    and self._ready_count() > 1:
                # one overloaded replica is not fleet overload: give a
                # less-loaded replica one chance before shedding
                excluded.add(rep.replica_id)
                retries += 1
                last = (status, result, rep.replica_id, rep.version)
                continue
            self._note_forward(1, int(arr.shape[0]) if arr.ndim > 1
                               else 1)
            return status, result, {}, rep.replica_id, rep.version, \
                retries, channel
        if last is not None:
            status, result, rid, ver = last
            return status, result, {}, rid, ver, retries, -1
        with self._stats:
            self.counters["unrouted"] += 1
        return ("closed", "no ready replicas", {}, "", "", retries, -1)

    def _mark_suspect(self, rep: ReplicaState) -> None:
        with self._lock:
            if not rep.suspect:
                rep.suspect = True
                rep.suspect_since = time.monotonic()

    def _note_forward(self, requests: int, rows: int) -> None:
        with self._stats:
            self._win["forwards"] += 1
            self._win["forward_requests"] += requests
            self._win["forward_rows"] += rows

    def _ready_count(self) -> int:
        with self._lock:
            return sum(1 for r in self._reps.values()
                       if r.ready and not r.draining
                       and not r.suspect)

    def _pick(self, excluded: set) -> Optional[ReplicaState]:
        """Least-loaded ready replica (in-flight forwards + last
        polled queue depth), honoring the canary pin."""
        with self._lock:
            cands = [r for r in self._reps.values()
                     if r.ready and not r.draining and not r.suspect
                     and r.replica_id not in excluded]
            if not cands:
                # desperation pass: every healthy replica is excluded
                # or suspect — a suspect replica may have recovered,
                # and answering beats returning "no replicas"
                cands = [r for r in self._reps.values()
                         if r.ready and not r.draining
                         and r.replica_id not in excluded]
            if not cands:
                return None
            if self._pin_version is not None:
                self._pick_seq += 1
                f = self._pin_fraction
                want_canary = (math.floor(self._pick_seq * f)
                               > math.floor((self._pick_seq - 1) * f))
                pool = [r for r in cands
                        if (r.version == self._pin_version)
                        == want_canary]
                if pool:
                    cands = pool
            # rotating tiebreak: breaking load ties by replica_id
            # biased ALL cold-start and equal-load traffic onto the
            # lexicographically-first replica — rotate instead, so an
            # idle fleet spreads evenly (pinned by test)
            load = min(r.inflight + r.health.get("queue_rows", 0)
                       for r in cands)
            ties = [r for r in cands
                    if r.inflight + r.health.get("queue_rows", 0)
                    == load]
            self._pick_rr += 1
            return ties[self._pick_rr % len(ties)]

    def _forward_window(self, timeout_ms: Optional[float]) -> float:
        # a client that declared a deadline LONGER than the default
        # forward timeout gets the wait window to match — otherwise
        # a legitimately slow request could never succeed through the
        # balancer and would burn duplicate device work via retries
        window = self.FORWARD_TIMEOUT_S
        if timeout_ms:
            window = max(window, timeout_ms / 1e3 + 5.0)
        return window

    def _forward(self, rep: ReplicaState, model_id: str, tenant: str,
                 arr: np.ndarray, timeout_ms: Optional[float]
                 ) -> Tuple[str, Any, int]:
        """One exchange with the replica: a pipelined submit on a
        multiplexed channel (protocol v2), or — for a v1-only replica
        or ``fleet_channels_per_replica = 0`` — a blocking round trip
        on a pooled connection. Any transport/framing failure raises
        :class:`ReplicaUnreachable`. Returns (status, result,
        channel_index); -1 = pooled."""
        window = self._forward_window(timeout_ms)
        ch = rep.channel(self.tier.channels_per_replica,
                         self.CHANNEL_IO_TIMEOUT_S)
        if ch is None:
            status, result = self._forward_pooled(
                rep, model_id, tenant, arr, timeout_ms, window)
            return status, result, -1
        buffers, nrows, elems = _row_buffers(arr)
        fut = ch.submit(model_id, tenant, buffers, nrows, elems,
                        timeout_ms or 0.0, window)
        try:
            status, result = fut.result(timeout=window)
        except ReplicaUnreachable:
            raise
        except FutureTimeout:
            raise ReplicaUnreachable(
                "replica %s did not answer within %.0fs"
                % (rep.replica_id, window))
        return status, result, ch.index

    def _forward_pooled(self, rep: ReplicaState, model_id: str,
                        tenant: str, arr: np.ndarray,
                        timeout_ms: Optional[float],
                        sock_timeout: float) -> Tuple[str, Any]:
        """The v1 fallback: one blocking binary round trip over a
        pooled persistent connection."""
        try:
            client = rep.acquire(sock_timeout)
        except OSError as e:
            raise ReplicaUnreachable(
                "replica %s unreachable: %s" % (rep.replica_id, e))
        ok = False
        try:
            client.sock.settimeout(sock_timeout)
            status, result = client.predict(
                arr, model=model_id, tenant=tenant,
                timeout_ms=timeout_ms if timeout_ms else 0.0)
            ok = True
        except OSError as e:
            raise ReplicaUnreachable(
                "replica %s failed mid-request: %s"
                % (rep.replica_id, e))
        finally:
            # release-or-discard: EVERY exit returns the connection to
            # the pool or closes it. A non-OSError escaping predict
            # (e.g. a protocol ValueError from a malformed reply) used
            # to skip both — permanently losing the pool slot AND
            # leaking the socket (pinned by test)
            if ok:
                rep.release(client)
            else:
                try:
                    client.close()
                except OSError:
                    pass  # cxxlint: disable=CXL006 -- the transport already failed; close is best-effort cleanup
        return status, result

    # -- coalesced forwarding (fleet_coalesce_ms) --------------------------

    def _forward_merged(self, model_id: str, jobs: List[_MergeJob],
                        excluded: Optional[set] = None,
                        retries: int = 0,
                        last: Optional[Tuple] = None,
                        on_done=None) -> None:
        """Forward one merged super-batch, NON-blocking: completion
        (split, retry, shed) continues on the answering channel's
        reader thread, then calls ``on_done`` exactly once (the
        coalescer's slot-free hook). Retry and busy semantics apply
        to the WHOLE merged batch — the rows are idempotent together,
        so a replica loss retries them together and a kill
        mid-traffic drops zero and duplicates zero of them (pinned by
        test)."""
        excluded = set() if excluded is None else excluded
        rep = self._pick(excluded)
        if rep is None:
            if last is not None:
                status, result, rid, ver = last
            else:
                status, result, rid, ver = \
                    "closed", "no ready replicas", "", ""
                with self._stats:
                    self.counters["unrouted"] += len(jobs)
            self._resolve_merged(jobs, status, result, {}, rid, ver,
                                 retries, -1, on_done)
            return
        nrows = sum(j.nrows for j in jobs)
        timeout_ms = max((j.timeout_ms or 0.0 for j in jobs),
                         default=0.0)
        window = self._forward_window(timeout_ms)
        with self._lock:
            rep.inflight += 1
        t_fwd = time.monotonic()

        def transport_failed(exc):
            with self._lock:
                rep.inflight -= 1
            self._mark_suspect(rep)
            excluded.add(rep.replica_id)
            if retries < self.tier.retries:
                self._forward_merged(model_id, jobs, excluded,
                                     retries + 1, last, on_done)
            else:
                with self._stats:
                    self.counters["unrouted"] += len(jobs)
                self._resolve_merged(jobs, "closed",
                                     "no ready replicas", {}, "", "",
                                     retries + 1, -1, on_done)

        try:
            # merged forwards carry tenant "" — members may belong to
            # different tenants, and quota is a FLEET-WIDE contract
            # enforced at this balancer before merging (replicas are
            # spawned quota-stripped, doc/serving.md); a replica that
            # still enforces its own per-tenant quotas must not be
            # fronted with coalescing on
            ch = rep.channel(self.tier.channels_per_replica,
                             self.CHANNEL_IO_TIMEOUT_S)
            if ch is None:
                # v1-only replica: one blocking pooled round trip with
                # the members concatenated (the rare compat path)
                merged = np.concatenate(
                    [np.ascontiguousarray(j.arr, dtype="<f4").reshape(
                        j.nrows, -1) for j in jobs])
                status, result = self._forward_pooled(
                    rep, model_id, "", merged, timeout_ms, window)
                self._merged_reply(model_id, jobs, rep, -1, status,
                                   result, excluded, retries, last,
                                   t_fwd, nrows, on_done)
                return
            buffers = []
            elems = 0
            for j in jobs:
                bufs, _, elems = _row_buffers(j.arr)
                buffers.extend(bufs)
            fut = ch.submit(model_id, "", buffers, nrows, elems,
                            timeout_ms, window, blocking=False)
        except ReplicaUnreachable as e:
            transport_failed(e)
            return
        except Exception as e:
            with self._lock:
                rep.inflight -= 1
            self._resolve_merged(jobs, "error", str(e), {},
                                 rep.replica_id, rep.version, retries,
                                 -1, on_done)
            return

        def _done(f):
            exc = f.exception()
            if exc is not None:
                transport_failed(exc)
                return
            status, result = f.result()
            self._merged_reply(model_id, jobs, rep, ch.index, status,
                               result, excluded, retries, last, t_fwd,
                               nrows, on_done)

        fut.add_done_callback(_done)

    def _merged_reply(self, model_id, jobs, rep, channel, status,
                      result, excluded, retries, last, t_fwd,
                      nrows, on_done) -> None:
        """Classify one merged forward's reply: retry (closed/busy,
        whole batch) or resolve every member."""
        with self._lock:
            rep.inflight -= 1
        if status == "closed" and not self._closing \
                and retries < self.tier.retries:
            excluded.add(rep.replica_id)
            self._forward_merged(
                model_id, jobs, excluded, retries + 1,
                (status, result, rep.replica_id, rep.version),
                on_done)
            return
        if status == "busy" and retries == 0 \
                and self._ready_count() > 1:
            excluded.add(rep.replica_id)
            self._forward_merged(
                model_id, jobs, excluded, retries + 1,
                (status, result, rep.replica_id, rep.version),
                on_done)
            return
        self._note_forward(len(jobs), nrows)
        self._emit("fleet_batch", model=model_id,
                   replica=rep.replica_id, status=status,
                   requests=len(jobs), rows=nrows, channel=channel,
                   retries=retries, balancer=self.balancer_id,
                   latency_ms=(time.monotonic() - t_fwd) * 1e3)
        self._resolve_merged(jobs, status, result, {},
                             rep.replica_id, rep.version, retries,
                             channel, on_done)

    def _resolve_merged(self, jobs, status, result, extra, rid, ver,
                        retries, channel, on_done=None) -> None:
        """Split an ok super-batch reply by row offsets; any other
        status fans out to every member unchanged. Frees the
        coalescer slot FIRST so the next queued super-batch overlaps
        with the member futures waking their waiters."""
        if on_done is not None:
            on_done()
        coalesced = len(jobs)
        if status == "ok":
            total = sum(j.nrows for j in jobs)
            # an ok reply's payload is already the decoded row array
            # (np.frombuffer view on the channel reader) — no re-copy
            out = result
            if out.shape[0] != total:
                status, result = "error", (
                    "replica answered %d rows for %d sent"
                    % (out.shape[0], total))
            else:
                offset = 0
                for j in jobs:
                    rows = out[offset:offset + j.nrows]
                    offset += j.nrows
                    if not j.future.done():
                        j.future.set_result((
                            "ok", rows, extra, rid, ver, retries,
                            coalesced, channel))
                return
        for j in jobs:
            if not j.future.done():
                j.future.set_result((status, result, extra, rid, ver,
                                     retries, coalesced, channel))

    # -- telemetry / accounting -------------------------------------------

    def _emit(self, kind: str, **fields) -> None:
        self._safe_emit(kind, **fields)

    def _record(self, protocol: str, status: str, model: str,
                tenant: str, rows: int, replica_id: str, version: str,
                retries: int, t0: float, coalesced: int = 1,
                channel: int = -1) -> None:
        latency_s = time.monotonic() - t0
        shed = status in ("busy", "over_quota")
        with self._stats:
            self.counters["requests"] += 1
            self.counters["retries"] += retries
            self._win["requests"] += 1
            if status == "ok":
                self.counters["ok"] += 1
                self._win["ok"] += 1
                self._win_lat.observe(latency_s)
            elif shed:
                self.counters["shed"] += 1
                self._win["shed"] += 1
            else:
                self.counters["errors"] += 1
                self._win["errors"] += 1
            if version:
                vs = self._versions.get(version)
                if vs is None:
                    vs = self._versions[version] = _VersionStats()
                if status == "ok":
                    vs.ok += 1
                    vs.lat.observe(latency_s)
                elif not shed:
                    vs.errors += 1
        self._emit("fleet_route", protocol=protocol, status=status,
                   model=model, tenant=tenant, rows=rows,
                   replica=replica_id, version=version,
                   retries=retries, latency_ms=latency_s * 1e3,
                   coalesced=coalesced, channel=channel,
                   balancer=self.balancer_id)

    def take_window(self) -> Dict[str, Any]:
        """Counters since the last call plus the CURRENT fleet load —
        the autoscaler's input. Swapping the window out keeps rates
        honest without unbounded history."""
        now = time.monotonic()
        with self._stats:
            w = self._win
            lat = self._win_lat
            self._win = {"requests": 0, "ok": 0, "shed": 0,
                         "errors": 0, "forwards": 0,
                         "forward_requests": 0, "forward_rows": 0}
            self._win_lat = LatencyHistogram()
            t0, self._win_t0 = self._win_t0, now
        with self._lock:
            ready = [r for r in self._reps.values()
                     if r.ready and not r.draining and not r.suspect]
            queue_rows = sum(r.health.get("queue_rows", 0)
                             for r in ready)
            max_batch = max(
                (m.get("max_batch", 0)
                 for r in ready
                 for m in r.health.get("model_health", [])),
                default=0)
            total = len(self._reps)
        return {
            "requests": w["requests"], "ok": w["ok"],
            "shed": w["shed"], "errors": w["errors"],
            "p99_ms": round(lat.percentile(0.99), 3),
            "queue_rows": queue_rows, "max_batch": max_batch,
            "ready": len(ready), "replicas": total,
            "window_s": now - t0,
            # data-path health (doc/serving.md "Fleet data path"):
            # pipelining depth across the multiplexed channels right
            # now, and how well the coalescer merged this window
            "channel_depth": sum(r.channel_depth() for r in ready),
            "forwards": w["forwards"],
            "coalesce_fill": round(
                w["forward_requests"] / w["forwards"], 3)
            if w["forwards"] else 0.0,
        }

    # -- health polling ----------------------------------------------------

    def _poll_once(self, rep: ReplicaState) -> None:
        try:
            conn = http.client.HTTPConnection(
                rep.host, rep.http_port,
                timeout=max(1.0, self.tier.health_poll_s * 4))
            try:
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                payload = json.loads(resp.read())
                ok = resp.status == 200 and payload.get("ok")
            finally:
                conn.close()
        except (OSError, ValueError):
            ok, payload = False, None
        with self._lock:
            if ok:
                rep.health = payload
                rep.health_ts = time.monotonic()
                rep.health_src = "poll"
                rep.fail_polls = 0
                rep.suspect = False
                rep.suspect_since = 0.0
            else:
                rep.fail_polls += 1
                if rep.fail_polls >= self.tier.unhealthy_after \
                        and not rep.suspect:
                    rep.suspect = True
                    rep.suspect_since = time.monotonic()

    def _poll_targets(self) -> List[ReplicaState]:
        """The replicas THIS door polls: with N doors, replica i (in
        sorted id order) belongs to door ``i % N`` — tier health costs
        one poll per replica per period, not N. A replica whose state
        has gone stale (its owner door died, or gossip is broken)
        falls back to a direct poll from everyone: correctness first,
        amplification second."""
        with self._lock:
            reps = sorted(self._reps.values(),
                          key=lambda r: r.replica_id)
            npeers = len(self._peers)
        if not npeers:
            return reps
        n = npeers + 1
        stale_after = max(2 * self.tier.gossip_s,
                          4 * self.tier.health_poll_s)
        now = time.monotonic()
        return [rep for i, rep in enumerate(reps)
                if i % n == self.balancer_index % n
                or now - rep.health_ts > stale_after]

    def _poll_loop(self) -> None:
        while not self._poll_stop.wait(self.tier.health_poll_s):
            for rep in self._poll_targets():
                self._poll_once(rep)

    # -- intra-tier gossip (sharded front tier) ----------------------------

    def view_snapshot(self) -> Dict[str, Any]:
        """``GET /fleet/view``: what this door KNOWS first-hand — the
        health of the replicas it polled itself (``age_s`` relative,
        monotonic clocks don't compare across processes) plus its own
        demand rates. Gossip-learned state is excluded so a view never
        echoes another door's data back as fresh."""
        now = time.monotonic()
        reps: Dict[str, Any] = {}
        with self._lock:
            for r in self._reps.values():
                if r.health_src != "poll" or not r.health_ts:
                    continue
                reps[r.replica_id] = {
                    "health": r.health, "suspect": r.suspect,
                    "age_s": round(now - r.health_ts, 3)}
        return {"balancer": self.balancer_id,
                "index": self.balancer_index,
                "replicas": reps,
                "demand": self.quota.demand_view(),
                "inflight": self._inflight_snapshot()}

    def merge_view(self, view: Dict[str, Any]) -> None:
        """Fold one peer's ``/fleet/view`` into the local tables:
        newer replica health wins (by age), and the peer's demand
        rates feed the next quota rebalance."""
        bid = str(view.get("balancer", ""))
        if not bid:
            return
        now = time.monotonic()
        with self._lock:
            self._peer_views[bid] = {
                "ts": now,
                "demand": {str(t): float(r) for t, r in
                           dict(view.get("demand", {})).items()}}
            for rid, info in dict(view.get("replicas", {})).items():
                rep = self._reps.get(rid)
                if rep is None:
                    continue
                ts = now - float(info.get("age_s", 0.0))
                if ts <= rep.health_ts:
                    continue          # our own information is newer
                health = info.get("health")
                if health:
                    rep.health = dict(health)
                rep.health_ts = ts
                rep.health_src = "gossip"
                suspect = bool(info.get("suspect", False))
                if suspect and not rep.suspect:
                    rep.suspect = True
                    rep.suspect_since = now
                elif not suspect and rep.suspect:
                    rep.suspect = False
                    rep.suspect_since = 0.0
                    rep.fail_polls = 0

    def _fetch_peer_view(self, host: str, port: int
                         ) -> Optional[Dict[str, Any]]:
        try:
            conn = http.client.HTTPConnection(
                host, port, timeout=max(1.0, self.tier.gossip_s * 4))
            try:
                conn.request("GET", "/fleet/view")
                resp = conn.getresponse()
                if resp.status != 200:
                    return None
                return json.loads(resp.read())
            finally:
                conn.close()
        except (OSError, ValueError):
            return None

    def _gossip_loop(self) -> None:
        next_rebalance = time.monotonic() \
            + self.tier.quota_rebalance_s
        while not self._poll_stop.wait(self.tier.gossip_s):
            for bid, host, port in self.tier_peers():
                view = self._fetch_peer_view(host, port)
                if view is not None:
                    self.merge_view(view)
            if time.monotonic() >= next_rebalance:
                self._rebalance_quota()
                next_rebalance = time.monotonic() \
                    + self.tier.quota_rebalance_s

    def _rebalance_quota(self) -> None:
        """Close this door's demand window and recompute its share
        fractions from the merged per-door demand views."""
        views = {self.balancer_id: self.quota.sample_demand()}
        with self._lock:
            for bid, pv in self._peer_views.items():
                views[bid] = dict(pv.get("demand", {}))
        changed = self.quota.rebalance(views)
        if changed:
            self._emit(
                "quota_rebalance", balancer=self.balancer_id,
                tenants=len(changed),
                window_s=round(self.tier.quota_rebalance_s, 3),
                shares={t: round(f, 4) for t, f in changed.items()})

    # -- own health / status ----------------------------------------------

    def _inflight_snapshot(self) -> int:
        with self._stats:
            return self._inflight_reqs

    def health_snapshot(self) -> Dict[str, Any]:
        with self._stats:
            c = dict(self.counters)
            inflight = self._inflight_reqs
        reps = self.describe_replicas()
        ready = sum(1 for r in reps
                    if r["ready"] and not r["draining"]
                    and not r["suspect"])
        with self._lock:
            pin = {"version": self._pin_version,
                   "fraction": self._pin_fraction} \
                if self._pin_version else None
            npeers = len(self._peers)
            rep_states = list(self._reps.values())
        chan_depth = sum(r.channel_depth() for r in rep_states)
        return {"ok": ready > 0, "tier": "balancer",
                "balancer": self.balancer_id,
                "balancers": npeers + 1,
                "ready": ready, "replicas": reps,
                "requests": c["requests"], "shed": c["shed"],
                "errors": c["errors"], "retries": c["retries"],
                "canary": pin,
                # self-report: this door's OWN load, uniform with the
                # replica tier's /healthz so serve_bench and the
                # controller read both tiers the same way
                "inflight": inflight,
                "channel_depth": chan_depth,
                "quota_shares": self.quota.share_snapshot(),
                "queue_rows": sum(r["queue_rows"] for r in reps),
                "resident_bytes": sum(r["resident_bytes"]
                                      for r in reps)}

    def models_snapshot(self) -> Dict[str, Any]:
        """``GET /v1/models`` at the balancer: the model table proxied
        from one ready replica (they all serve the same contract),
        annotated with the per-version replica split."""
        with self._lock:
            cands = [r for r in self._reps.values()
                     if r.ready and not r.suspect]
        models: List[Dict[str, Any]] = []
        for rep in cands:
            try:
                conn = http.client.HTTPConnection(
                    rep.host, rep.http_port, timeout=5.0)
                try:
                    conn.request("GET", "/v1/models")
                    resp = conn.getresponse()
                    if resp.status == 200:
                        models = json.loads(resp.read())["models"]
                        break
                finally:
                    conn.close()
            except (OSError, ValueError):
                continue          # a dead replica: try the next one
        versions: Dict[str, int] = {}
        with self._lock:
            for r in self._reps.values():
                versions[r.version] = versions.get(r.version, 0) + 1
        return {"models": models, "replica_versions": versions}

    # -- listeners ---------------------------------------------------------

    def start(self) -> None:
        t = self.tier
        if t.http_port >= 0:
            self._http_server = _FleetHTTPServer(
                (t.host, t.http_port), _BalancerHttpHandler, self)
            self.http_port = self._http_server.server_address[1]
            th = threading.Thread(
                target=self._http_server.serve_forever,
                name="fleet-http", daemon=True)
            th.start()
            self._threads.append(th)
        if t.binary_port >= 0:
            self._binary_server = _FleetBinaryServer(
                (t.host, t.binary_port), _BinaryHandler, self)
            self.binary_port = self._binary_server.server_address[1]
            th = threading.Thread(
                target=self._binary_server.serve_forever,
                name="fleet-binary", daemon=True)
            th.start()
            self._threads.append(th)
        poller = threading.Thread(target=self._poll_loop,
                                  name="fleet-health", daemon=True)
        poller.start()
        self._threads.append(poller)
        if t.balancers > 1:
            gossiper = threading.Thread(target=self._gossip_loop,
                                        name="fleet-gossip",
                                        daemon=True)
            gossiper.start()
            self._threads.append(gossiper)

    def close(self) -> Dict[str, Any]:
        self._closing = True
        if self._coal is not None:
            self._coal.close()   # flush-forward anything windowed
        self._poll_stop.set()
        for srv in (self._http_server, self._binary_server):
            if srv is not None:
                srv.shutdown()
                srv.server_close()
        for th in self._threads:
            th.join(timeout=30)
        with self._lock:
            reps = list(self._reps.values())
            self._reps = {}
        for rep in reps:
            rep.close_pool()
        with self._stats:
            return dict(self.counters)


# -- balancer HTTP protocol ------------------------------------------------
#
# Reuses the fleet front end's JSON plumbing (_send_json, keep-alive,
# no access log); only the introspection payloads differ — requests go
# through FleetBalancer.handle, which shares FleetServer.handle's
# contract, so the POST body/reply grammar is identical on purpose.


class _BalancerHttpHandler(_HttpHandler):

    def do_GET(self):
        bal = self.server.fleet
        if self.path == "/healthz":
            self._send_json(200, bal.health_snapshot())
        elif self.path == "/v1/models":
            self._send_json(200, bal.models_snapshot())
        elif self.path == "/fleet/view":
            # intra-tier gossip: peers fetch this door's first-hand
            # replica health + demand rates (non-destructive)
            self._send_json(200, bal.view_snapshot())
        elif self.path == "/fleet/window":
            # DESTRUCTIVE window read for the controller's autoscale
            # aggregation — one caller per door, by contract
            self._send_json(200, bal.take_window())
        else:
            self._send_json(404, {"error": "not_found",
                                  "message": "unknown path %r"
                                  % self.path})

    def do_POST(self):
        bal = self.server.fleet
        if self.path != "/v1/predict":
            self._send_json(404, {"error": "not_found",
                                  "message": "POST /v1/predict"})
            return
        t0 = time.monotonic()
        try:
            length = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(length) or b"{}")
            model = str(req.get("model", ""))
            tenant = str(req.get("tenant", ""))
            timeout_ms = req.get("timeout_ms")
            rows = req["rows"]
        except (ValueError, KeyError, TypeError) as e:
            bal._record("http", "bad_request", "", "", 0, "", "", 0,
                        t0, coalesced=0, channel=-1)
            self._send_json(400, {"error": "bad_request",
                                  "message": "body must be JSON with "
                                  "'rows': %s" % e})
            return
        status, result, extra = bal.handle(
            model, tenant, rows, protocol="http",
            timeout_ms=timeout_ms)
        code = HTTP_STATUS[status]
        if status == "ok":
            flat = np.asarray(result)
            self._send_json(code, {
                "model": model,
                "rows": int(flat.shape[0]),
                "result": flat.reshape(flat.shape[0], -1).tolist()})
            return
        headers = {}
        if status in ("busy", "over_quota"):
            headers["Retry-After"] = "%d" % max(
                1, int(extra.get("retry_after_s", 1) + 0.999))
        self._send_json(code, dict(
            {"error": status, "message": result}, **extra),
            headers=headers)

"""Distributed tenant quotas: fleet rate split into per-door shares.

With N balancer processes fronting one fleet, a single in-process
:class:`~cxxnet_tpu.serve.quota.QuotaManager` would multiply every
tenant's contract by N. Instead each door runs a
:class:`QuotaShareManager` enforcing a *fraction* of the fleet policy,
and the fractions rebalance periodically toward observed per-door
demand: a tenant bursting through one door borrows unused share from
idle doors, while the sum of shares never exceeds 1.

Invariants (property-tested in tests/test_fleet_front_tier.py):

- **Never over fleet rate by more than one rebalance window.** With
  consistent demand views the per-tenant share fractions sum to
  exactly 1, so the summed refill rates equal the fleet rate; burst
  capacity is split the same way. Views are exchanged over gossip, so
  doors transiently disagree — and the dangerous disagreement is
  everyone raising at once (a fleet-wide demand ramp is seen
  own-fresh, peers-stale at every door). Hence the asymmetric rule:
  share *cuts* apply immediately, share *raises* are deferred one
  rebalance round — a door may only grow past its applied share after
  its demand has had a full round to reach the peers cutting theirs.
  A demand shift can then over-admit only within the staleness of one
  gossip/rebalance window — after which shares have converged again.
- **A single-door fleet is bit-identical to ``QuotaManager``.** At
  ``balancers=1`` the share fraction is exactly ``1.0``; bucket
  parameters are ``rate * 1.0`` / ``burst * 1.0`` (IEEE-exact), and
  rebalancing is a no-op (``reconfigure`` returns before touching
  bucket state when parameters are unchanged).

Share formula (:func:`compute_shares`): a floor of
``floor_total / n`` per door (so an idle door keeps a trickle for
newly arriving traffic and never deadlocks a tenant), the remainder
proportional to each door's observed demand rate. Deterministic: every
door computes the same fractions from the same merged views.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence, Tuple

from ..serve.quota import QuotaManager, TokenBucket

# fraction of the fleet rate reserved as a uniform floor across doors;
# the other 90% follows demand
FLOOR_TOTAL = 0.1


def compute_shares(demand: Dict[str, float], balancers: int,
                   floor_total: float = FLOOR_TOTAL
                   ) -> Dict[str, float]:
    """Per-door share fractions for one tenant from per-door demand
    rates (rows/s). ``balancers`` is the configured tier width — it
    sets the floor even when some doors' views are missing (a missing
    door keeps enforcing its last-known share locally, so handing its
    slice to others could transiently exceed the fleet rate).

    Guarantees: fractions over the doors present sum to <= 1 (== 1
    when all ``balancers`` doors are present), every present door gets
    >= ``floor_total / balancers``, and ``balancers == 1`` returns
    exactly 1.0."""
    ids = sorted(demand)
    if balancers <= 1:
        return {b: 1.0 for b in ids}
    f0 = floor_total / balancers
    total = sum(max(0.0, r) for r in demand.values())
    if total <= 0.0:
        return {b: 1.0 / balancers for b in ids}
    scale = 1.0 - f0 * balancers
    return {b: f0 + scale * max(0.0, demand[b]) / total for b in ids}


class QuotaShareManager(QuotaManager):
    """A :class:`QuotaManager` whose buckets enforce this door's share
    of the fleet policy.

    Demand accounting rides :meth:`admit` (requested rows, admitted or
    shed — shed demand is exactly the signal that this door needs more
    share). :meth:`sample_demand` converts the window to rates for the
    gossip view; :meth:`rebalance` applies merged views from every
    door and retunes live buckets in place."""

    def __init__(self, cfg: Sequence = (), balancer_id: str = "b0",
                 balancers: int = 1):
        super().__init__(cfg)
        self.balancer_id = balancer_id
        self.balancers = max(1, int(balancers))
        self._fracs: Dict[str, float] = {}
        # raw computed fracs from the previous rebalance round: the
        # cap on this round's raises (cuts bypass it)
        self._computed: Dict[str, float] = {}
        self._demand: Dict[str, float] = {}
        self._demand_t0 = time.monotonic()
        self._demand_rates: Dict[str, float] = {}
        self.rebalances = 0

    # -- share math -------------------------------------------------------

    def _frac_for(self, tenant: str) -> float:
        return self._fracs.get(tenant, 1.0 / self.balancers)

    @staticmethod
    def _scaled_burst(burst: float, frac: float) -> float:
        # a door's burst slice must still admit a minimal request, or
        # a tenant could be starved forever at a near-floor share
        return max(burst * frac, min(burst, 1.0))

    def _bucket_for(self, tenant: str) -> Optional[TokenBucket]:
        policy = self.policy_for(tenant)
        if policy is None:
            return None
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                rate, burst = policy
                frac = self._frac_for(tenant)
                b = TokenBucket(rate * frac,
                                self._scaled_burst(burst, frac))
                self._buckets[tenant] = b
            return b

    # -- demand accounting ------------------------------------------------

    def admit(self, tenant: str, rows: int) -> None:
        with self._lock:
            self._demand[tenant] = \
                self._demand.get(tenant, 0.0) + float(rows)
        super().admit(tenant, rows)

    def sample_demand(self) -> Dict[str, float]:
        """Close the demand window: per-tenant requested rows/s since
        the previous sample. The result is also cached for
        :meth:`demand_view` (the gossip endpoint must be
        non-destructive — N-1 peers fetch it per period)."""
        now = time.monotonic()
        with self._lock:
            window, self._demand = self._demand, {}
            t0, self._demand_t0 = self._demand_t0, now
            dt = max(1e-6, now - t0)
            self._demand_rates = \
                {t: r / dt for t, r in window.items()}
            return dict(self._demand_rates)

    def demand_view(self) -> Dict[str, float]:
        """Last sampled demand rates (non-destructive)."""
        with self._lock:
            return dict(self._demand_rates)

    # -- rebalance --------------------------------------------------------

    def rebalance(self, views: Dict[str, Dict[str, float]]
                  ) -> Dict[str, float]:
        """Recompute this door's share per tenant from the merged
        per-door demand views ``{balancer_id: {tenant: rows/s}}``
        (must include this door's own view) and retune live buckets.
        Returns the changed ``{tenant: frac}``. Pure share math is
        :func:`compute_shares` — deterministic, so every door derives
        consistent fractions from consistent views.

        Raises are deferred one round (see the module invariant): a
        computed frac above the applied one takes effect only if the
        previous round computed at least as much — by then this
        door's demand has been gossiped and the doors losing share
        have already cut (cuts apply immediately)."""
        tenants = set()
        for view in views.values():
            tenants.update(view)
        with self._lock:
            tenants.update(self._buckets)
            tenants.update(self._fracs)
        changed: Dict[str, float] = {}
        for tenant in sorted(tenants):
            demand = {b: float(views[b].get(tenant, 0.0))
                      for b in views}
            demand.setdefault(self.balancer_id, 0.0)
            fracs = compute_shares(demand, self.balancers)
            computed = fracs.get(self.balancer_id,
                                 1.0 / self.balancers)
            with self._lock:
                prev = self._frac_for(tenant)
                if computed > prev:
                    cap = self._computed.get(tenant, prev)
                    frac = max(prev, min(computed, cap))
                else:
                    frac = computed
                self._computed[tenant] = computed
                self._fracs[tenant] = frac
                bucket = self._buckets.get(tenant)
            if frac != prev:
                changed[tenant] = frac
            policy = self.policy_for(tenant)
            if bucket is not None and policy is not None:
                rate, burst = policy
                bucket.reconfigure(rate * frac,
                                   self._scaled_burst(burst, frac))
        self.rebalances += 1
        return changed

    def share_snapshot(self) -> Dict[str, object]:
        """For /healthz: the door's current share fractions."""
        with self._lock:
            fracs = {t: round(f, 4)
                     for t, f in sorted(self._fracs.items())}
        return {"balancers": self.balancers,
                "fracs": fracs, "rebalances": self.rebalances}

"""Placement: launchers and the fleet endpoint registry.

Two abstractions move the fleet off "one box, hardcoded
``127.0.0.1``":

- :class:`Launcher` — how a fleet member process is started. The
  controller composes the SAME CLI command either way
  (``python -m cxxnet_tpu.main <conf> task=... key=val ...``); the
  launcher decides where it runs. :class:`LocalLauncher` is
  ``subprocess.Popen`` on this host (the only launcher this container
  can exercise); :class:`SshLauncher` wraps the identical argv in
  ``ssh <host>`` — the command contract is already remote-safe because
  discovery happens through files/ports, not pipes.

- :class:`EndpointRegistry` — one JSON file naming every fleet member
  (replicas AND balancers): id, role, host, ports, version, kind,
  draining. It generalizes the per-process ``*.ports.json`` port files:
  the controller is the single writer; balancer processes watch it
  (mtime) to learn replicas and tier peers; clients read it to get the
  balancer endpoint list for failover. Writes are atomic
  (tmp + ``os.replace``), same discipline as
  ``FleetServer._write_port_file``.

``task = fleet_balancer`` (main.py) is the spawn target for extra
front doors; :class:`BalancerManager` starts them with the same
port-file handshake replicas use.
"""

from __future__ import annotations

import json
import os
import shlex
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

from .config import FleetTierConfig


class PlacementError(RuntimeError):
    """A launcher cannot start processes where it was asked to."""


def write_endpoint_file(path: str, payload: Dict[str, object]) -> None:
    """Atomically commit a small JSON discovery file: readers see the
    old content or the new content, never a torn write."""
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump(payload, f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


# -- launchers ------------------------------------------------------------


class Launcher:
    """How fleet member processes start. ``launch`` returns a
    ``subprocess.Popen``-compatible handle (``pid``, ``poll``,
    ``terminate``, ``kill``, ``wait``); ``host`` is the address the
    spawned process is reachable at (its listeners bind there and the
    balancer/clients connect there)."""

    kind = "abstract"

    def host(self) -> str:
        raise NotImplementedError

    def launch(self, argv: Sequence[str],
               log_path: str) -> subprocess.Popen:
        raise NotImplementedError


class LocalLauncher(Launcher):
    """Spawn on this host via ``subprocess.Popen``, stdout+stderr to a
    log file, PYTHONPATH pinned to this checkout so the child imports
    the same cxxnet_tpu (not a shadowing site-packages install)."""

    kind = "local"

    def host(self) -> str:
        return "127.0.0.1"

    def launch(self, argv: Sequence[str],
               log_path: str) -> subprocess.Popen:
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + \
            env.get("PYTHONPATH", "")
        with open(log_path, "ab") as logf:
            return subprocess.Popen(list(argv), stdout=logf,
                                    stderr=subprocess.STDOUT, env=env)


class SshLauncher(Launcher):
    """Cross-machine stub: the same CLI argv wrapped in ``ssh <host>``.

    The command contract is already machine-spread-safe — the child
    publishes its ports through a file on a path the controller can
    read (a shared filesystem in a real deployment) and serves on the
    host ``host()`` returns. This container has no second machine and
    no sshd, so ``launch`` raises :class:`PlacementError`; ``command``
    is the tested contract a remote deployment fills in.
    """

    kind = "ssh"

    def __init__(self, hosts: Sequence[str]):
        if not hosts:
            raise ValueError("ssh launcher needs fleet_hosts")
        self.hosts = list(hosts)
        self._next = 0

    def host(self) -> str:
        # round-robin placement over the host list; the host is chosen
        # at launch time and the same host is reported for discovery
        return self.hosts[self._next % len(self.hosts)]

    def command(self, argv: Sequence[str]) -> List[str]:
        target = self.host()
        return ["ssh", "-o", "BatchMode=yes", target,
                " ".join(shlex.quote(a) for a in argv)]

    def launch(self, argv: Sequence[str],
               log_path: str) -> subprocess.Popen:
        raise PlacementError(
            "ssh launcher is a placement stub in this build: would "
            "run %r" % (self.command(argv),))


def make_launcher(tier: FleetTierConfig) -> Launcher:
    """The launcher ``fleet_launcher`` names (default local)."""
    if tier.launcher == "ssh":
        return SshLauncher(tier.hosts)
    return LocalLauncher()


# -- endpoint registry ----------------------------------------------------


def endpoint_entry(member_id: str, role: str, host: str,
                   http_port: int, binary_port: int,
                   version: str = "", kind: str = "",
                   pid: int = 0,
                   draining: bool = False) -> Dict[str, object]:
    """One registry row. ``role`` is ``replica`` or ``balancer``."""
    return {"id": member_id, "role": role, "host": host,
            "http_port": int(http_port),
            "binary_port": int(binary_port),
            "version": version, "kind": kind, "pid": int(pid),
            "draining": bool(draining)}


class EndpointRegistry:
    """The fleet's shared discovery file.

    Single-writer (the controller — or the bench harness standing in
    for it), many readers. Readers cache on mtime so the balancer's
    sync loop costs a ``stat`` per poll, not a parse."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._cache: Dict[str, Dict[str, object]] = {}
        self._mtime: Optional[float] = None
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)

    def write(self, entries: Sequence[Dict[str, object]]) -> None:
        """Replace the full endpoint set."""
        with self._lock:
            self._cache = {str(e["id"]): dict(e) for e in entries}
            self._commit()

    def upsert(self, entry: Dict[str, object]) -> None:
        with self._lock:
            self._load_locked()
            self._cache[str(entry["id"])] = dict(entry)
            self._commit()

    def remove(self, member_id: str) -> None:
        with self._lock:
            self._load_locked()
            self._cache.pop(member_id, None)
            self._commit()

    def set_draining(self, member_id: str,
                     draining: bool = True) -> None:
        with self._lock:
            self._load_locked()
            e = self._cache.get(member_id)
            if e is not None:
                e["draining"] = bool(draining)
                self._commit()

    def _commit(self) -> None:
        write_endpoint_file(
            self.path, {"v": 1, "endpoints": self._cache})
        try:
            self._mtime = os.stat(self.path).st_mtime
        except OSError:
            self._mtime = None

    def _load_locked(self) -> None:
        try:
            mtime = os.stat(self.path).st_mtime
        except OSError:
            self._cache = {}
            self._mtime = None
            return
        if mtime == self._mtime:
            return
        try:
            with open(self.path) as f:
                doc = json.load(f)
            self._cache = {str(k): dict(v) for k, v in
                           dict(doc.get("endpoints", {})).items()}
            self._mtime = mtime
        except (OSError, ValueError):
            pass  # cxxlint: disable=CXL006 -- torn concurrent replace or unreadable file: keeping the previous view and retrying at the next poll IS the recovery

    def changed(self) -> bool:
        """Cheap mtime probe — has the file moved since last read?"""
        try:
            return os.stat(self.path).st_mtime != self._mtime
        except OSError:
            return self._mtime is not None

    def read(self) -> Dict[str, Dict[str, object]]:
        with self._lock:
            self._load_locked()
            return {k: dict(v) for k, v in self._cache.items()}

    def endpoints(self, role: str = "") -> List[Dict[str, object]]:
        """Entries, optionally filtered by role, sorted by id."""
        table = self.read()
        rows = [e for e in table.values()
                if not role or e.get("role") == role]
        return sorted(rows, key=lambda e: str(e["id"]))


def sync_from_registry(balancer, registry: EndpointRegistry,
                       self_id: str) -> bool:
    """Apply the registry's current view to a live balancer: add new
    replicas, drop removed ones, propagate draining flags, and refresh
    the tier peer list (every balancer entry except ``self_id``).
    Returns True when anything changed. Shared by the
    ``task=fleet_balancer`` runtime and the in-process test fakes so
    both run the same reconciliation."""
    if not registry.changed():
        return False
    table = registry.read()
    changed = False
    seen = set()
    for e in table.values():
        if e.get("role") != "replica":
            continue
        rid = str(e["id"])
        seen.add(rid)
        if not balancer.has_replica(rid):
            balancer.add_replica(
                rid, str(e.get("host", "127.0.0.1")),
                int(e.get("http_port", 0)),
                int(e.get("binary_port", 0)),
                version=str(e.get("version", "")),
                kind=str(e.get("kind", "")) or "baseline")
            changed = True
        if balancer.set_replica_draining(
                rid, bool(e.get("draining", False))):
            changed = True
    for rid in balancer.replica_ids():
        if rid not in seen:
            balancer.remove_replica(rid)
            changed = True
    peers = [(str(e["id"]), str(e.get("host", "127.0.0.1")),
              int(e.get("http_port", 0)))
             for e in table.values()
             if e.get("role") == "balancer" and str(e["id"]) != self_id]
    if balancer.set_tier_peers(peers):
        changed = True
    return changed


# -- balancer process manager ---------------------------------------------


class BalancerProcess:
    """One spawned front-door process: handle + published ports."""

    def __init__(self, balancer_id: str, index: int,
                 proc: subprocess.Popen, host: str,
                 port_file: str, log_path: str):
        self.balancer_id = balancer_id
        self.index = index
        self.proc = proc
        self.host = host
        self.port_file = port_file
        self.log_path = log_path
        self.http_port = 0
        self.binary_port = 0
        self.stopped = False

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.poll() is None


class BalancerManager:
    """Spawn/stop extra balancer processes (``task=fleet_balancer``)
    with the replica spawn discipline: CLI + overrides, port-file
    handshake, log capture, SpawnError with the log tail."""

    def __init__(self, conf_path: str, tier: FleetTierConfig,
                 extra_overrides: Sequence[str] = (),
                 launcher: Optional[Launcher] = None,
                 monitor_dir: str = ""):
        self.conf_path = conf_path
        self.tier = tier
        self.extra_overrides = list(extra_overrides)
        self.launcher = launcher or make_launcher(tier)
        self.monitor_dir = monitor_dir
        self._lock = threading.Lock()
        self._balancers: Dict[str, BalancerProcess] = {}
        self._closed = False
        os.makedirs(tier.fleet_dir, exist_ok=True)

    def _command(self, bid: str, index: int,
                 port_file: str) -> List[str]:
        overrides = [
            "task=fleet_balancer",
            "fleet_balancer_id=%s" % bid,
            "fleet_balancer_index=%d" % index,
            "fleet_balancers=%d" % self.tier.balancers,
            "fleet_http_port=0",
            "fleet_binary_port=0",
            "fleet_host=%s" % self.launcher.host(),
            "fleet_port_file=%s" % port_file,
            "fleet_registry=%s" % self.tier.registry_path,
            "fleet_duration_s=0",
            # the spawning conf may itself say task=fleet with replica
            # counts — the balancer task ignores those, but the canary
            # keys must not re-arm inside a door process
            "canary_source=",
        ]
        if self.monitor_dir:
            overrides += [
                "monitor=jsonl",
                "monitor_path=%s" % os.path.join(
                    self.monitor_dir, "%s.jsonl" % bid),
            ]
        else:
            overrides += ["monitor=none"]
        return ([sys.executable, "-m", "cxxnet_tpu.main",
                 self.conf_path] + self.extra_overrides + overrides)

    def spawn(self, index: int) -> BalancerProcess:
        """Start door ``b<index>`` and block until it publishes its
        ports or dies; raises SpawnError with the log tail."""
        from .replica import SpawnError, _log_tail
        bid = "b%d" % index
        port_file = os.path.join(self.tier.fleet_dir,
                                 "%s.ports.json" % bid)
        log_path = os.path.join(self.tier.fleet_dir, "%s.log" % bid)
        if os.path.exists(port_file):
            os.remove(port_file)
        proc = self.launcher.launch(
            self._command(bid, index, port_file), log_path)
        bal = BalancerProcess(bid, index, proc, self.launcher.host(),
                              port_file, log_path)
        deadline = time.monotonic() + self.tier.spawn_timeout_s
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise SpawnError(
                    "balancer %s (pid %d) exited with code %s before "
                    "publishing ports; log tail:\n%s"
                    % (bid, proc.pid, proc.returncode,
                       _log_tail(log_path)))
            if os.path.exists(port_file):
                with open(port_file) as f:
                    ports = json.load(f)
                bal.http_port = int(ports["http_port"])
                bal.binary_port = int(ports["binary_port"])
                with self._lock:
                    if self._closed:
                        closed = True
                    else:
                        closed = False
                        self._balancers[bid] = bal
                if closed:
                    proc.terminate()
                    proc.wait()
                    raise SpawnError(
                        "balancer %s came up after the manager "
                        "closed; stopped" % bid)
                return bal
            time.sleep(0.05)
        proc.kill()
        proc.wait()
        raise SpawnError(
            "balancer %s (pid %d) timed out after %.0fs waiting for "
            "ports; log tail:\n%s"
            % (bid, proc.pid, self.tier.spawn_timeout_s,
               _log_tail(log_path)))

    def balancers(self) -> List[BalancerProcess]:
        with self._lock:
            return sorted(self._balancers.values(),
                          key=lambda b: b.index)

    def poll_dead(self) -> List[BalancerProcess]:
        """Doors that died without the manager stopping them — removed
        from the table so the controller can deregister and respawn."""
        dead = []
        with self._lock:
            for bid in list(self._balancers):
                bal = self._balancers[bid]
                if not bal.stopped and not bal.alive():
                    dead.append(bal)
                    del self._balancers[bid]
        return dead

    def stop(self, bal: BalancerProcess,
             timeout_s: float = 30.0) -> Optional[int]:
        with self._lock:
            bal.stopped = True
            self._balancers.pop(bal.balancer_id, None)
        if bal.alive():
            bal.proc.terminate()
            try:
                bal.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                bal.proc.kill()
                bal.proc.wait()
        return bal.proc.returncode

    def close(self) -> None:
        with self._lock:
            self._closed = True
        for bal in self.balancers():
            self.stop(bal)

"""Horizontal fleet: replica balancer, telemetry-driven autoscale,
canary rollout (``task = fleet``, doc/serving.md "Horizontal fleet").

The tier above the serve core that turns N shared-nothing
``serve_fleet`` replica **processes** into one elastic, self-healing
service:

- :mod:`~cxxnet_tpu.fleet.balancer` — front-of-fleet routing over
  both existing protocols: load-aware health (enriched ``/healthz``),
  idempotent retry across a replica loss (zero dropped requests),
  fleet-wide tenant quotas, canary traffic pinning;
- :mod:`~cxxnet_tpu.fleet.replica` — replica process lifecycle:
  spawn through the standard CLI, learn ephemeral ports via
  ``serve_port_file``, graceful drain/stop;
- :mod:`~cxxnet_tpu.fleet.controller` — the autoscaler: classify
  load from the balancer's telemetry window (queue depth, shed rate,
  p99 vs SLO), scale out from the same sealed bundle (near-zero cold
  start is what makes elasticity cheap), drain in at idle, self-heal
  crashed replicas;
- :mod:`~cxxnet_tpu.fleet.canary` — one-shot canary rollout: pin a
  fraction, compare per-version windows, promote or roll back with a
  schema-validated decision record;
- :mod:`~cxxnet_tpu.fleet.placement` — where processes run: the
  ``Launcher`` seam behind the spawn path (local Popen today, ssh
  with the same CLI + port-file contract tomorrow) and the
  endpoint-registry file that generalizes per-replica port files
  (doc/serving.md "Sharded front tier");
- :mod:`~cxxnet_tpu.fleet.quota_shares` — distributed tenant quotas:
  the fleet rate decomposed into per-door budget shares, rebalanced
  toward observed demand over gossip.
"""

from .balancer import (FleetBalancer, ReplicaChannel, ReplicaState,
                       ReplicaUnreachable, ReplicaV1Only)
from .canary import CanaryRollout, canary_decision
from .config import FleetTierConfig, models_spec, version_of
from .controller import (FleetController, aggregate_windows,
                         classify_load)
from .placement import (BalancerManager, BalancerProcess,
                        EndpointRegistry, Launcher, LocalLauncher,
                        PlacementError, SshLauncher, endpoint_entry,
                        make_launcher, sync_from_registry,
                        write_endpoint_file)
from .quota_shares import QuotaShareManager, compute_shares
from .replica import ReplicaManager, ReplicaProcess, SpawnError

__all__ = [
    "FleetBalancer", "ReplicaChannel", "ReplicaState",
    "ReplicaUnreachable", "ReplicaV1Only",
    "CanaryRollout", "canary_decision", "FleetTierConfig",
    "models_spec", "version_of", "FleetController",
    "aggregate_windows", "classify_load",
    "BalancerManager", "BalancerProcess", "EndpointRegistry",
    "Launcher", "LocalLauncher", "PlacementError", "SshLauncher",
    "endpoint_entry", "make_launcher", "sync_from_registry",
    "write_endpoint_file", "QuotaShareManager", "compute_shares",
    "ReplicaManager", "ReplicaProcess", "SpawnError",
]

"""Horizontal fleet: replica balancer, telemetry-driven autoscale,
canary rollout (``task = fleet``, doc/serving.md "Horizontal fleet").

The tier above the serve core that turns N shared-nothing
``serve_fleet`` replica **processes** into one elastic, self-healing
service:

- :mod:`~cxxnet_tpu.fleet.balancer` — front-of-fleet routing over
  both existing protocols: load-aware health (enriched ``/healthz``),
  idempotent retry across a replica loss (zero dropped requests),
  fleet-wide tenant quotas, canary traffic pinning;
- :mod:`~cxxnet_tpu.fleet.replica` — replica process lifecycle:
  spawn through the standard CLI, learn ephemeral ports via
  ``serve_port_file``, graceful drain/stop;
- :mod:`~cxxnet_tpu.fleet.controller` — the autoscaler: classify
  load from the balancer's telemetry window (queue depth, shed rate,
  p99 vs SLO), scale out from the same sealed bundle (near-zero cold
  start is what makes elasticity cheap), drain in at idle, self-heal
  crashed replicas;
- :mod:`~cxxnet_tpu.fleet.canary` — one-shot canary rollout: pin a
  fraction, compare per-version windows, promote or roll back with a
  schema-validated decision record.
"""

from .balancer import (FleetBalancer, ReplicaChannel, ReplicaState,
                       ReplicaUnreachable, ReplicaV1Only)
from .canary import CanaryRollout, canary_decision
from .config import FleetTierConfig, models_spec, version_of
from .controller import FleetController, classify_load
from .replica import ReplicaManager, ReplicaProcess, SpawnError

__all__ = [
    "FleetBalancer", "ReplicaChannel", "ReplicaState",
    "ReplicaUnreachable", "ReplicaV1Only",
    "CanaryRollout", "canary_decision", "FleetTierConfig",
    "models_spec", "version_of", "FleetController", "classify_load",
    "ReplicaManager", "ReplicaProcess", "SpawnError",
]

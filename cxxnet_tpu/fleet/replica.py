"""Replica processes: spawn, watch, drain, stop.

A fleet replica is a **shared-nothing OS process** running the
existing ``task = serve_fleet`` front end (``serve/frontend.py``) over
its own engines — no cross-process collectives, no shared device
state, which is exactly why scale-out works on any backend (including
the CPU backend whose jax runtime cannot run multi-process
collectives). The manager spawns replicas through the same CLI every
deployment uses::

    python -m cxxnet_tpu.main <conf> task=serve_fleet \
        serve_models=<pinned sources> serve_http_port=0 \
        serve_binary_port=0 serve_port_file=<fleet_dir>/<rid>.ports.json

and learns the ephemeral ports from the port file the replica commits
atomically after its listeners bind (``serve_port_file``). Replica
overrides pin model sources (version pins — fleet versioning is
controller-driven, so the per-replica hot-swap watcher is off), strip
tenant quotas (the balancer enforces them fleet-wide, before any
replica queue), and silence the replica monitor (the balancer's
stream is the fleet telemetry; replica accounting rides ``/healthz``).

Boot cost is why scale-out is cheap at all: replicas booting from a
sealed bundle (doc/artifacts.md) deserialize their executables instead
of compiling — PR 9's near-zero cold start is the enabling mechanism
for elastic replica counts.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

from .config import FleetTierConfig, ModelEntry, models_spec
from .placement import Launcher, LocalLauncher


class SpawnError(RuntimeError):
    """A replica process failed to come up (died or timed out before
    publishing its ports); carries the tail of the replica log."""


def _log_tail(path: str, n: int = 2000) -> str:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - n))
            return f.read().decode(errors="replace")
    except OSError:
        return "<replica log unreadable>"


class ReplicaProcess:
    """One spawned replica: the OS process plus what the balancer
    needs to route to it (host/ports) and what the controller needs to
    manage it (kind, version, model sources)."""

    def __init__(self, replica_id: str, proc: subprocess.Popen,
                 models: Sequence[ModelEntry], version: str,
                 kind: str, port_file: str, log_path: str,
                 host: str = "127.0.0.1"):
        self.replica_id = replica_id
        self.proc = proc
        self.models = list(models)
        self.version = version
        self.kind = kind                     # "baseline" | "canary"
        self.port_file = port_file
        self.log_path = log_path
        self.host = host
        self.http_port = 0
        self.binary_port = 0
        self.stopped = False                 # stopped BY the manager

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.poll() is None


class ReplicaManager:
    """Spawn/stop fleet replicas as child processes of this host.

    Thread discipline: the controller's scale thread calls
    ``spawn``/``stop``/``poll_dead`` while ``close`` may run on the
    main thread — the replica table is lock-guarded.
    """

    def __init__(self, conf_path: str, tier: FleetTierConfig,
                 extra_overrides: Sequence[str] = (),
                 launcher: Optional[Launcher] = None):
        self.conf_path = conf_path
        self.tier = tier
        # overrides every replica inherits (e.g. the CLI overrides the
        # operator passed to task=fleet, minus the fleet-only keys)
        self.extra_overrides = list(extra_overrides)
        # where replica processes run: local Popen by default; the
        # placement layer (fleet/placement.py) swaps in cross-machine
        # launchers behind the same CLI + port-file contract
        self.launcher = launcher or LocalLauncher()
        self._lock = threading.Lock()
        self._replicas: Dict[str, ReplicaProcess] = {}
        self._seq = 0
        self._closed = False
        os.makedirs(tier.fleet_dir, exist_ok=True)

    # -- spawn ------------------------------------------------------------

    def _next_id(self) -> str:
        with self._lock:
            self._seq += 1
            return "r%03d" % self._seq

    def _command(self, rid: str, models: Sequence[ModelEntry],
                 port_file: str) -> List[str]:
        overrides = [
            "task=serve_fleet",
            "serve_models=%s" % models_spec(models),
            "serve_http_port=0",
            "serve_binary_port=0",
            "serve_host=%s" % self.launcher.host(),
            "serve_port_file=%s" % port_file,
            # fleet versioning is controller-driven (canary rollout /
            # promote): the per-replica snapshot watcher must not race
            # it by swapping sources underneath the balancer's
            # version accounting
            "serve_swap_poll_s=0",
            "serve_fleet_duration_s=0",
            # quotas are enforced fleet-wide at the balancer, BEFORE
            # any replica queue — a replica-level second enforcement
            # would shed admitted traffic
            "serve_quota=",
            "serve_quota_default=",
            # the balancer's stream is the fleet telemetry; a shared
            # monitor_path across replicas would interleave corruptly
            "monitor=none",
        ]
        return ([sys.executable, "-m", "cxxnet_tpu.main",
                 self.conf_path] + self.extra_overrides + overrides)

    def spawn(self, models: Sequence[ModelEntry], version: str,
              kind: str = "baseline") -> ReplicaProcess:
        """Start one replica over ``models`` and block until it
        publishes its ports (listeners bound, engines warmed) or dies;
        raises :class:`SpawnError` with the log tail on failure."""
        rid = self._next_id()
        port_file = os.path.join(self.tier.fleet_dir,
                                 "%s.ports.json" % rid)
        log_path = os.path.join(self.tier.fleet_dir, "%s.log" % rid)
        if os.path.exists(port_file):
            os.remove(port_file)
        proc = self.launcher.launch(
            self._command(rid, models, port_file), log_path)
        rep = ReplicaProcess(rid, proc, models, version, kind,
                             port_file, log_path,
                             host=self.launcher.host())
        deadline = time.monotonic() + self.tier.spawn_timeout_s
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise SpawnError(
                    "replica %s (pid %d) exited with code %s before "
                    "publishing ports; log tail:\n%s"
                    % (rid, proc.pid, proc.returncode,
                       _log_tail(log_path)))
            if os.path.exists(port_file):
                with open(port_file) as f:
                    ports = json.load(f)
                rep.http_port = int(ports["http_port"])
                rep.binary_port = int(ports["binary_port"])
                with self._lock:
                    if self._closed:
                        # the fleet shut down while this replica was
                        # booting: registering it would leak a process
                        # nothing will ever stop
                        closed = True
                    else:
                        closed = False
                        self._replicas[rid] = rep
                if closed:
                    proc.terminate()
                    proc.wait()
                    raise SpawnError(
                        "replica %s came up after the manager closed; "
                        "stopped" % rid)
                return rep
            time.sleep(0.05)
        proc.kill()
        proc.wait()
        raise SpawnError(
            "replica %s (pid %d) timed out after %.0fs waiting for "
            "ports; log tail:\n%s"
            % (rid, proc.pid, self.tier.spawn_timeout_s,
               _log_tail(log_path)))

    # -- lifecycle --------------------------------------------------------

    def replicas(self) -> List[ReplicaProcess]:
        with self._lock:
            return list(self._replicas.values())

    def poll_dead(self) -> List[ReplicaProcess]:
        """Replicas that died WITHOUT the manager stopping them (a
        crash / OOM-kill / operator kill): removed from the table and
        returned so the controller can deroute and self-heal."""
        dead = []
        with self._lock:
            for rid in list(self._replicas):
                rep = self._replicas[rid]
                if not rep.stopped and not rep.alive():
                    dead.append(rep)
                    del self._replicas[rid]
        return dead

    def stop(self, rep: ReplicaProcess,
             timeout_s: float = 30.0) -> Optional[int]:
        """Graceful stop: SIGTERM (the replica's serve_fleet loop
        drains its engines and exits), escalate to SIGKILL after
        ``timeout_s``. Returns the exit code."""
        with self._lock:
            rep.stopped = True
            self._replicas.pop(rep.replica_id, None)
        if rep.alive():
            rep.proc.terminate()
            try:
                rep.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                rep.proc.kill()
                rep.proc.wait()
        return rep.proc.returncode

    def close(self) -> None:
        with self._lock:
            self._closed = True
        for rep in self.replicas():
            self.stop(rep)

"""Parsed configuration for the horizontal fleet tier.

One place turns the ordered ``(name, value)`` config stream into the
knobs the balancer, autoscale controller, and canary rollout share
(``task = fleet``, doc/serving.md "Horizontal fleet"). Grammar:

- ``fleet_*`` keys size and tune the tier (replica bounds, listener
  ports, health/scale cadence, load thresholds);
- ``canary_*`` keys arm a one-shot canary rollout of a new bundle
  version;
- the replicas themselves are configured by the SAME ``serve_*`` keys
  as a standalone ``task = serve_fleet`` process — the controller
  passes the config file through and appends per-replica overrides
  (ephemeral ports, port file, pinned model sources, quotas stripped).
"""

from __future__ import annotations

import os
from typing import List, Sequence, Tuple

from ..serve.frontend import FleetConfig

# one model entry: (model_id, source, bucket_override)
ModelEntry = Tuple[str, str, str]


def version_of(source: str) -> str:
    """Human-readable version label for a model source — the basename
    (``0002.model.bundle``) keeps bundle counters visible in telemetry
    and the canary decision record."""
    base = os.path.basename(str(source).rstrip("/"))
    return base or str(source)


def models_spec(entries: Sequence[ModelEntry]) -> str:
    """Re-assemble ``serve_models`` grammar from parsed entries (the
    inverse of ``FleetConfig._parse_models``): ``;``-separated when any
    entry carries a bucket ladder (ladders are comma lists), ``,``
    otherwise."""
    parts = ["%s=%s|%s" % (m, s, b) if b else "%s=%s" % (m, s)
             for m, s, b in entries]
    return ";".join(parts) if any(b for _, _, b in entries) \
        else ",".join(parts)


class FleetTierConfig:
    """Parsed ``fleet_*`` / ``canary_*`` keys (doc/serving.md
    "Horizontal fleet" has the full table)."""

    def __init__(self, cfg: Sequence):
        self.replicas = 1
        self.min_replicas = 0          # 0 -> fleet_replicas
        self.max_replicas = 0          # 0 -> max(fleet_replicas, 4)
        self.http_port = 0
        self.binary_port = 0
        self.host = "127.0.0.1"
        self.fleet_dir = "./fleet_run"
        self.source = ""
        self.health_poll_s = 0.5
        self.unhealthy_after = 2
        self.wedged_after_s = 30.0
        self.retries = 3
        self.channels_per_replica = 2
        self.coalesce_ms = 0.0
        self.coalesce_rows = 256
        self.spawn_timeout_s = 180.0
        self.scale_interval_s = 1.0
        self.scale_up_after_s = 2.0
        self.scale_down_after_s = 10.0
        self.queue_hi = 1.0
        self.queue_lo = 0.05
        self.shed_hi = 0.02
        self.slo_p99_ms = 0.0
        self.duration_s = 0.0
        self.canary_source = ""
        self.canary_model = ""
        self.canary_fraction = 0.1
        self.canary_window_s = 30.0
        self.canary_min_requests = 50
        self.canary_max_error_rate = 0.02
        self.canary_p99_ratio = 1.5
        self.canary_out = ""
        self.balancers = 1
        self.balancer_id = ""
        self.balancer_index = 0
        self.gossip_s = 0.5
        self.quota_rebalance_s = 2.0
        self.launcher = "local"
        self.hosts: List[str] = []
        self.registry = ""
        self.port_file = ""
        models_val = ""
        model_dir, model_in = "", ""
        for name, val in cfg:
            if name == "fleet_balancers":
                self.balancers = int(val)
            if name == "fleet_balancer_id":
                self.balancer_id = val
            if name == "fleet_balancer_index":
                self.balancer_index = int(val)
            if name == "fleet_gossip_s":
                self.gossip_s = float(val)
            if name == "fleet_quota_rebalance_s":
                self.quota_rebalance_s = float(val)
            if name == "fleet_launcher":
                self.launcher = val
            if name == "fleet_hosts":
                self.hosts = [h.strip() for h in val.split(",")
                              if h.strip()]
            if name == "fleet_registry":
                self.registry = val
            if name == "fleet_port_file":
                self.port_file = val
            if name == "fleet_replicas":
                self.replicas = int(val)
            if name == "fleet_min_replicas":
                self.min_replicas = int(val)
            if name == "fleet_max_replicas":
                self.max_replicas = int(val)
            if name == "fleet_http_port":
                self.http_port = int(val)
            if name == "fleet_binary_port":
                self.binary_port = int(val)
            if name == "fleet_host":
                self.host = val
            if name == "fleet_dir":
                self.fleet_dir = val
            if name == "fleet_source":
                self.source = val
            if name == "fleet_health_poll_s":
                self.health_poll_s = float(val)
            if name == "fleet_unhealthy_after":
                self.unhealthy_after = int(val)
            if name == "fleet_wedged_after_s":
                self.wedged_after_s = float(val)
            if name == "fleet_retries":
                self.retries = int(val)
            if name == "fleet_channels_per_replica":
                self.channels_per_replica = int(val)
            if name == "fleet_coalesce_ms":
                self.coalesce_ms = float(val)
            if name == "fleet_coalesce_rows":
                self.coalesce_rows = int(val)
            if name == "fleet_spawn_timeout_s":
                self.spawn_timeout_s = float(val)
            if name == "fleet_scale_interval_s":
                self.scale_interval_s = float(val)
            if name == "fleet_scale_up_after_s":
                self.scale_up_after_s = float(val)
            if name == "fleet_scale_down_after_s":
                self.scale_down_after_s = float(val)
            if name == "fleet_queue_hi":
                self.queue_hi = float(val)
            if name == "fleet_queue_lo":
                self.queue_lo = float(val)
            if name == "fleet_shed_hi":
                self.shed_hi = float(val)
            if name == "fleet_slo_p99_ms":
                self.slo_p99_ms = float(val)
            if name == "fleet_duration_s":
                self.duration_s = float(val)
            if name == "canary_source":
                self.canary_source = val
            if name == "canary_model":
                self.canary_model = val
            if name == "canary_fraction":
                self.canary_fraction = float(val)
            if name == "canary_window_s":
                self.canary_window_s = float(val)
            if name == "canary_min_requests":
                self.canary_min_requests = int(val)
            if name == "canary_max_error_rate":
                self.canary_max_error_rate = float(val)
            if name == "canary_p99_ratio":
                self.canary_p99_ratio = float(val)
            if name == "canary_out":
                self.canary_out = val
            if name == "serve_models":
                models_val = val
            if name == "model_dir":
                model_dir = val
            if name == "model_in":
                model_in = val
        if self.replicas < 1:
            raise ValueError("fleet_replicas must be >= 1")
        if self.channels_per_replica < 0:
            raise ValueError(
                "fleet_channels_per_replica must be >= 0 "
                "(0 = pooled v1 data path)")
        if self.coalesce_ms < 0:
            raise ValueError("fleet_coalesce_ms must be >= 0")
        if self.coalesce_rows < 1:
            raise ValueError("fleet_coalesce_rows must be >= 1")
        if not self.min_replicas:
            self.min_replicas = self.replicas
        if not self.max_replicas:
            self.max_replicas = max(self.replicas, 4)
        if not (self.min_replicas <= self.replicas
                <= self.max_replicas):
            raise ValueError(
                "fleet replica bounds must satisfy min (%d) <= "
                "initial (%d) <= max (%d)"
                % (self.min_replicas, self.replicas,
                   self.max_replicas))
        if not 0.0 < self.canary_fraction < 1.0:
            raise ValueError(
                "canary_fraction must be in (0, 1), got %r"
                % self.canary_fraction)
        if self.balancers < 1:
            raise ValueError("fleet_balancers must be >= 1")
        if not 0 <= self.balancer_index < self.balancers:
            raise ValueError(
                "fleet_balancer_index must be in [0, %d), got %d"
                % (self.balancers, self.balancer_index))
        if not self.balancer_id:
            self.balancer_id = "b%d" % self.balancer_index
        if self.gossip_s <= 0:
            raise ValueError("fleet_gossip_s must be > 0")
        if self.quota_rebalance_s <= 0:
            raise ValueError("fleet_quota_rebalance_s must be > 0")
        if self.launcher not in ("local", "ssh"):
            raise ValueError(
                "fleet_launcher must be local or ssh, got %r"
                % self.launcher)
        if self.launcher == "ssh" and not self.hosts:
            raise ValueError("fleet_launcher=ssh needs fleet_hosts")
        if self.balancers > 1 and self.canary_source:
            # canary pinning routes a deterministic request fraction
            # through ONE door's rollout state; a sharded front tier
            # would need tier-wide canary accounting, which is out of
            # scope for now
            raise ValueError(
                "canary_source requires fleet_balancers=1 (canary "
                "accounting is single-door)")
        if self.http_port < 0 and self.binary_port < 0:
            raise ValueError(
                "fleet balancer with both protocols disabled serves "
                "nothing — enable fleet_http_port or "
                "fleet_binary_port")
        # the model set every replica serves: an explicit serve_models
        # spec passes through verbatim; otherwise one "default" model
        # over fleet_source (falling back to the model_in / model_dir
        # the rest of the system already uses)
        if models_val:
            self.models: List[ModelEntry] = \
                FleetConfig._parse_models(models_val)
        else:
            src = self.source or model_in or model_dir
            if not src:
                raise ValueError(
                    "fleet needs a model source: serve_models, "
                    "fleet_source, model_in, or model_dir")
            self.models = [("default", src, "")]
        if not self.canary_model:
            self.canary_model = self.models[0][0]
        if self.canary_source and self.canary_model not in \
                {m for m, _, _ in self.models}:
            raise ValueError(
                "canary_model %r is not a served model id (%s)"
                % (self.canary_model,
                   ", ".join(m for m, _, _ in self.models)))

    @property
    def registry_path(self) -> str:
        """The endpoint-registry file this fleet shares (explicit
        ``fleet_registry`` or ``<fleet_dir>/endpoints.json``)."""
        return self.registry or os.path.join(self.fleet_dir,
                                             "endpoints.json")

    def models_with_source(self, source: str) -> List[ModelEntry]:
        """The model set with the canary-target model's source replaced
        — what a canary replica serves, and what the whole fleet
        serves after a promote."""
        return [(m, source if m == self.canary_model else s, b)
                for m, s, b in self.models]

    def target_version(self, entries: Sequence[ModelEntry]) -> str:
        """Version label of the canary-target model within a model
        set."""
        for m, s, _ in entries:
            if m == self.canary_model:
                return version_of(s)
        return version_of(entries[0][1])

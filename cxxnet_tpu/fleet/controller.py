"""Telemetry-driven autoscale controller + replica lifecycle.

The :class:`FleetController` owns the whole tier (``task = fleet``):
it spawns the initial replicas through the
:class:`~cxxnet_tpu.fleet.replica.ReplicaManager`, registers them with
the :class:`~cxxnet_tpu.fleet.balancer.FleetBalancer`, then runs a
scale loop that every ``fleet_scale_interval_s``:

1. **self-heals** — a replica that died (crash, OOM-kill) is derouted
   and, when the fleet is below ``fleet_min_replicas``, replaced;
2. **steps the canary rollout** when one is armed
   (``fleet/canary.py``);
3. **classifies load** from the balancer's window (queued rows vs
   fleet dispatch capacity, shed rate, p99 vs ``fleet_slo_p99_ms``)
   via the pure :func:`classify_load`, and scales out after sustained
   overload / drains one replica in after sustained idleness — the
   zero-drop order: stop routing, wait for in-flight, SIGTERM.

Scale-out is cheap because replicas boot from the same sealed bundle
(zero-compile cold start, doc/artifacts.md); device-memory honesty is
enforced where the weights land: ``serve_device_mem_budget`` passes
through to every replica, whose router refuses an over-budget model
set at boot — a spawn that would not fit fails loudly instead of
packing devices past the budget.

Every action emits a schema-validated ``fleet_scale`` record.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..monitor import SafeEmitter
from .balancer import FleetBalancer
from .canary import CanaryRollout
from .config import FleetTierConfig
from .placement import (BalancerManager, EndpointRegistry,
                        endpoint_entry)
from .replica import ReplicaManager, ReplicaProcess, SpawnError


def classify_load(stats: Dict[str, Any],
                  tier: FleetTierConfig) -> Tuple[str, str]:
    """Pure load classification of one balancer window:
    ``("overload" | "idle" | "steady", reason)``.

    - queued rows are normalized by the fleet's dispatch capacity
      (ready replicas x max_batch): a ratio above ``fleet_queue_hi``
      means the queues cannot drain at this replica count;
    - a shed (busy/over-quota at the *balancer's* busy retry limit)
      rate above ``fleet_shed_hi`` means requests are already being
      turned away;
    - with ``fleet_slo_p99_ms`` set, an ok-request p99 above the SLO
      is overload even when queues look short (slow replicas);
    - idle needs the opposite of all three AND a queue ratio under
      ``fleet_queue_lo`` — with no traffic at all, an empty queue is
      enough.
    """
    ready = max(1, int(stats.get("ready", 0)))
    cap = max(1, int(stats.get("max_batch", 0))) * ready
    qratio = float(stats.get("queue_rows", 0)) / cap
    total = int(stats.get("requests", 0))
    shed_rate = float(stats.get("shed", 0)) / total if total else 0.0
    p99 = float(stats.get("p99_ms", 0.0))
    slo = tier.slo_p99_ms
    if qratio >= tier.queue_hi:
        return "overload", ("queued rows at %.2fx fleet dispatch "
                            "capacity" % qratio)
    if total and shed_rate > tier.shed_hi:
        return "overload", ("shed rate %.3f over fleet_shed_hi %.3f"
                            % (shed_rate, tier.shed_hi))
    if slo > 0 and stats.get("ok", 0) and p99 > slo:
        return "overload", ("p99 %.1f ms over SLO %.1f ms"
                            % (p99, slo))
    if total == 0 and stats.get("queue_rows", 0) == 0:
        return "idle", "no traffic"
    if qratio <= tier.queue_lo and shed_rate == 0.0 \
            and (slo <= 0 or p99 <= 0.5 * slo):
        return "idle", ("queue ratio %.3f under fleet_queue_lo %.3f"
                        % (qratio, tier.queue_lo))
    return "steady", "within thresholds"


def aggregate_windows(windows: Sequence[Dict[str, Any]]
                      ) -> Dict[str, Any]:
    """Fold per-door balancer windows into one fleet window for
    :func:`classify_load`. Traffic counters are disjoint per door and
    SUM; replica-state gauges (queued rows, ready count, dispatch
    capacity) are each door's view of the SAME replicas and take the
    max (summing would multiply the fleet's queue by N doors); p99 is
    the worst door (conservative for the SLO rule); coalesce fill is
    forward-weighted."""
    agg: Dict[str, Any] = {
        "requests": 0, "ok": 0, "shed": 0, "errors": 0,
        "p99_ms": 0.0, "queue_rows": 0, "max_batch": 0, "ready": 0,
        "replicas": 0, "window_s": 0.0, "channel_depth": 0,
        "forwards": 0, "coalesce_fill": 0.0,
        "balancers": len(windows)}
    fill_weighted = 0.0
    for w in windows:
        for k in ("requests", "ok", "shed", "errors", "forwards",
                  "channel_depth"):
            agg[k] += int(w.get(k, 0))
        for k in ("queue_rows", "max_batch", "ready", "replicas"):
            agg[k] = max(agg[k], int(w.get(k, 0)))
        for k in ("p99_ms", "window_s"):
            agg[k] = max(agg[k], float(w.get(k, 0.0)))
        fill_weighted += float(w.get("coalesce_fill", 0.0)) \
            * int(w.get("forwards", 0))
    if agg["forwards"]:
        agg["coalesce_fill"] = round(
            fill_weighted / agg["forwards"], 3)
    return agg


class FleetController:
    """Owns balancer + replica manager + optional canary; the
    ``task = fleet`` body builds exactly one of these.

    ``manager`` is injectable so the scale/canary logic is testable
    against fake replicas (anything with the ReplicaManager surface:
    ``spawn`` / ``stop`` / ``poll_dead`` / ``replicas`` / ``close``).
    """

    def __init__(self, cfg: Sequence, conf_path: str = "",
                 monitor=None, manager=None,
                 extra_overrides: Sequence[str] = (),
                 bal_manager=None):
        self.cfg = list(cfg)
        self.tier = FleetTierConfig(self.cfg)
        self._mon = monitor
        self._safe_emit = SafeEmitter(monitor,
                                      "cxxnet_tpu fleet controller")
        self.balancer = FleetBalancer(self.tier, self.cfg,
                                      monitor=monitor)
        self.manager = manager if manager is not None else \
            ReplicaManager(conf_path, self.tier,
                           extra_overrides=extra_overrides)
        # sharded front tier (fleet_balancers > 1): this process keeps
        # door b0 in-process (canary/window reads stay direct) and
        # spawns doors b1..bN-1 through the placement layer; discovery
        # for doors and clients is the endpoint-registry file. Like
        # ``manager``, ``bal_manager`` is injectable for tests.
        self.registry: Optional[EndpointRegistry] = None
        self.bal_manager = None
        if self.tier.balancers > 1 or self.tier.registry:
            self.registry = EndpointRegistry(self.tier.registry_path)
            self.registry.write([])
        if self.tier.balancers > 1:
            self.bal_manager = bal_manager if bal_manager is not None \
                else BalancerManager(
                    conf_path, self.tier,
                    extra_overrides=extra_overrides,
                    monitor_dir=self.tier.fleet_dir
                    if monitor is not None else "")
        # the model set newly spawned baseline replicas serve; a
        # canary promote repoints this at the new version
        self._lock = threading.Lock()
        self._current_models = list(self.tier.models)
        self._reps: Dict[str, ReplicaProcess] = {}
        self.canary: Optional[CanaryRollout] = None
        if self.tier.canary_source:
            self.canary = CanaryRollout(self, self.tier,
                                        monitor=monitor)
        self._stop = threading.Event()
        self._scale_thread: Optional[threading.Thread] = None
        self._overload_since: Optional[float] = None
        self._idle_since: Optional[float] = None

    # -- replica lifecycle -------------------------------------------------

    def current_models(self):
        with self._lock:
            return list(self._current_models)

    def set_current_models(self, models) -> None:
        with self._lock:
            self._current_models = list(models)

    def current_version(self) -> str:
        return self.tier.target_version(self.current_models())

    def ready_count(self, kind: Optional[str] = None) -> int:
        return len(self.balancer.replica_ids(kind=kind))

    def spawn_replica(self, models=None, kind: str = "baseline"
                      ) -> ReplicaProcess:
        """Spawn + register one replica (blocking until it serves);
        raises :class:`~cxxnet_tpu.fleet.replica.SpawnError` upward —
        callers decide whether a failed spawn is fatal (boot) or a
        telemetry event (scale-out, canary)."""
        models = self.current_models() if models is None else models
        version = self.tier.target_version(models)
        rep = self.manager.spawn(models, version, kind=kind)
        with self._lock:
            self._reps[rep.replica_id] = rep
        host = getattr(rep, "host", "127.0.0.1")
        self.balancer.add_replica(rep.replica_id, host,
                                  rep.http_port, rep.binary_port,
                                  version, kind=kind)
        if self.registry is not None:
            self.registry.upsert(endpoint_entry(
                rep.replica_id, "replica", host, rep.http_port,
                rep.binary_port, version=version, kind=kind,
                pid=rep.pid))
        self._emit_scale("replica_ready",
                         "replica %s (pid %d) serving %s"
                         % (rep.replica_id, rep.pid, version))
        return rep

    def retire_replica(self, rep: ReplicaProcess,
                       action: str = "scale_in") -> None:
        """Zero-drop scale-in: deroute, wait for in-flight forwards,
        then graceful-stop the process (its serve_fleet loop drains
        its own queues on SIGTERM)."""
        if self.registry is not None:
            # external doors learn the drain from the registry before
            # the process goes away — same zero-drop order, tier-wide
            self.registry.set_draining(rep.replica_id, True)
        drained = self.balancer.drain_replica(rep.replica_id)
        drained = self._await_external_drain(rep.replica_id) \
            and drained
        self.balancer.remove_replica(rep.replica_id)
        if self.registry is not None:
            self.registry.remove(rep.replica_id)
        self.manager.stop(rep)
        with self._lock:
            self._reps.pop(rep.replica_id, None)
        self._emit_scale(action,
                         "replica %s retired (drained=%s)"
                         % (rep.replica_id, drained))

    def _emit(self, kind: str, **fields) -> None:
        # telemetry failure must not fail scaling; SafeEmitter owns
        # the warn-once latch
        self._safe_emit(kind, **fields)

    def _emit_scale(self, action: str, reason: str, **fields) -> None:
        if self.bal_manager is not None:
            fields.setdefault(
                "balancers", 1 + len(self.bal_manager.balancers()))
        self._emit("fleet_scale", action=action,
                   replicas=len(self.manager.replicas()),
                   ready=self.ready_count(), reason=reason,
                   **fields)

    # -- sharded front tier (fleet_balancers > 1) --------------------------

    def _register_door0(self) -> None:
        if self.registry is not None:
            self.registry.upsert(endpoint_entry(
                self.balancer.balancer_id, "balancer", self.tier.host,
                self.balancer.http_port, self.balancer.binary_port))

    def _sync_door_peers(self) -> None:
        """Point the in-process door at the external doors (external
        doors learn their peers from the registry instead)."""
        if self.bal_manager is None:
            return
        self.balancer.set_tier_peers(
            [(b.balancer_id, b.host, b.http_port)
             for b in self.bal_manager.balancers()])

    def _spawn_door(self, index: int) -> None:
        bal = self.bal_manager.spawn(index)
        if self.registry is not None:
            self.registry.upsert(endpoint_entry(
                bal.balancer_id, "balancer", bal.host, bal.http_port,
                bal.binary_port, pid=bal.pid))
        self._sync_door_peers()
        self._emit_scale("balancer_ready",
                         "balancer %s (pid %d) serving"
                         % (bal.balancer_id, bal.pid))

    def _fetch_json(self, host: str, port: int,
                    path: str) -> Optional[Dict[str, Any]]:
        try:
            conn = http.client.HTTPConnection(host, port, timeout=5.0)
            try:
                conn.request("GET", path)
                resp = conn.getresponse()
                if resp.status != 200:
                    return None
                return json.loads(resp.read())
            finally:
                conn.close()
        except (OSError, ValueError):
            return None

    def _await_external_drain(self, rid: str,
                              timeout_s: float = 30.0) -> bool:
        """Wait until every external door has SEEN the drain (its
        registry sync applied the flag, or the replica left its table)
        and has no in-flight forwards to the victim. An unreachable
        door does not block a retire — its own self-heal handles it."""
        if self.bal_manager is None:
            return True
        deadline = time.monotonic() + timeout_s
        for bal in self.bal_manager.balancers():
            while time.monotonic() < deadline:
                snap = self._fetch_json(bal.host, bal.http_port,
                                        "/healthz")
                if snap is None:
                    break
                row = next((r for r in snap.get("replicas", [])
                            if r.get("replica") == rid), None)
                if row is None or (row.get("draining")
                                   and not row.get("inflight")):
                    break
                time.sleep(0.05)
            else:
                return False
        return True

    def front_doors(self) -> List[Dict[str, Any]]:
        """Every door of the tier as ``(id, host, http, binary)``
        descriptors — b0 in-process plus the spawned doors; what the
        bench and clients iterate for failover endpoints."""
        doors = [{"id": self.balancer.balancer_id,
                  "host": self.tier.host,
                  "http_port": self.balancer.http_port,
                  "binary_port": self.balancer.binary_port}]
        if self.bal_manager is not None:
            doors += [{"id": b.balancer_id, "host": b.host,
                       "http_port": b.http_port,
                       "binary_port": b.binary_port}
                      for b in self.bal_manager.balancers()]
        return doors

    # -- startup / shutdown ------------------------------------------------

    def start(self) -> None:
        self.balancer.start()
        self._register_door0()
        for _ in range(self.tier.replicas):
            self.spawn_replica()                 # SpawnError is fatal here
        if self.bal_manager is not None:
            for i in range(1, self.tier.balancers):
                self._spawn_door(i)              # SpawnError fatal too
        if self.canary is not None:
            self.canary.arm()
        self._scale_thread = threading.Thread(
            target=self._scale_loop, name="fleet-scale", daemon=True)
        self._scale_thread.start()

    def close(self) -> Dict[str, Any]:
        self._stop.set()
        if self._scale_thread is not None:
            self._scale_thread.join(timeout=60)
        if self.bal_manager is not None:
            # doors first: their in-flight forwards drain into the
            # replicas, which are still up to answer them
            for bal in self.bal_manager.balancers():
                if self.registry is not None:
                    self.registry.remove(bal.balancer_id)
                self.bal_manager.stop(bal)
            self.bal_manager.close()
        with self._lock:
            reps = list(self._reps.values())
        for rep in reps:
            self.retire_replica(rep, action="shutdown")
        self.manager.close()
        summary = self.balancer.close()
        if self.registry is not None:
            self.registry.remove(self.balancer.balancer_id)
        if self.canary is not None:
            summary["canary"] = self.canary.state
        return summary

    # -- the scale loop ----------------------------------------------------

    def _scale_loop(self) -> None:
        while not self._stop.wait(self.tier.scale_interval_s):
            try:
                self._tick()
            except Exception as e:
                # a scaling bug must not kill the loop that also does
                # self-healing; record it and keep ticking
                self._emit_scale("tick_error", "scale tick failed: %s"
                                 % e)

    def _tick(self, stats: Optional[Dict[str, Any]] = None) -> None:
        """One controller step; ``stats`` is injectable for tests
        (defaults to draining the balancer's live window)."""
        self._reap_dead()
        if self.canary is not None:
            self.canary.step()
        if stats is None:
            stats = self._take_fleet_window()
        state, reason = classify_load(stats, self.tier)
        now = time.monotonic()
        self._overload_since = (self._overload_since or now) \
            if state == "overload" else None
        self._idle_since = (self._idle_since or now) \
            if state == "idle" else None
        baseline = self.ready_count(kind="baseline")
        if state == "overload" \
                and now - self._overload_since \
                >= self.tier.scale_up_after_s:
            if baseline < self.tier.max_replicas:
                self._overload_since = None
                try:
                    self.spawn_replica()
                except SpawnError as e:
                    self._emit_scale("spawn_failed", str(e))
                else:
                    self._emit_scale("scale_out", reason, **{
                        k: stats[k] for k in
                        ("queue_rows", "shed", "p99_ms")
                        if k in stats})
        elif state == "idle" \
                and now - self._idle_since \
                >= self.tier.scale_down_after_s:
            if baseline > self.tier.min_replicas:
                self._idle_since = None
                victim = self._scale_in_victim()
                if victim is not None:
                    self.retire_replica(victim)

    def _take_fleet_window(self) -> Dict[str, Any]:
        """The autoscaler's input across the whole front tier: the
        in-process door's window plus one destructive
        ``GET /fleet/window`` per external door (this controller is
        the only window reader, by contract)."""
        windows = [self.balancer.take_window()]
        if self.bal_manager is not None:
            for bal in self.bal_manager.balancers():
                w = self._fetch_json(bal.host, bal.http_port,
                                     "/fleet/window")
                if w is not None:
                    windows.append(w)
        if len(windows) == 1:
            return windows[0]
        return aggregate_windows(windows)

    def _reap_dead(self) -> None:
        """Deroute crashed replicas, reap alive-but-wedged ones, then
        self-heal below the minimum."""
        if self.bal_manager is not None:
            # a dead front door loses no requests (clients fail over),
            # but the tier must heal back to fleet_balancers doors
            for bal in self.bal_manager.poll_dead():
                if self.registry is not None:
                    self.registry.remove(bal.balancer_id)
                self._sync_door_peers()
                self._emit_scale(
                    "balancer_lost",
                    "balancer %s (pid %d) exited with %s"
                    % (bal.balancer_id, bal.pid,
                       bal.proc.returncode))
                if not self._stop.is_set():
                    try:
                        self._spawn_door(bal.index)
                    except SpawnError as e:
                        self._emit_scale("spawn_failed", str(e))
        if self.tier.wedged_after_s > 0:
            # a process that is alive but unresponsive (deadlock,
            # swap-death) never shows up in poll_dead — without this
            # it would hold a fleet slot forever while serving nothing
            for rid in self.balancer.suspect_overdue(
                    self.tier.wedged_after_s):
                with self._lock:
                    rep = self._reps.get(rid)
                if rep is None:
                    continue
                self.balancer.remove_replica(rid)
                self.manager.stop(rep, timeout_s=5.0)
                with self._lock:
                    self._reps.pop(rid, None)
                self._emit_scale(
                    "replica_lost",
                    "replica %s wedged: suspect for over "
                    "fleet_wedged_after_s (%.0fs), force-stopped"
                    % (rid, self.tier.wedged_after_s))
                if self.canary is not None and rep.kind == "canary":
                    self.canary.canary_died(rep)
        for rep in self.manager.poll_dead():
            self.balancer.remove_replica(rep.replica_id)
            with self._lock:
                self._reps.pop(rep.replica_id, None)
            self._emit_scale("replica_lost",
                             "replica %s (pid %d) exited with %s"
                             % (rep.replica_id, rep.pid,
                                rep.proc.returncode
                                if hasattr(rep, "proc") else "?"))
            if self.canary is not None and rep.kind == "canary":
                self.canary.canary_died(rep)
        while self.ready_count(kind="baseline") \
                < self.tier.min_replicas and not self._stop.is_set():
            try:
                self.spawn_replica()
            except SpawnError as e:
                self._emit_scale("spawn_failed", str(e))
                break

    def _scale_in_victim(self) -> Optional[ReplicaProcess]:
        """Newest ready baseline replica — canary replicas are the
        rollout's to manage, and the oldest replicas have the warmest
        page caches."""
        ids = set(self.balancer.replica_ids(kind="baseline"))
        with self._lock:
            cands = [r for r in self._reps.values()
                     if r.replica_id in ids]
        return max(cands, key=lambda r: r.replica_id, default=None)

"""Canary rollout: pin a traffic fraction to a new bundle version,
compare, promote or roll back — automatically.

Armed by ``canary_source`` (a new sealed bundle / snapshot for the
``canary_model`` entry), the rollout:

1. spawns one canary replica serving the new version and pins
   ``canary_fraction`` of balancer traffic to it (deterministic
   interleave — no RNG, reproducible splits);
2. observes per-version outcome/latency windows for
   ``canary_window_s`` (the balancer resets both windows at pin time,
   so baseline and canary are measured over the same period under the
   same traffic);
3. decides via the pure :func:`canary_decision`: the canary must not
   raise the error rate beyond ``canary_max_error_rate`` over
   baseline, nor stretch ok-request p99 beyond ``canary_p99_ratio`` x
   baseline, with at least ``canary_min_requests`` canary samples;
4. **promote** — the controller's current model set repoints at the
   new version; baseline replicas are rolled one at a time
   (spawn-new -> drain-old -> stop, never dropping below the serving
   count) and the canary replica joins the baseline pool; or
   **rollback** — the canary replica drains and stops, the good
   version keeps serving. A canary replica that dies or fails to boot
   (the injected-bad-bundle case) rolls back immediately.

Every phase emits a schema-validated ``canary`` record; the
promote/rollback record doubles as the decision record written to
``canary_out`` (default ``<fleet_dir>/canary_decision.json``) after
:func:`~cxxnet_tpu.monitor.schema.validate_record` passes on it.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

from ..monitor import SafeEmitter
from ..monitor.schema import validate_record
from .config import FleetTierConfig, version_of
from .replica import SpawnError


def canary_decision(base: Dict[str, Any], cane: Dict[str, Any],
                    tier: FleetTierConfig) -> Tuple[str, str]:
    """Pure comparison of two per-version stat windows
    (``{"ok", "errors", "requests", "p99_ms"}``):
    ``("promote" | "rollback" | "wait", reason)``.

    Not enough canary samples -> wait. A canary error rate more than
    ``canary_max_error_rate`` above baseline's, or an ok-request p99
    beyond ``canary_p99_ratio`` x baseline's (when baseline has a
    meaningful p99), rolls back; otherwise promote."""
    n_c = int(cane.get("requests", 0))
    if n_c < tier.canary_min_requests:
        return "wait", ("canary has %d/%d required requests"
                        % (n_c, tier.canary_min_requests))
    err_c = cane.get("errors", 0) / float(n_c)
    n_b = int(base.get("requests", 0))
    err_b = base.get("errors", 0) / float(n_b) if n_b else 0.0
    if err_c > err_b + tier.canary_max_error_rate:
        return "rollback", (
            "canary error rate %.4f exceeds baseline %.4f + "
            "canary_max_error_rate %.4f"
            % (err_c, err_b, tier.canary_max_error_rate))
    p99_b = float(base.get("p99_ms", 0.0))
    p99_c = float(cane.get("p99_ms", 0.0))
    if p99_b > 0 and cane.get("ok", 0) \
            and p99_c > tier.canary_p99_ratio * p99_b:
        return "rollback", (
            "canary p99 %.1f ms exceeds %.2fx baseline p99 %.1f ms"
            % (p99_c, tier.canary_p99_ratio, p99_b))
    return "promote", (
        "canary error rate %.4f (baseline %.4f), p99 %.1f ms "
        "(baseline %.1f ms) within thresholds"
        % (err_c, err_b, p99_c, p99_b))


class CanaryRollout:
    """One-shot canary driven by the controller's scale loop
    (``step()`` per tick). States: ``armed`` -> ``observing`` ->
    ``promoted`` | ``rolled_back``."""

    def __init__(self, controller, tier: FleetTierConfig,
                 monitor=None):
        self.controller = controller
        self.tier = tier
        self._safe_emit = SafeEmitter(monitor, "cxxnet_tpu canary")
        self._lock = threading.Lock()
        self.state = "armed"
        self.canary_version = version_of(tier.canary_source)
        self.baseline_version = ""
        self._rep = None                     # the canary replica
        self._observe_t0 = 0.0
        self.decision: Optional[Dict[str, Any]] = None

    # -- state machine -----------------------------------------------------

    def arm(self) -> None:
        """Spawn the canary replica and pin the traffic fraction;
        a boot failure (bad bundle: refuses to load, over budget,
        crashes during warmup) rolls back immediately — the injected-
        bad-bundle acceptance path."""
        self.baseline_version = self.controller.current_version()
        if self.canary_version == self.baseline_version:
            self._finish("rollback",
                         "canary_source is already the serving "
                         "version", {}, {})
            return
        models = self.tier.models_with_source(self.tier.canary_source)
        try:
            self._rep = self.controller.spawn_replica(models=models,
                                                      kind="canary")
        except SpawnError as e:
            self._finish("rollback",
                         "canary replica failed to boot: %s" % e,
                         {}, {})
            return
        self.controller.balancer.pin_canary(self.canary_version,
                                            self.tier.canary_fraction)
        with self._lock:
            self.state = "observing"
            self._observe_t0 = time.monotonic()
        self._phase_record(
            "start", "observing %s at fraction %g for %gs"
            % (self.canary_version, self.tier.canary_fraction,
               self.tier.canary_window_s), {}, {})

    def step(self) -> None:
        """One controller tick: decide once the window has elapsed
        (and keep waiting for samples up to 3 windows — a canary that
        cannot accumulate ``canary_min_requests`` in that long has no
        evidence either way, and an unobserved version must not be
        promoted)."""
        with self._lock:
            if self.state != "observing":
                return
            elapsed = time.monotonic() - self._observe_t0
        if elapsed < self.tier.canary_window_s:
            return
        stats = self.controller.balancer.version_stats()
        base = stats.get(self.baseline_version, {})
        cane = stats.get(self.canary_version, {})
        verdict, reason = canary_decision(base, cane, self.tier)
        if verdict == "wait":
            if elapsed < 3 * self.tier.canary_window_s:
                return
            verdict, reason = "rollback", (
                "insufficient canary traffic after %.0fs: %s"
                % (elapsed, reason))
        if verdict == "promote":
            self._promote(reason, base, cane)
        else:
            self._rollback(reason, base, cane)

    def canary_died(self, rep) -> None:
        """Controller noticed the canary process exited: the strongest
        possible rollback signal."""
        with self._lock:
            if self.state != "observing":
                return
            self._rep = None
        stats = self.controller.balancer.version_stats()
        self._rollback("canary replica %s died mid-window"
                       % rep.replica_id,
                       stats.get(self.baseline_version, {}),
                       stats.get(self.canary_version, {}))

    # -- outcomes ----------------------------------------------------------

    def _promote(self, reason: str, base: Dict, cane: Dict) -> None:
        """Repoint the fleet at the new version and roll the old
        baseline replicas one at a time — spawn-before-retire, so the
        serving count never dips."""
        ctl = self.controller
        new_models = self.tier.models_with_source(
            self.tier.canary_source)
        ctl.set_current_models(new_models)
        old = [r for r in ctl.manager.replicas()
               if r.version == self.baseline_version]
        for rep in old:
            try:
                ctl.spawn_replica()          # now spawns the new version
            except SpawnError as e:
                # promote already decided on measured evidence; a
                # failed roll spawn leaves the old replica serving
                ctl._emit_scale("spawn_failed",
                                "promote roll: %s" % e)
                break
            ctl.retire_replica(rep, action="promote_roll")
        # the canary replica is now just a baseline of the new version
        if self._rep is not None:
            self._rep.kind = "baseline"
            ctl.balancer.set_replica_kind(self._rep.replica_id,
                                          "baseline")
        ctl.balancer.unpin_canary()
        self._finish("promote", reason, base, cane)

    def _rollback(self, reason: str, base: Dict, cane: Dict) -> None:
        ctl = self.controller
        ctl.balancer.unpin_canary()
        rep = self._rep
        self._rep = None
        if rep is not None and rep.alive():
            ctl.retire_replica(rep, action="canary_rollback")
        self._finish("rollback", reason, base, cane)

    def _finish(self, phase: str, reason: str, base: Dict,
                cane: Dict) -> None:
        with self._lock:
            self.state = "promoted" if phase == "promote" \
                else "rolled_back"
        self.decision = self._phase_record(phase, reason, base, cane)
        self._write_decision(self.decision)

    # -- records -----------------------------------------------------------

    def _emit(self, kind: str, **fields) -> None:
        self._safe_emit(kind, **fields)

    def _phase_record(self, phase: str, reason: str, base: Dict,
                      cane: Dict) -> Dict[str, Any]:
        rec = {
            "event": "canary", "t": time.time(), "phase": phase,
            "baseline_version": self.baseline_version,
            "canary_version": self.canary_version,
            "fraction": self.tier.canary_fraction,
            "reason": reason,
            "window_s": self.tier.canary_window_s,
            "baseline": dict(base), "canary": dict(cane),
        }
        errs = validate_record(rec)
        assert not errs, "canary decision record invalid: %s" % errs
        fields = dict(rec)
        fields.pop("event")
        fields.pop("t")
        self._emit("canary", **fields)
        return rec

    def _write_decision(self, rec: Dict[str, Any]) -> None:
        """The decision record file operators and deploy tooling read
        (atomic tmp+rename; schema-validated above)."""
        out = self.tier.canary_out or os.path.join(
            self.tier.fleet_dir, "canary_decision.json")
        d = os.path.dirname(os.path.abspath(out))
        os.makedirs(d, exist_ok=True)
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f, sort_keys=True, indent=1)
        os.replace(tmp, out)

"""CLI task driver.

Parity with ``/root/reference/src/cxxnet_main.cpp:26-575``: a config file
plus ``key=value`` CLI overrides drives tasks ``train`` / ``finetune`` /
``pred`` / ``extract_feature`` / ``get_weight`` (plus the TPU-port tasks
``serve`` / ``serve_fleet`` / ``fleet`` / ``quantize`` / ``export`` /
``continual``); snapshots are written as
``<model_dir>/<round:04d>.model.npz``; ``continue=1`` resumes from the
latest snapshot (SyncLastestModel, :180-202); ``test_io=1`` exercises the
data pipeline without the net (:455-468); only the root process saves
and logs in distributed runs (:424-435, 501-503).

Usage: python -m cxxnet_tpu.main config.conf [key=value ...]
"""

from __future__ import annotations

import os
import re
import signal
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .io import create_iterator
from .io.iter_batch import enable_chain_wait_stats, pipeline_snapshot
from .monitor import (Monitor, create_monitor, device_memory_snapshot,
                      run_metadata, set_global)
from .nnet.checkpoint import CheckpointManager, find_latest_valid
from .nnet.trainer import NetTrainer
from .parallel import (allreduce_host_sum, clear_dryrun_topology,
                       current_topology, init_distributed, is_root,
                       set_allreduce_retry, set_dryrun_topology,
                       synced_batches, world_size)
from .parallel.topology import DryrunFeed, build_dryrun_feed
from .utils.config import (parse_cli_overrides, parse_config_file,
                           split_sections)
from .utils.stream import open_stream, set_stream_retry, uri_scheme

_MODEL_RE = re.compile(r"^(\d{4})\.model\.npz$")

# exit code of a preempted run: SIGTERM/SIGINT arrived, the emergency
# snapshot committed, telemetry flushed. EX_TEMPFAIL — schedulers and
# wrapper scripts treat it as "re-queue me" (doc/checkpointing.md)
EXIT_PREEMPTED = 75

# tasks that read data through the pred iterator (or its fallback);
# quantize rides here too — calibration wants the deterministic eval
# transform, not the shuffled/augmented training stream
_PRED_TASKS = ("pred", "extract_feature", "extract", "pred_raw", "serve",
               "quantize", "build_index")

# randomized-pipeline knobs neutralized when a pred-like task falls
# back to the train data block: evaluation order must be the file
# order and every example must go through the deterministic eval
# transform (center crop / mean / scale stay — they define the input
# distribution; the stochastic knobs do not)
_PRED_NEUTRAL = (
    ("shuffle", "0"), ("shuffle_chunk", "0"),
    ("rand_crop", "0"), ("rand_mirror", "0"),
    ("max_random_contrast", "0"), ("max_random_illumination", "0"),
    ("max_rotate_angle", "0"), ("max_shear_ratio", "0"),
    ("max_aspect_ratio", "0"),
    ("min_random_scale", "1"), ("max_random_scale", "1"),
    ("min_crop_size", "-1"), ("max_crop_size", "-1"),
    ("rotate", "-1"), ("rotate_list", ""),
)


class LearnTask:
    def __init__(self) -> None:
        self.task = "train"
        self.net_type = "feedforward"
        self.num_round = 10
        self.start_counter = 1
        self.save_period = 1
        self.model_dir = "./models"
        self.model_in = ""
        self.continue_training = 0
        self.print_step = 100
        self.silent = 0
        self.task_eval_train = 1
        self.test_on_server = 0
        self.name_pred = "pred.txt"
        self.output_format = "txt"
        self.extract_node_name = ""
        self.weight_filename = "weight.txt"
        self.weight_layer = ""
        self.weight_tag = "wmat"
        self.test_io = 0
        self.device = ""
        # batches per jitted dispatch in the train loop (update_many):
        # amortizes host dispatch latency; schedule stays per-update
        # correct. 1 = per-batch update().
        self.dispatch_period = 8
        # precompile = 1: AOT-compile the dispatch programs for the
        # run's static shapes before round 0 (trainer.precompile);
        # combined with compile_cache_dir the compiles amortize across
        # runs (doc/observability.md)
        self.precompile = 0
        # crash-safe checkpointing (doc/checkpointing.md): background
        # commit thread, retention GC, durable fsync, remote-read
        # retries. checkpoint_async=1 keeps the training thread's
        # share of a snapshot to the device->host gather.
        self.checkpoint_async = 1
        self.checkpoint_fsync = 1
        self.keep_snapshots = 0          # 0 = keep every snapshot
        self.stream_retry = 0            # remote read retries (opt-in)
        # post-training quantization (task = quantize,
        # doc/perf_profile.md "Low-precision inference"): target dtype,
        # calibration stream length, the f32 parity gate, output path
        self.quantize_dtype = "int8"
        self.quantize_batches = 8
        self.quantize_parity_eps = 0.05
        self.quantize_out = ""
        # sealed artifact export (task = export, doc/artifacts.md):
        # output bundle directory; "" derives NNNN.model.bundle beside
        # model_in so a watched model_dir picks the bundle up
        self.export_out = ""
        # embedding index build (task = build_index, doc/retrieval.md):
        # similarity metric sealed into the index, and a corpus-size
        # cap (0 = embed the whole iterator)
        self.index_metric = "dot"
        self.index_rows = 0
        # finetune remap contract (doc/tasks.md "finetune"): layers
        # named here re-initialize fresh (the new-label-count head);
        # any OTHER shape mismatch is a typed FinetuneShapeError
        # naming the layer unless finetune_strict = 0 restores the
        # reference's silent skip-and-reinit
        self.finetune_remap: Tuple[str, ...] = ()
        self.finetune_strict = 1
        # multi-host SPMD launch (doc/distributed.md): coordinator
        # address + world shape driving jax.distributed.initialize.
        # Env vars (CXXNET_COORDINATOR et al.) and managed-runtime
        # autodetect keep working; config keys win when set.
        self.dist_coordinator = ""
        self.dist_num_hosts = 0          # 0 = env / runtime autodetect
        self.dist_host_rank = -1         # -1 = env / runtime autodetect
        # single-process multi-host dryrun: fake N input hosts over
        # this process's devices — full shard math (mesh build,
        # per-host batch assembly, re-derivation), zero DCN
        self.dist_dryrun_hosts = 0
        # bounded retries for the process-group metric allreduce
        # (transient DCN hiccups re-enter the collective; 0 fails fast)
        self.dist_allreduce_retry = 2
        # observability (doc/observability.md); a null monitor until
        # run() builds the configured one, so task methods are safe to
        # call directly in tests
        self._mon = Monitor()
        self._cfg_stream = []
        self._resume_report = None
        self._resume_found = False
        # preemption flag set from the SIGTERM/SIGINT handler; holds
        # the signal number until the train loop's next update boundary
        self._preempt_signum: Optional[int] = None

    # -- config ----------------------------------------------------------

    def _set(self, name: str, val: str) -> None:
        if name == "task":
            self.task = val
        if name == "net_type":
            self.net_type = val
        if name in ("num_round", "max_round"):
            self.num_round = int(val)
        if name == "start_counter":
            self.start_counter = int(val)
        if name == "save_model":
            self.save_period = 0 if val == "0" else int(val)
        if name == "model_dir":
            self.model_dir = val
        if name == "model_in":
            self.model_in = val
        if name == "continue":
            self.continue_training = int(val)
        if name == "print_step":
            self.print_step = int(val)
        if name == "silent":
            self.silent = int(val)
        if name in ("eval_train", "train_eval"):
            self.task_eval_train = int(val)
        if name == "test_on_server":
            self.test_on_server = int(val)
        if name == "extract_node_name":
            self.extract_node_name = val
        if name == "extract_layer_name":
            # reference semantics: the get_weight layer selector
            # (cxxnet_main.cpp:339), NOT an extract_feature trigger
            self.weight_layer = val
        if name == "output_format":
            if val not in ("txt", "bin"):
                raise ValueError(
                    "output_format must be 'txt' or 'bin', got %r" % val)
            self.output_format = val
        if name == "weight_filename":
            self.weight_filename = val
        if name == "weight_layer":
            self.weight_layer = val
        if name == "weight_tag":
            self.weight_tag = val
        if name == "test_io":
            self.test_io = int(val)
        if name == "dev":
            self.device = val
        if name == "dispatch_period":
            self.dispatch_period = max(1, int(val))
        if name == "precompile":
            self.precompile = int(val)
        if name == "checkpoint_async":
            self.checkpoint_async = int(val)
        if name == "checkpoint_fsync":
            self.checkpoint_fsync = int(val)
        if name == "keep_snapshots":
            self.keep_snapshots = int(val)
        if name == "stream_retry":
            self.stream_retry = int(val)
        if name == "quantize_dtype":
            self.quantize_dtype = val
        if name == "quantize_batches":
            self.quantize_batches = int(val)
        if name == "quantize_parity_eps":
            self.quantize_parity_eps = float(val)
        if name == "quantize_out":
            self.quantize_out = val
        if name == "export_out":
            self.export_out = val
        if name == "index_metric":
            self.index_metric = val
        if name == "index_rows":
            self.index_rows = int(val)
        if name == "finetune_remap":
            self.finetune_remap = tuple(
                t.strip() for t in val.split(",") if t.strip())
        if name == "finetune_strict":
            self.finetune_strict = int(val)
        if name == "dist_coordinator":
            self.dist_coordinator = val
        if name == "dist_num_hosts":
            self.dist_num_hosts = int(val)
        if name == "dist_host_rank":
            self.dist_host_rank = int(val)
        if name == "dist_dryrun_hosts":
            self.dist_dryrun_hosts = int(val)
        if name == "dist_allreduce_retry":
            self.dist_allreduce_retry = int(val)

    # -- model files -----------------------------------------------------

    def _model_path(self, counter: int) -> str:
        if uri_scheme(self.model_dir):
            return "%s/%04d.model.npz" % (self.model_dir.rstrip("/"),
                                          counter)
        return os.path.join(self.model_dir, "%04d.model.npz" % counter)

    def _sync_latest_model(self) -> Optional[str]:
        """Find the newest *valid* snapshot in model_dir
        (cxxnet_main:180-202, hardened): every candidate is
        digest/structure verified newest-first, corrupt ones are
        quarantined with a warning, and only a snapshot that actually
        loads is handed to load_model. Works for remote model_dir URIs
        via the stream layer."""
        rep = find_latest_valid(self.model_dir, monitor=self._mon)
        self._resume_report = rep
        if rep.path is None:
            if rep.quarantined:
                self._mon.warn_once(
                    "resume_no_valid_snapshot",
                    "continue=1: model_dir %r holds %d snapshot(s) but "
                    "none verifies — quarantined %s and starting from "
                    "round 0" % (self.model_dir, rep.scanned,
                                 ", ".join(rep.quarantined)))
            return None
        self.start_counter = rep.counter + 1
        return rep.path

    # -- run -------------------------------------------------------------

    def run(self, argv: List[str]) -> int:
        if len(argv) < 1:
            print("Usage: python -m cxxnet_tpu.main config.conf "
                  "[key=value ...]")
            return 1
        # CPU-only local mode (example/multi-machine/launch.py): this
        # environment preloads jax at interpreter start, so JAX_PLATFORMS
        # in the env is read too late — honor it via jax.config before
        # the backend initializes
        ndev = os.environ.get("CXXNET_NUM_CPU_DEVICES")
        if ndev:
            from .parallel import force_virtual_cpu
            force_virtual_cpu(int(ndev))
        # config parses BEFORE distributed bring-up (pure text, no jax
        # touched) so the dist_* launch keys can drive
        # jax.distributed.initialize — env vars stay as fallback
        cfg = parse_config_file(argv[0])
        cfg += parse_cli_overrides(argv[1:])
        blocks, global_cfg = split_sections(cfg)
        for name, val in global_cfg:
            self._set(name, val)
        init_distributed(
            coordinator=self.dist_coordinator or None,
            num_processes=self.dist_num_hosts or None,
            process_id=None if self.dist_host_rank < 0
            else self.dist_host_rank)
        set_allreduce_retry(self.dist_allreduce_retry)
        # 'pred = <outfile>' doubles as the pred-block marker
        # (cxxnet_main.cpp:281-282), so read it from the raw stream
        for name, val in cfg:
            if name == "pred":
                self.name_pred = val

        # structured telemetry (monitor = none|stdout|jsonl); non-root
        # ranks get a null sink inside create_monitor. Installed as the
        # process-global so deep call sites (metric fallback warnings)
        # reach the same stream.
        self._cfg_stream = cfg
        self._mon = create_monitor(global_cfg)
        set_global(self._mon)
        # opt-in retry for transient remote-stream reads (flaky object
        # stores on preemptible capacity); 0 = fail fast, the default
        set_stream_retry(self.stream_retry)

        # iterators (closed on exit: prefetch threads / decode pools);
        # hoisted above the try so the finally can always iterate it
        all_iters: List[object] = []
        try:
            if self.dist_dryrun_hosts > 1:
                # fake the input topology for THIS run; cleared in the
                # finally so library callers never inherit a stale fake
                set_dryrun_topology(self.dist_dryrun_hosts)
            # model_in via filename convention infers start counter when
            # continuing training (cxxnet_main.cpp:204-215); finetune starts
            # a fresh model numbering
            if self.model_in and self.task == "train":
                m = _MODEL_RE.match(os.path.basename(self.model_in))
                if m:
                    self.start_counter = int(m.group(1)) + 1

            if self.continue_training:
                latest = self._sync_latest_model()
                self._resume_found = latest is not None
                if latest is not None:
                    self.model_in = latest
                rep = self._resume_report
                if self._mon.enabled and rep is not None:
                    self._mon.emit(
                        "resume",
                        source=latest or "",
                        counter=-1 if rep.counter is None
                        else rep.counter,
                        scanned=rep.scanned,
                        quarantined=len(rep.quarantined))

            itr_train = None
            eval_iters: List[Tuple[str, object]] = []
            pred_iter = None
            batch_cfg = [(k, v) for k, v in global_cfg
                         if k in ("batch_size", "input_shape", "label_width")]
            # multi-process dp: config batch_size is GLOBAL (doc/global.md);
            # each rank's iterator produces its 1/world_size local shard,
            # which the trainer assembles into the global batch
            # (make_array_from_process_local_data). Rank-disjoint DATA comes
            # from the iterators' own part_index/num_parts sharding.
            nproc = world_size()

            def _local_bs(v: str) -> str:
                assert int(v) % nproc == 0, \
                    "batch_size %s must divide evenly across %d " \
                    "processes" % (v, nproc)
                return str(int(v) // nproc)

            def _localize(pairs):
                """Divide every batch_size by world_size — both the global
                section AND iterator-block overrides (a block-level
                batch_size applied after the divided global one would feed
                world_size-times-too-many rows into the global assembly)."""
                if nproc == 1:
                    return pairs
                return [(k, _local_bs(v) if k == "batch_size" else v)
                        for k, v in pairs]

            batch_cfg = _localize(batch_cfg)
            if self.task == "serve_fleet":
                # the fleet front end serves network traffic, not an
                # iterator — skip data-block construction entirely (a
                # deployment config's train blocks may point at paths
                # the serving host does not mount)
                return self._task_serve_fleet(cfg)
            if self.task == "fleet":
                # the horizontal tier: balancer + autoscaler + canary
                # over N replica processes, each a task=serve_fleet
                # child spawned from this same config file
                return self._task_fleet(cfg, argv[0], argv[1:])
            if self.task == "fleet_balancer":
                # one door of a sharded front tier: a standalone
                # balancer process learning replicas and peers from
                # the endpoint registry (spawned by task=fleet when
                # fleet_balancers > 1, or run standalone)
                return self._task_fleet_balancer(cfg)
            if self.task == "export":
                # sealing a snapshot into a bundle needs no data
                # either — only the net config and the serve contract
                assert self.model_in, "task export requires model_in"
                return self._task_export(cfg)
            if (self.task in _PRED_TASKS and not self.test_io
                    and not any(b["kind"] == "pred" for b in blocks)):
                # no 'pred =' block: these tasks fall back to the train
                # data block, which is configured for training (shuffled,
                # randomly augmented) — say so once, and neutralize the
                # stochastic knobs so the output is deterministic and
                # row-aligned with the source files
                for b in blocks:
                    if b["kind"] != "data":
                        continue
                    b["cfg"] = list(b["cfg"]) + list(_PRED_NEUTRAL)
                    self._mon.warn_once(
                        "pred_fallback_train_iter",
                        "task=%s has no 'pred =' iterator block; "
                        "falling back to the train data block %r with "
                        "shuffle/augmentation disabled" %
                        (self.task, b["name"]))
            for b in blocks:
                if (self.dist_dryrun_hosts > 1 and b["kind"] == "data"
                        and (self.test_io
                             or self.task in ("train", "finetune"))):
                    # multi-host dryrun (doc/distributed.md): one
                    # batch-block-sharded chain per virtual host,
                    # assembled into the exact single-host global
                    # batch in host-rank order. Eval blocks stay
                    # unsharded — the shard math under test is the
                    # training input path
                    gbs = 0
                    for k, v in list(batch_cfg) + list(b["cfg"]):
                        if k == "batch_size":
                            gbs = int(v)
                    assert gbs > 0, "dryrun requires batch_size"
                    self._mon.warn_once(
                        "dryrun_neutralized_knobs",
                        "dist_dryrun_hosts=%d: shuffle off and "
                        "round_batch=0 on every per-host chain (the "
                        "bit-identity and exactly-once invariants "
                        "need deterministic record order)"
                        % self.dist_dryrun_hosts)
                    it = build_dryrun_feed(b["cfg"], batch_cfg,
                                           self.dist_dryrun_hosts, gbs)
                    it.init()
                    all_iters.append(it)
                    itr_train = it
                    continue
                it = create_iterator(_localize(b["cfg"]), batch_cfg)
                it.init()
                all_iters.append(it)
                if b["kind"] == "data":
                    itr_train = it
                elif b["kind"] == "eval":
                    eval_iters.append((b["name"], it))
                elif b["kind"] == "pred":
                    pred_iter = it

            if self.test_io:
                return self._task_test_io(itr_train)

            if self.task == "serve":
                assert self.model_in, "task serve requires model_in"
                return self._task_serve(cfg, pred_iter or itr_train)

            if self.task == "quantize":
                assert self.model_in, "task quantize requires model_in"
                return self._task_quantize(cfg, pred_iter or itr_train)

            if self.task == "build_index":
                assert self.model_in, \
                    "task build_index requires model_in"
                return self._task_build_index(cfg,
                                              pred_iter or itr_train)

            trainer = NetTrainer(cfg)
            if self.task in ("train", "finetune", "continual"):
                # monitor BEFORE init/load: the finetune carry record
                # and a bundle model_in's artifact_load accounting are
                # emitted during the bootstrap below
                trainer.set_monitor(self._mon)
                mode = self.task
                if self.task == "continual":
                    # the loop's training mode (continual_task):
                    # train = fresh init / warm-start model_in;
                    # finetune = remap-aware bootstrap
                    from .continual import ContinualConfig
                    mode = ContinualConfig(cfg).task
                if self.model_in and (mode == "train"
                                      or self._resume_found):
                    # plain verified load — including a resumed
                    # (continue = 1) finetune/continual run: its own
                    # snapshots already carry the remapped structure,
                    # so resume must NOT re-remap a freshly
                    # initialized head over the trained one
                    trainer.load_model(self.model_in)
                else:
                    trainer.init_model()
                    if mode == "finetune":
                        assert self.model_in, "finetune requires model_in"
                        trainer.finetune_from(
                            self.model_in, remap=self.finetune_remap,
                            strict=bool(self.finetune_strict))
                if self.task == "continual":
                    return self._task_continual(cfg, trainer,
                                                itr_train, eval_iters)
                return self._task_train(trainer, itr_train, eval_iters)

            assert self.model_in, "task %s requires model_in" % self.task
            # monitor before load: a bundle model_in emits its
            # artifact_load accounting during load_model
            trainer.set_monitor(self._mon)
            trainer.load_model(self.model_in)
            if self.task == "pred":
                return self._task_predict(trainer, pred_iter or itr_train)
            if self.task in ("extract_feature", "extract",
                             "pred_raw"):
                # "extract" is the reference task name
                # (cxxnet_main.cpp:115); "pred_raw" appears in the
                # reference kaggle_bowl pred.conf meaning a raw
                # probability dump = extract of the top node
                if self.task == "pred_raw" and \
                        not self.extract_node_name:
                    self.extract_node_name = "top"
                return self._task_extract(trainer, pred_iter or itr_train)
            if self.task == "get_weight":
                return self._task_get_weight(trainer)
            print("unknown task %r" % self.task)
            return 1
        finally:
            # iterator construction and the task bodies share one
            # cleanup scope: a config error must still close prefetch
            # threads, release the jsonl sink, and clear the global
            # monitor (a stale global would swallow later warn_once
            # calls in long-lived library processes). The nested
            # finally flushes the sink even when an iterator close
            # raises (a wedged prefetch thread must not lose the
            # buffered tail of the record stream).
            try:
                for it in all_iters:
                    it.close()
            finally:
                clear_dryrun_topology()
                set_global(None)
                self._mon.close()

    def _task_test_io(self, itr) -> int:
        assert itr is not None, "test_io requires a data block"
        mon = self._mon
        if mon.enabled:
            mon.emit("run_start",
                     **run_metadata("test_io", self._cfg_stream))
        start = time.time()
        n = 0
        for r in range(self.num_round):
            for batch in itr:
                n += batch.batch_size - batch.num_batch_padd
        dt = time.time() - start
        ips = n / max(dt, 1e-9)
        mon.line("test_io: %d instances in %.2fs (%.1f/sec)"
                 % (n, dt, ips))
        if mon.enabled:
            mon.emit("test_io", instances=n, wall_s=dt,
                     instances_per_sec=ips)
        return 0

    # -- preemption ------------------------------------------------------

    def _install_preempt_handlers(self):
        """Catch SIGTERM/SIGINT (the preemption notice) and convert
        them into a flag the train loop honors at the next update
        boundary — an emergency snapshot beats dying mid-write. Only
        the main thread can own signal handlers; library callers on
        other threads keep their process defaults."""
        if threading.current_thread() is not threading.main_thread():
            return []
        installed = []

        def _on_signal(signum, frame):
            self._preempt_signum = signum

        for s in (signal.SIGTERM, signal.SIGINT):
            try:
                installed.append((s, signal.signal(s, _on_signal)))
            except (ValueError, OSError) as e:
                # without the handler a preemption kills the process
                # mid-round instead of snapshotting — worth a warning
                self._mon.warn_once(
                    "preempt_handler_unavailable",
                    "cannot install handler for signal %s (%s); "
                    "preemption will not trigger an emergency "
                    "snapshot" % (s, e))
        return installed

    @staticmethod
    def _restore_handlers(installed) -> None:
        for s, old in installed:
            try:
                signal.signal(s, old)
            except (ValueError, OSError, TypeError):
                pass  # cxxlint: disable=CXL006 -- best-effort restore on the exit path; install already warned when signals are unavailable

    def _preempt_now(self) -> bool:
        """True when any rank has a pending preemption signal. Multi-
        process: a host allreduce so every rank takes the emergency
        exit at the same update boundary (a lone rank breaking out of
        the SPMD loop would deadlock the others) — call at identical
        points on all ranks."""
        flagged = self._preempt_signum is not None
        if world_size() > 1:
            total = allreduce_host_sum(
                np.asarray([1 if flagged else 0], np.int32))
            return int(np.asarray(total)[0]) > 0
        return flagged

    def _preempt_exit(self, ckpt, round_idx: int, mon) -> int:
        """Emergency snapshot at the current update boundary, clean
        telemetry, distinct exit code. ``round_idx`` rounds completed
        fully, so the snapshot commits under counter ``round_idx`` —
        resume re-runs the interrupted round from its start with the
        mid-round weights (never loses a completed round)."""
        signum = int(self._preempt_signum or 0)
        if self.silent == 0 and is_root():
            mon.line("preempted by signal %d: emergency snapshot "
                     "%04d.model.npz" % (signum, round_idx))
        ckpt.save(round_idx, emergency=True)
        ckpt.close()
        if mon.enabled:
            mon.emit("preempt", signal=signum, round=round_idx,
                     exit_code=EXIT_PREEMPTED)
        return EXIT_PREEMPTED

    def _task_train(self, trainer, itr_train, eval_iters) -> int:
        assert itr_train is not None, "train requires a data block"
        mon = self._mon
        if trainer._mon is not mon:      # run() may have attached it
            trainer.set_monitor(mon)     # already (no duplicate
            #                              model_info records)
        if hasattr(itr_train, "set_transform"):
            # threadbuffer chains overlap host->device transfer with
            # device compute by device_put-ing in the prefetch thread
            itr_train.set_transform(trainer.device_put_batch)
        monitored = mon.enabled
        io_hist = None
        if monitored:
            mon.emit("run_start", **run_metadata(
                self.task, self._cfg_stream, trainer.mesh))
            topo = current_topology()
            if topo.num_hosts > 1:
                # the input/mesh topology this dist (or dryrun) run
                # trains under (doc/distributed.md)
                mon.emit("dist_topology", **topo.describe(),
                         mesh=dict(trainer.mesh.shape),
                         global_batch=trainer.batch_size)
            if trainer.topology_changed:
                # elastic handoff: the loaded snapshot was written
                # under a different world size/mesh; the reader shard
                # map re-derives at the round boundary (resume
                # re-runs the interrupted round from its start, so
                # the handoff record offset is 0 within the round)
                old = trainer.resumed_topology or {}
                mon.emit("dist_resize",
                         old_hosts=int(old.get("hosts", 0)),
                         new_hosts=topo.num_hosts,
                         counter=trainer.update_counter,
                         start_record=0)
            # batch-fetch latency histogram on the prefetch chain
            # (found anywhere in the chain, not only outermost);
            # attached only under an active monitor so the default
            # path never pays the per-batch clock reads
            io_hist = enable_chain_wait_stats(itr_train)
        k = self.dispatch_period
        # checkpoints go through the manager: atomic commit + digest,
        # background writer (checkpoint_async), retention GC
        # (keep_snapshots), telemetry (doc/checkpointing.md)
        ckpt = CheckpointManager(
            trainer, self._model_path, model_dir=self.model_dir,
            monitor=mon, async_=bool(self.checkpoint_async),
            fsync=bool(self.checkpoint_fsync),
            keep=self.keep_snapshots)
        if self.precompile:
            # AOT-compile every dispatch signature of the steady-state
            # loop (per-batch tail, K-batch window, eval forward) before
            # round 0: the round-0 recompile stalls collapse into one
            # accounted precompile window, and the stream records zero
            # compile events afterwards
            trainer.precompile(window=k)
        start = time.time()

        def _progress(r, nbatch):
            if (self.print_step and nbatch % self.print_step < k
                    and self.silent == 0 and is_root()):
                mon.line("round %8d:[%8d] %ld sec elapsed"
                         % (r, nbatch, int(time.time() - start)))

        # installed inside the try so every exit path restores the
        # process handlers (a long-lived library caller must get its
        # Ctrl-C back even when the loop below raises)
        handlers = []
        ndisp = 0
        try:
            handlers = self._install_preempt_handlers()
            for r in range(self.start_counter - 1, self.num_round):
                # update-boundary preemption check (collective when
                # multi-process): r rounds have fully completed
                if self._preempt_now():
                    return self._preempt_exit(ckpt, r, mon)
                trainer.start_round(r)
                if monitored:
                    mon.emit("round_start", round=r)
                # trace hooks are NOT gated on an enabled sink: a
                # profiler trace is one config line (monitor_trace_dir)
                # away even with monitor = none (doc/debug_perf.md)
                mon.maybe_start_trace(r)
                nbatch = 0
                window = []
                t_wait = time.perf_counter() if monitored else 0.0
                # lockstep across ranks: unequal per-rank batch counts
                # would deadlock the SPMD collectives (see
                # parallel.synced_batches)
                for batch in synced_batches(itr_train, window=k):
                    if monitored:
                        # data-wait half of the step-time split: time
                        # this loop spent blocked on the iterator since
                        # the last dispatch
                        trainer.note_data_wait(
                            time.perf_counter() - t_wait)
                    if k == 1:
                        trainer.update(batch)
                        nbatch += 1
                    else:
                        window.append(batch)
                        if len(window) < k:
                            if monitored:
                                t_wait = time.perf_counter()
                            continue
                        trainer.update_many(window)
                        nbatch += len(window)
                        window = []
                    _progress(r, nbatch)
                    # every rank reaches each dispatch boundary the
                    # same number of times (synced_batches), so the
                    # collective preemption check stays in lockstep.
                    # Multi-process, the check is a blocking host
                    # allgather — throttle it to every 8th dispatch
                    # (the shared ndisp counter keeps ranks agreeing
                    # on WHICH dispatches check) so the hot path does
                    # not grow a second per-dispatch host collective
                    ndisp += 1
                    if (world_size() == 1 or ndisp % 8 == 0) \
                            and self._preempt_now():
                        return self._preempt_exit(ckpt, r, mon)
                    if monitored:
                        t_wait = time.perf_counter()
                for batch in window:    # round tail: per-batch (a short
                    trainer.update(batch)  # window would recompile)
                    nbatch += 1
                trainer.end_round()     # close the throughput window
                #                         before evals start
                line = "[%d]" % (r + 1)
                if self.task_eval_train:
                    line += trainer.train_metric_str("train")
                for name, it in eval_iters:
                    line += trainer.evaluate(it, name)
                if self.silent == 0 and is_root():
                    mon.line(line)
                mon.maybe_stop_trace(r)
                if monitored:
                    mon.emit("round_end", round=r,
                             examples=trainer.last_round_examples,
                             wall_s=trainer.last_round_wall_s,
                             examples_per_sec=trainer
                             .last_round_examples_per_sec)
                    mon.emit("memory", round=r,
                             **device_memory_snapshot())
                    if io_hist is not None:
                        mon.emit("io_wait", round=r,
                                 **io_hist.snapshot())
                        io_hist.reset()
                    ps = pipeline_snapshot(itr_train)
                    if ps is not None:
                        # per-round input-pipeline health: buffer-reuse
                        # rate of the zero-copy assembly, H2D overlap
                        # of the prefetch staging (doc/observability.md)
                        mon.emit("pipeline", round=r, **ps)
                    if isinstance(itr_train, DryrunFeed):
                        # per-round per-host input-shard accounting:
                        # rows_per_host sums exactly to the round's
                        # real rows (the exactly-once invariant,
                        # counted per round)
                        mon.emit("dist_shard", round=r,
                                 **itr_train.accounting())
                        itr_train.reset_accounting()
                if self.test_on_server:
                    # per-round weight consistency audit (the
                    # reference's test_on_server CheckWeight_,
                    # async_updater-inl.hpp:149-154): every device
                    # replica must hold identical weights
                    trainer.check_weight_consistency()
                if self.save_period and (r + 1) % self.save_period == 0:
                    # all ranks call (ZeRO-state gathers are
                    # collective); only root commits, on the background
                    # writer when checkpoint_async
                    ckpt.save(r + 1)
            # drain the writer before run_end: every checkpoint record
            # lands in the stream, and the last commit is durable
            # before the exit code says success
            ckpt.close()
        finally:
            ckpt.close()
            self._restore_handlers(handlers)
        if self.silent == 0 and is_root():
            mon.line("updating end, %ld sec in all"
                     % int(time.time() - start))
        if monitored:
            c = trainer.counters_snapshot()
            mon.emit("run_end", wall_s=time.time() - start,
                     steps=int(c["steps"]), examples=int(c["examples"]))
        return 0

    def _task_continual(self, cfg, trainer, itr_train,
                        eval_iters) -> int:
        """Continual train-while-serve (doc/continual.md): one
        long-lived process trains on a looping iterator while the
        fleet front end serves live traffic from ``model_dir``; every
        ``continual_export_every`` updates the generation pipeline
        runs (eval gate -> verified snapshot -> sealed bundle ->
        watcher ``notify()`` -> zero-downtime flip), for
        ``continual_generations`` generations. SIGTERM/SIGINT takes
        the emergency-snapshot exit (code 75) like ``task = train``."""
        assert itr_train is not None, "continual requires a data block"
        assert world_size() == 1, \
            "task=continual must run single-process"
        from .continual import ContinualLoop
        mon = self._mon
        if hasattr(itr_train, "set_transform"):
            # same prefetch-thread H2D overlap as _task_train: the
            # long-lived trainer must not pay serialized transfers
            itr_train.set_transform(trainer.device_put_batch)
        if mon.enabled:
            mon.emit("run_start", **run_metadata(
                "continual", self._cfg_stream, trainer.mesh))
        handlers = []
        try:
            handlers = self._install_preempt_handlers()
            loop = ContinualLoop(
                cfg, trainer, itr_train, eval_iters,
                model_dir=self.model_dir,
                path_for=self._model_path,
                monitor=mon,
                should_stop=lambda: self._preempt_signum is not None,
                checkpoint_async=bool(self.checkpoint_async),
                checkpoint_fsync=bool(self.checkpoint_fsync),
                keep_snapshots=self.keep_snapshots,
                start_counter=self.start_counter,
                dispatch_period=self.dispatch_period)
            summary = loop.run()
        finally:
            self._restore_handlers(handlers)
        if summary["preempted"]:
            signum = int(self._preempt_signum or 0)
            if self.silent == 0 and is_root():
                mon.line("continual: preempted by signal %d after %d "
                         "generation(s); emergency snapshot committed"
                         % (signum, summary["deployed"]))
            if mon.enabled:
                mon.emit("preempt", signal=signum,
                         round=trainer.round,
                         exit_code=EXIT_PREEMPTED)
            return EXIT_PREEMPTED
        if mon.enabled:
            mon.emit("task_end", task="continual",
                     generations=summary["deployed"],
                     requests=summary["requests"])
        return 0

    def _task_serve(self, cfg, itr) -> int:
        """Long-lived concurrent predictor (doc/serving.md): load the
        snapshot into a frozen bucketed engine behind the dynamic
        batcher, then drive ``serve_clients`` threaded closed-loop
        clients over the iterator's examples — a self-contained soak
        that exercises the full concurrent path and emits the
        ``serve_*`` telemetry records."""
        assert itr is not None, "serve requires an iterator block"
        assert world_size() == 1, "task=serve must run single-process"
        from .serve import ServeSession, run_closed_loop
        mon = self._mon
        if mon.enabled:
            mon.emit("run_start",
                     **run_metadata("serve", self._cfg_stream))
        session = ServeSession(cfg, model_path=self.model_in,
                               monitor=mon)
        try:
            c = session.cfg
            # example pool for the clients: enough valid rows that
            # wrapping reuse stays fair, forced to a private float32
            # copy (iterator ring buffers recycle their arrays)
            want = max(256, c.clients * c.request_rows)
            pool_parts, got = [], 0
            for batch in itr:
                n = batch.batch_size - batch.num_batch_padd
                pool_parts.append(np.array(batch.data[:n], np.float32))
                got += n
                if got >= want:
                    break
            assert pool_parts, "serve: iterator produced no examples"
            pool = np.concatenate(pool_parts, axis=0)
            agg = run_closed_loop(session, pool, c.clients, c.requests,
                                  c.request_rows)
            summary = session.close()
        finally:
            # a failure between warmup and close must not leave the
            # worker threads emitting into a sink run() is about to
            # close (close is idempotent; no-op on the success path)
            session.close(drain=False)
        mon.line(
            "serve: %d ok / %d busy / %d timeout / %d error requests "
            "(%d rows) in %.2fs, p50 %.1f ms p99 %.1f ms, fill %.2f, "
            "compiles after warmup %d"
            % (agg["ok"], agg["busy"], agg["timeout"], agg["error"],
               summary["rows"], agg["wall_s"],
               summary["latency_p50_ms"], summary["latency_p99_ms"],
               summary["fill_rate"], summary["compile_events"]))
        if mon.enabled:
            mon.emit("task_end", task="serve", requests=agg["ok"],
                     rows=summary["rows"])
        return 0

    def _task_quantize(self, cfg, itr) -> int:
        """Post-training calibration (doc/perf_profile.md
        "Low-precision inference"): stream the iterator through the
        frozen eval net collecting per-channel activation/weight
        ranges, parity-gate the quantized graph against the f32 eval
        outputs over the same batches, and commit a digest-verified
        snapshot whose ``quant/`` arrays carry the ranges — the
        artifact ``serve_dtype = int8|fp8`` loads."""
        assert itr is not None, "quantize requires an iterator block"
        assert world_size() == 1, "task=quantize must run single-process"
        from .io.data import DataBatch
        from .nnet.checkpoint import write_snapshot
        from .nnet.quantize import Calibrator, normalize_serve_dtype
        mon = self._mon
        t_start = time.time()
        qdtype = normalize_serve_dtype(self.quantize_dtype)
        if qdtype not in ("int8", "fp8"):
            raise ValueError(
                "quantize_dtype must be int8 or fp8, got %r"
                % self.quantize_dtype)
        if mon.enabled:
            mon.emit("run_start",
                     **run_metadata("quantize", self._cfg_stream))
        # calibration runs the f32 graph whatever the config's
        # serve_dtype says (a deployment conf carries serve_dtype=int8
        # for the serve replicas; the override appends last, so it wins)
        trainer = NetTrainer(list(cfg) + [("serve_dtype", "float32")])
        trainer.load_model(self.model_in)
        top = (trainer.graph.num_nodes - 1,)
        calib = Calibrator(trainer)
        if not calib.targets:
            raise ValueError(
                "task=quantize: this net has no quantizable layers "
                "(conv/fullc owning their params, no channel-alignment "
                "annotations) — nothing to calibrate")
        batches, refs = [], []
        for batch in itr:
            # private copies: iterator ring buffers recycle their arrays
            nb = DataBatch(data=np.array(batch.data),
                           label=np.array(batch.label),
                           num_batch_padd=batch.num_batch_padd)
            nvalid = nb.batch_size - nb.num_batch_padd
            (val,) = trainer._call_pred(
                trainer._put_batch_array(nb.data),
                trainer._put_mask(nb), (), top)
            refs.append(np.array(trainer._local_rows(val)[:nvalid]))
            calib.observe(nb)
            batches.append(nb)
            if len(batches) >= self.quantize_batches:
                break
        assert batches, "quantize: iterator produced no batches"
        tables = calib.finish()
        qmeta = {"dtype": qdtype, "batches": len(batches),
                 "source": self.model_in,
                 "bn_fold_eval": trainer.net._bn_fold_eval,
                 "parity_eps": self.quantize_parity_eps}
        # activate the quantized graph on THIS trainer (fresh programs)
        # and measure parity against the stored f32 outputs
        trainer.set_quantization(tables, qmeta, dtype=qdtype)
        max_abs = mean_sum = agree = nrow = nelt = 0
        for nb, ref in zip(batches, refs):
            nvalid = nb.batch_size - nb.num_batch_padd
            (val,) = trainer._call_pred(
                trainer._put_batch_array(nb.data),
                trainer._put_mask(nb), (), top)
            got = trainer._local_rows(val)[:nvalid]
            diff = np.abs(got.astype(np.float64) - ref)
            max_abs = max(max_abs, float(diff.max()))
            mean_sum += float(diff.sum())
            nelt += diff.size
            agree += int(np.sum(trainer.rows_to_prediction(got)
                                == trainer.rows_to_prediction(ref)))
            nrow += nvalid
        mean_abs = mean_sum / max(nelt, 1)
        agree_rate = agree / max(nrow, 1)
        rep = trainer.quant_report
        out = self.quantize_out or re.sub(
            r"\.npz$", "", self.model_in) + ".%s.npz" % qdtype
        ok = mean_abs <= self.quantize_parity_eps
        if ok:
            arrays, meta = trainer.gather_snapshot()
            write_snapshot(out, arrays, meta,
                           fsync=bool(self.checkpoint_fsync))
        wall = time.time() - t_start
        if mon.enabled:
            mon.emit("quantize", dtype=rep.get("dtype", qdtype),
                     batches=len(batches), layers=rep.get("layers", 0),
                     fallback_layers=rep.get("fallback_layers", 0),
                     parity_max_abs=max_abs, parity_mean_abs=mean_abs,
                     agree_rate=agree_rate, out=out if ok else "",
                     wall_ms=wall * 1e3)
        mon.line(
            "quantize[%s]: %d layers (%d fallback) over %d batches, "
            "parity mean|Δ| %.2g max|Δ| %.2g agree %.3f — %s"
            % (rep.get("dtype", qdtype), rep.get("layers", 0),
               rep.get("fallback_layers", 0), len(batches), mean_abs,
               max_abs, agree_rate,
               ("wrote %s" % out) if ok else
               "PARITY GATE FAILED (eps %g), no snapshot written"
               % self.quantize_parity_eps))
        if mon.enabled:
            mon.emit("task_end", task="quantize", outfile=out if ok
                     else "", rows=nrow)
        return 0 if ok else 1

    def _task_serve_fleet(self, cfg) -> int:
        """Fleet serving (doc/serving.md "Fleet serving"): N routed
        engines with per-tenant quotas and checkpoint-driven hot-swap
        behind the HTTP/JSON + binary protocol listeners. Runs for
        ``serve_fleet_duration_s`` seconds (0 = until SIGTERM/SIGINT —
        the deployment mode), then drains every engine cleanly."""
        assert world_size() == 1, \
            "task=serve_fleet must run single-process"
        from .serve import FleetServer
        mon = self._mon
        if mon.enabled:
            mon.emit("run_start",
                     **run_metadata("serve_fleet", self._cfg_stream))
        fleet = FleetServer(cfg, monitor=mon)
        handlers = []
        try:
            fleet.start()
            mon.line("serve_fleet: listening http=%s binary=%s, "
                     "models: %s"
                     % (fleet.http_port, fleet.binary_port,
                        ", ".join("%s@%04d" % (d["model"], d["counter"])
                                  for d in fleet.describe())))
            handlers = self._install_preempt_handlers()
            dur = fleet.fleet_cfg.duration_s
            deadline = time.monotonic() + dur if dur > 0 else None
            while self._preempt_signum is None:
                if deadline is not None \
                        and time.monotonic() >= deadline:
                    break
                time.sleep(0.05)
            summary = fleet.close()
        finally:
            # a failure between start and close must still stop the
            # listener/watcher threads and drain the engines (close is
            # idempotent; no-op on the success path)
            fleet.close(drain=False)
            self._restore_handlers(handlers)
        c = summary["requests"]
        mon.line("serve_fleet: %d requests (%d ok / %d over_quota / "
                 "%d busy / %d timeout / %d error), %d hot-swaps"
                 % (c["requests"], c["ok"], c["over_quota"], c["busy"],
                    c["timeout"], c["error"], summary["swaps"]))
        if mon.enabled:
            mon.emit("task_end", task="serve_fleet",
                     requests=c["requests"], swaps=summary["swaps"])
        return 0

    def _task_fleet(self, cfg, conf_path: str,
                    cli_overrides: List[str]) -> int:
        """Horizontal fleet (doc/serving.md "Horizontal fleet"): a
        front-of-fleet balancer + autoscale controller (+ optional
        canary rollout) over N shared-nothing ``serve_fleet`` replica
        processes spawned from this same config file. Runs for
        ``fleet_duration_s`` seconds (0 = until SIGTERM/SIGINT), then
        drains every replica cleanly — scale-in order on every exit
        path: deroute, wait in-flight, SIGTERM."""
        assert world_size() == 1, "task=fleet must run single-process"
        from .fleet import FleetController
        mon = self._mon
        if mon.enabled:
            mon.emit("run_start",
                     **run_metadata("fleet", self._cfg_stream))
        controller = FleetController(cfg, conf_path, monitor=mon,
                                     extra_overrides=cli_overrides)
        handlers = []
        summary = {}
        try:
            controller.start()
            bal = controller.balancer
            mon.line("fleet: balancer http=%s binary=%s, %d replicas "
                     "serving %s%s"
                     % (bal.http_port, bal.binary_port,
                        controller.ready_count(),
                        controller.current_version(),
                        ", canary %s armed"
                        % controller.canary.canary_version
                        if controller.canary else ""))
            handlers = self._install_preempt_handlers()
            dur = controller.tier.duration_s
            deadline = time.monotonic() + dur if dur > 0 else None
            while self._preempt_signum is None:
                if deadline is not None \
                        and time.monotonic() >= deadline:
                    break
                time.sleep(0.05)
        finally:
            # a failure between start and the wait loop must still
            # stop the scale thread, drain replicas, and close the
            # listeners (close is idempotent per component)
            summary = controller.close()
            self._restore_handlers(handlers)
        mon.line("fleet: %d requests (%d ok / %d shed / %d error, "
                 "%d retries recovered)%s"
                 % (summary.get("requests", 0), summary.get("ok", 0),
                    summary.get("shed", 0), summary.get("errors", 0),
                    summary.get("retries", 0),
                    ", canary %s" % summary["canary"]
                    if "canary" in summary else ""))
        if mon.enabled:
            mon.emit("task_end", task="fleet",
                     requests=summary.get("requests", 0))
        return 0

    def _task_fleet_balancer(self, cfg) -> int:
        """One door of the sharded front tier (doc/serving.md
        "Sharded front tier"): a standalone :class:`FleetBalancer`
        that publishes its ports through ``fleet_port_file`` and
        reconciles replicas / tier peers from the shared endpoint
        registry on every sync tick — the same spawn-through-CLI +
        port-file discipline replicas use. Runs for
        ``fleet_duration_s`` seconds (0 = until SIGTERM/SIGINT)."""
        assert world_size() == 1, \
            "task=fleet_balancer must run single-process"
        from .fleet import FleetBalancer, FleetTierConfig
        from .fleet.placement import (EndpointRegistry,
                                      sync_from_registry,
                                      write_endpoint_file)
        mon = self._mon
        if mon.enabled:
            mon.emit("run_start",
                     **run_metadata("fleet_balancer",
                                    self._cfg_stream))
        tier = FleetTierConfig(cfg)
        bal = FleetBalancer(tier, cfg, monitor=mon)
        registry = EndpointRegistry(tier.registry_path)
        handlers = []
        summary = {}
        try:
            bal.start()
            sync_from_registry(bal, registry, tier.balancer_id)
            if tier.port_file:
                write_endpoint_file(
                    tier.port_file,
                    {"pid": os.getpid(), "http_port": bal.http_port,
                     "binary_port": bal.binary_port})
            mon.line("fleet_balancer: %s http=%s binary=%s, "
                     "registry %s"
                     % (tier.balancer_id, bal.http_port,
                        bal.binary_port, tier.registry_path))
            handlers = self._install_preempt_handlers()
            dur = tier.duration_s
            deadline = time.monotonic() + dur if dur > 0 else None
            # the sync cadence bounds how fast this door sees a drain
            # or a new replica — well under the controller's drain
            # wait, and cheap (an mtime stat when nothing changed)
            sync_s = min(0.2, tier.gossip_s)
            while self._preempt_signum is None:
                if deadline is not None \
                        and time.monotonic() >= deadline:
                    break
                sync_from_registry(bal, registry, tier.balancer_id)
                time.sleep(sync_s)
        finally:
            summary = bal.close()
            self._restore_handlers(handlers)
        mon.line("fleet_balancer: %s served %d requests (%d ok / "
                 "%d shed / %d error, %d retries recovered)"
                 % (tier.balancer_id, summary.get("requests", 0),
                    summary.get("ok", 0), summary.get("shed", 0),
                    summary.get("errors", 0),
                    summary.get("retries", 0)))
        if mon.enabled:
            mon.emit("task_end", task="fleet_balancer",
                     requests=summary.get("requests", 0))
        return 0

    def _task_export(self, cfg) -> int:
        """Seal ``model_in`` into a deployable artifact bundle
        (doc/artifacts.md): load the verified snapshot into a frozen
        bucket-ladder engine, AOT-compile every (bucket, mask-variant)
        executable the serve contract can dispatch, and commit
        snapshot + serialized executables + fingerprint + manifest as
        one two-phase bundle at ``export_out`` (default: the
        ``NNNN.model.bundle`` sibling of ``model_in``). A serve
        replica booting from the bundle on a matching runtime
        deserializes instead of compiling — near-zero cold start."""
        assert world_size() == 1, "task=export must run single-process"
        from .artifact.bundle import default_bundle_path, export_bundle
        from .serve import ServeConfig, build_engine
        mon = self._mon
        if mon.enabled:
            mon.emit("run_start",
                     **run_metadata("export", self._cfg_stream))
        sc = ServeConfig(cfg)
        engine = build_engine(cfg, self.model_in, buckets=sc.buckets,
                              max_batch=sc.max_batch, node=sc.node,
                              monitor=mon)
        # warm_run off: export needs the executables, not the
        # first-request latency of a live server
        compiled = engine.warmup(warm_run=False)
        out = self.export_out or default_bundle_path(self.model_in)
        stats = export_bundle(engine, out, node=sc.node, monitor=mon)
        if mon.enabled:
            mon.emit("export", **stats)
        mon.line("export: sealed %s -> %s (%d programs compiled, %d "
                 "serialized, %d bytes)"
                 % (self.model_in, out, compiled, stats["programs"],
                    stats["bytes"]))
        if mon.enabled:
            mon.emit("task_end", task="export", outfile=out)
        return 0

    def _task_build_index(self, cfg, itr) -> int:
        """Embed the iterator's corpus through the frozen serve net
        and seal model + index as ONE deployable bundle
        (doc/retrieval.md): stream valid rows through the bucketed
        engine (the exact dispatch ``/v1/embed`` serves), build the
        exact top-k index over the embeddings, AOT-compile the search
        program family into the same registry, and commit everything
        as a digest-verified artifact. A replica booting from the
        bundle serves ``/v1/embed`` and ``/v1/search`` with zero
        compiles, and a hot-swap flips model and index atomically."""
        assert itr is not None, "build_index requires an iterator block"
        assert world_size() == 1, \
            "task=build_index must run single-process"
        from .artifact.bundle import default_bundle_path, export_bundle
        from .retrieval import (EmbeddingIndex, RetrievalEngine,
                                self_recall)
        from .serve import ServeConfig, build_engine
        mon = self._mon
        t_start = time.time()
        if mon.enabled:
            mon.emit("run_start",
                     **run_metadata("build_index", self._cfg_stream))
        sc = ServeConfig(cfg)
        engine = build_engine(cfg, self.model_in, buckets=sc.buckets,
                              max_batch=sc.max_batch, node=sc.node,
                              monitor=mon)
        compiled = engine.warmup(warm_run=False)
        # corpus pass: valid rows only, private copies (iterator ring
        # buffers recycle their arrays), capped by index_rows
        parts, got, cap = [], 0, self.index_rows
        for batch in itr:
            n = batch.batch_size - batch.num_batch_padd
            if cap and got + n > cap:
                n = cap - got
            if n > 0:
                parts.append(np.array(batch.data[:n], np.float32))
                got += n
            if cap and got >= cap:
                break
        assert parts, "build_index: iterator produced no examples"
        rows = np.concatenate(parts, axis=0)
        vecs = np.asarray(engine.run(rows), np.float32)
        index = EmbeddingIndex.build(
            ids=np.arange(rows.shape[0], dtype=np.int64),
            vectors=vecs.reshape(rows.shape[0], -1),
            metric=self.index_metric, node=sc.node)
        spec = sc.search_buckets
        buckets = tuple(sorted({int(t) for t in spec.split(",")
                                if t.strip()})) \
            if spec and spec != "auto" else None
        rengine = RetrievalEngine(index, engine.trainer.programs,
                                  k=sc.search_k or 10,
                                  buckets=buckets, monitor=mon)
        budget = int(engine.trainer.serve_device_mem_budget * 1e6)
        rengine.warmup(warm_run=False, budget_bytes=budget)
        t_rec = time.time()
        rec = self_recall(rengine)
        if mon.enabled:
            mon.emit("retrieval", queries=min(8, index.rows), k=1,
                     metric=index.metric, recall=rec,
                     wall_ms=(time.time() - t_rec) * 1e3)
        out = self.export_out or default_bundle_path(self.model_in)
        stats = export_bundle(engine, out, node=sc.node, monitor=mon,
                              retrieval=rengine)
        if mon.enabled:
            mon.emit("index_build", out=out, rows=index.rows,
                     dim=index.dim, metric=index.metric, node=sc.node,
                     bytes=index.nbytes,
                     wall_ms=(time.time() - t_start) * 1e3)
            mon.emit("export", **stats)
        mon.line(
            "build_index: %d rows x %d dims (%s) sealed with %s -> %s "
            "(self-recall@1 %.3f, %d+%d programs, %d index bytes)"
            % (index.rows, index.dim, index.metric, self.model_in,
               out, rec, compiled, len(rengine.buckets), index.nbytes))
        if mon.enabled:
            mon.emit("task_end", task="build_index", outfile=out,
                     rows=index.rows)
        return 0

    def _task_predict(self, trainer, itr) -> int:
        assert itr is not None, "pred requires an iterator"
        # pred/extract are single-process tasks (as in the reference
        # CLI): under multi-process dp each rank would see only its
        # data shard and they would race on the output file
        assert world_size() == 1, \
            "task=pred must run single-process (launch without " \
            "CXXNET_COORDINATOR)"
        mon = self._mon
        if mon.enabled:
            mon.emit("run_start", **run_metadata(
                "pred", self._cfg_stream, trainer.mesh))
        nrow = 0
        with open_stream(self.name_pred, "w") as f:
            for batch in itr:
                for v in trainer.predict(batch):
                    f.write("%g\n" % v)
                    nrow += 1
        mon.line("finished prediction, write into %s" % self.name_pred)
        if mon.enabled:
            mon.emit("task_end", task="pred", outfile=self.name_pred,
                     rows=nrow)
        return 0

    def _task_extract(self, trainer, itr) -> int:
        assert itr is not None, "extract requires an iterator"
        assert world_size() == 1, \
            "task=extract_feature must run single-process"
        if self._mon.enabled:
            self._mon.emit("run_start", **run_metadata(
                "extract", self._cfg_stream, trainer.mesh))
        node = self.extract_node_name
        txt = self.output_format == "txt"
        nrow, shape3 = 0, (0, 0, 0)
        mode = "w" if txt else "wb"
        with open_stream(self.name_pred, mode) as f:
            for batch in itr:
                feats = trainer.extract_feature(batch, node)
                if feats.ndim == 4:      # NHWC -> reference (ch, y, x)
                    feats = feats.transpose(0, 3, 1, 2)
                    shape3 = feats.shape[1:]
                else:
                    feats = feats.reshape(feats.shape[0], -1)
                    shape3 = (1, 1, feats.shape[1])
                nrow += feats.shape[0]
                if txt:
                    flat = feats.reshape(feats.shape[0], -1)
                    for row in flat:
                        f.write(" ".join("%g" % x for x in row) + "\n")
                else:
                    f.write(np.ascontiguousarray(
                        feats, dtype="<f4").tobytes())
        # shape sidecar: "nrow,ch,y,x" (cxxnet_main.cpp:418)
        with open_stream(self.name_pred + ".meta", "w") as fm:
            fm.write("%d,%d,%d,%d\n" % ((nrow,) + tuple(shape3)))
        self._mon.line("finished feature extraction, write into %s"
                       % self.name_pred)
        if self._mon.enabled:
            self._mon.emit("task_end", task="extract",
                           outfile=self.name_pred, rows=nrow)
        return 0

    def _task_get_weight(self, trainer) -> int:
        assert self.weight_layer, "get_weight requires weight_layer"
        if self._mon.enabled:
            self._mon.emit("run_start", **run_metadata(
                "get_weight", self._cfg_stream, trainer.mesh))
        w = trainer.get_weight(self.weight_layer, self.weight_tag)
        rows = w.reshape(w.shape[0], -1) if w.ndim > 1 else w[None, :]
        if self.output_format == "txt":
            with open_stream(self.weight_filename, "w") as f:
                np.savetxt(f, rows, fmt="%g")
        else:                            # raw float32 (cxxnet_main:350)
            with open_stream(self.weight_filename, "wb") as f:
                f.write(np.ascontiguousarray(rows, "<f4").tobytes())
        self._mon.line("weight %s:%s %s written to %s"
                       % (self.weight_layer, self.weight_tag, w.shape,
                          self.weight_filename))
        if self._mon.enabled:
            self._mon.emit("task_end", task="get_weight",
                           outfile=self.weight_filename)
        return 0


def main(argv: Optional[List[str]] = None) -> int:
    return LearnTask().run(sys.argv[1:] if argv is None else argv)


if __name__ == "__main__":
    sys.exit(main())

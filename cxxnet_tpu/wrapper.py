"""User-facing Python API: ``DataIter``, ``Net``, ``train``.

Same surface as the reference's Python wrapper
(``/root/reference/wrapper/cxxnet.py:65-308``), which wrapped the C ABI
with ctypes. Here the framework *is* Python, so these classes sit
directly on the core; the C ABI (``wrapper/cxxnet_wrapper.cc``) embeds
the interpreter and dispatches to this same module, keeping one backend
for Python, C, and Matlab callers.

Layout convention at this boundary is the reference's: 4-D batches are
``(batch, channel, height, width)`` (NCHW) numpy float32; labels are
``(batch, label_width)``. Internally the framework stores spatial nodes
NHWC for the MXU — conversion happens here, once, at the API edge.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from .io import create_iterator
from .io.data import DataBatch
from .nnet.trainer import NetTrainer
from .utils.config import parse_config, split_sections


def _nchw_to_internal(data: np.ndarray, is_mat: bool) -> np.ndarray:
    """(b,c,h,w) user array -> internal NHWC / (b,features) layout."""
    data = np.asarray(data, np.float32)
    if data.ndim != 4:
        raise ValueError(
            "need a 4 dimensional tensor (batch, channel, height, width)")
    if is_mat:
        b, c, h, w = data.shape
        if c == 1 and h == 1:
            return data.reshape(b, w)
        return data.reshape(b, -1)
    return np.transpose(data, (0, 2, 3, 1))


def _internal_to_nchw(data: np.ndarray) -> np.ndarray:
    """internal NHWC / (b,features) -> (b,c,h,w) user array."""
    data = np.asarray(data)
    if data.ndim == 2:
        return data.reshape(data.shape[0], 1, 1, data.shape[1])
    return np.transpose(data, (0, 3, 1, 2))


class DataIter:
    """Data iterator (reference cxxnet.py:65-103).

    ``cfg`` is config text containing one iterator block, e.g.::

        iter = mnist
        path_img = ...
        iter = end

    plus any batch params (batch_size, input_shape, label_width).
    """

    def __init__(self, cfg: str):
        pairs = parse_config(cfg)
        blocks, global_cfg = split_sections(pairs)
        if not blocks:
            raise ValueError("DataIter config contains no iterator block")
        if len(blocks) > 1:
            raise ValueError("DataIter config must contain exactly one "
                             "iterator block")
        batch_cfg = [(k, v) for k, v in global_cfg
                     if k in ("batch_size", "input_shape", "label_width")]
        self._it = create_iterator(blocks[0]["cfg"], batch_cfg)
        self._it.init()
        self.head = True
        self.tail = False

    def next(self) -> bool:
        ok = self._it.next()
        self.head = False
        self.tail = not ok
        return ok

    def before_first(self) -> None:
        self._it.before_first()
        self.head = True
        self.tail = False

    def check_valid(self) -> None:
        if self.head:
            raise RuntimeError(
                "iterator was at head state, call next to get to valid "
                "state")
        if self.tail:
            raise RuntimeError("iterator reaches end")

    @property
    def batch(self) -> DataBatch:
        self.check_valid()
        return self._it.value()

    def get_data(self) -> np.ndarray:
        """Current batch data in (batch, channel, height, width)."""
        return _internal_to_nchw(self.batch.data)

    def get_label(self) -> np.ndarray:
        """Current batch label (batch, label_width)."""
        lab = np.asarray(self.batch.label, np.float32)
        if lab.ndim == 1:
            lab = lab.reshape(-1, 1)
        return lab

    def __iter__(self):
        self.before_first()
        while self.next():
            yield self.batch


class Net:
    """Neural net object (reference cxxnet.py:108-280).

    ``dev`` selects the accelerator ('tpu' is the default; 'cpu' forces
    the host platform — useful for debugging; 'gpu:<n>' strings from
    reference configs are accepted and treated as the default device).
    ``cfg`` is config text with the netconfig block and globals.
    """

    def __init__(self, dev: str = "tpu", cfg: str = ""):
        if dev.startswith("cpu"):
            import jax
            jax.config.update("jax_platforms", "cpu")
        self._cfg = parse_config(cfg) if cfg else []
        if self._cfg:
            self._validate_netconfig(self._cfg)
        self._extra: List[Tuple[str, str]] = []
        self._trainer: Optional[NetTrainer] = None
        self._round = 0
        self._pred_buckets = None        # pred-shape ladder, built lazily

    @staticmethod
    def _validate_netconfig(cfg) -> None:
        """Reject bad structure/layer types at creation time, so C ABI
        callers get NULL from CXNNetCreate instead of a deferred
        failure (the reference net is built eagerly in CXNNetCreate)."""
        from .graph import NetGraph
        from .layers import known_layer_type
        g = NetGraph()
        g.configure(cfg)
        for li, info in enumerate(g.layers):
            if info.type == "share":
                continue
            if not known_layer_type(info.type):
                raise ValueError("unknown layer type %r (layer %d)"
                                 % (info.type, li))

    # -- config / lifecycle ---------------------------------------------

    def set_param(self, name, value) -> None:
        self._extra.append((str(name), str(value)))

    def _make_trainer(self) -> NetTrainer:
        if self._trainer is None:
            self._trainer = NetTrainer(list(self._cfg) + self._extra)
        return self._trainer

    def init_model(self) -> None:
        self._make_trainer().init_model()

    def load_model(self, fname: str) -> None:
        self._make_trainer().load_model(fname)

    def save_model(self, fname: str) -> None:
        self._req().save_model(fname)

    def _req(self) -> NetTrainer:
        if self._trainer is None or not self._trainer._initialized:
            raise RuntimeError("call init_model or load_model first")
        return self._trainer

    def start_round(self, round_counter: int) -> None:
        self._round = round_counter
        self._req().start_round(round_counter)

    def counters(self) -> Dict[str, float]:
        """Training-progress snapshot for polling callers (the C-ABI
        parity surface): ``steps`` (jitted dispatches), ``examples``
        (real rows consumed), ``last_round_examples_per_sec``
        (throughput of the last completed ``start_round`` window).
        Host-side ints only — safe to call from another thread at any
        frequency without forcing a device sync."""
        return self._req().counters_snapshot()

    # -- data plumbing ---------------------------------------------------

    def _to_batch(self, data, label=None) -> DataBatch:
        if isinstance(data, DataIter):
            return data.batch
        data = np.asarray(data, np.float32)
        t = self._req()
        is_mat = t.net.node_shapes[0].is_mat
        arr = _nchw_to_internal(data, is_mat)
        if label is not None:
            label = np.asarray(label, np.float32)
            if label.ndim == 1:
                label = label.reshape(-1, 1)
            if label.ndim != 2:
                raise ValueError("label must be 1-D or 2-D")
            if label.shape[0] != arr.shape[0]:
                raise ValueError("Net.update: data size mismatch")
        return DataBatch(data=arr, label=label)

    def _bucket_pred_batch(self, batch: DataBatch) -> DataBatch:
        """Round a pred/extract batch up to its bucket so repeat calls
        at varying sizes (e.g. a final partial batch) reuse one
        compiled executable per bucket instead of compiling per size.

        Pure shape policy via the serve bucketing helper: padded rows
        ride the ``num_batch_padd`` mask and are sliced off the result,
        so output is row-identical to the unpadded dispatch (pinned by
        tests). Already-padded iterator batches pass through."""
        from .serve.bucketing import (bucket_ladder, pad_to_bucket,
                                      pick_bucket)
        if batch.num_batch_padd:
            return batch
        t = self._req()
        if self._pred_buckets is None:
            align = dict(t.mesh.shape).get("data", 1)
            self._pred_buckets = bucket_ladder(t.batch_size,
                                               align=align)
        n = batch.batch_size
        bucket = pick_bucket(n, self._pred_buckets, extend=True)
        if bucket == n:
            return batch
        data, npad = pad_to_bucket(np.asarray(batch.data), bucket)
        label = batch.label
        if label is not None:
            label, _ = pad_to_bucket(np.asarray(label), bucket)
        return DataBatch(
            data=data, label=label, num_batch_padd=npad,
            extra_data=[pad_to_bucket(np.asarray(e), bucket)[0]
                        for e in batch.extra_data])

    # -- training / inference --------------------------------------------

    def update(self, data, label=None):
        """One training step on a batch (DataIter or NCHW ndarray+label)."""
        if isinstance(data, np.ndarray) and label is None:
            raise ValueError("Net.update: need label to use update")
        self._req().update(self._to_batch(data, label))

    def evaluate(self, data, name: str) -> str:
        """Full eval pass over a DataIter; returns the metric string."""
        if not isinstance(data, DataIter):
            raise TypeError("evaluate needs a DataIter")
        return self._req().evaluate(iter(data), name)

    def predict(self, data) -> np.ndarray:
        """Predicted class index (or scalar output) per row. Inputs
        pad to a batch-size bucket (doc/serving.md) so varying caller
        batch sizes reuse a handful of compiled executables."""
        batch = data.batch if isinstance(data, DataIter) \
            else self._to_batch(data)
        return self._req().predict(self._bucket_pred_batch(batch))

    def extract(self, data, name: str) -> np.ndarray:
        """Extract a named node's activations ('top[-k]' supported).
        Bucket-padded like :meth:`predict`."""
        batch = data.batch if isinstance(data, DataIter) \
            else self._to_batch(data)
        out = self._req().extract_feature(self._bucket_pred_batch(batch),
                                          name)
        return _internal_to_nchw(out)      # flat nodes -> (b,1,1,f)

    # -- weights ---------------------------------------------------------

    def set_weight(self, weight: np.ndarray, layer_name: str,
                   tag: str) -> None:
        if tag not in ("bias", "wmat"):
            raise ValueError("tag must be bias or wmat")
        t = self._req()
        weight = np.asarray(weight, np.float32)
        cur = t.get_weight(layer_name, tag)     # reference-layout shape
        if weight.shape != cur.shape:
            if weight.size != cur.size:
                raise ValueError(
                    "set_weight %s:%s: size %d does not match %d"
                    % (layer_name, tag, weight.size, cur.size))
            weight = weight.reshape(cur.shape)  # flat C-ABI input
        t.set_weight(layer_name, tag, weight)

    def get_weight(self, layer_name: str, tag: str) -> Optional[np.ndarray]:
        if tag not in ("bias", "wmat"):
            raise ValueError("tag must be bias or wmat")
        t = self._req()
        if layer_name not in t.params or tag not in t.params[layer_name]:
            return None
        return t.get_weight(layer_name, tag)


def train(cfg: str, data, num_round: int, param, eval_data=None,
          label=None) -> Net:
    """Train a net from config text (reference cxxnet.py:281-308).

    data: DataIter, or NCHW ndarray with ``label``.
    param: dict or (key, value) pairs applied via set_param.
    """
    net = Net(cfg=cfg)
    if isinstance(param, dict):
        param = param.items()
    for k, v in param:
        net.set_param(k, v)
    net.init_model()
    if isinstance(data, DataIter):
        for r in range(num_round):
            net.start_round(r)
            data.before_first()
            scounter = 0
            while data.next():
                net.update(data)
                scounter += 1
                if scounter % 100 == 0:
                    print("[%d] %d batch passed" % (r, scounter))
            if eval_data is not None:
                seval = net.evaluate(eval_data, "eval")
                print("[%d]%s" % (r, seval))
    else:
        if label is None:
            raise ValueError("train from ndarray needs label=")
        for r in range(num_round):
            net.start_round(r)
            net.update(data=data, label=label)
    return net

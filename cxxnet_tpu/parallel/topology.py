"""Host topology: the (hosts x local-devices) shape the mesh and the
input shard map derive from — and the single-process *dryrun* that
fakes it.

The reference scales across machines through rabit/ps-lite workers
(SURVEY.md §2.7, example/multi-machine/run.sh); the TPU-native
equivalent is one SPMD program over a mesh whose **data axis spans
hosts x local devices** while the **model axis stays within a host**
(collectives on the model axis run every layer — they belong on ICI,
never on DCN). This module owns that topology decision:

- :func:`current_topology` — the (num_hosts, host_rank, local devices)
  triple, read from ``jax`` for real multi-process runs or from the
  faked dryrun state below.
- :func:`set_dryrun_topology` / :func:`clear_dryrun_topology` — the
  single-process multi-host **dryrun**: ``dist_dryrun_hosts = H``
  partitions the input pipeline into H virtual hosts (each reading
  only its deterministic record shard and producing only its slice of
  the global batch) while the device mesh stays the process's real
  devices. The full shard math — mesh build, per-host batch assembly,
  shard-map re-derivation — runs in tier-1 with zero recompiles and a
  loss trajectory bit-identical to the single-host run on the same
  global batch, because the assembled global batch IS the single-host
  batch (doc/distributed.md "Dryrun vs real").
- :class:`DryrunFeed` — the dryrun batch assembler: one batch-level
  iterator chain per virtual host, concatenated in host-rank order —
  exactly the row order ``jax.make_array_from_process_local_data``
  gives a real multi-host run (each process's local rows land in
  ascending process order), so the dryrun validates the real
  assembly's data order, not a lookalike.

What the dryrun deliberately does NOT fake: cross-process collectives
(there is one process), DCN transport, per-host clock skew. Scaling
numbers from a dryrun measure shard math and input cost, never
interconnect — MULTICHIP records say so (the r07/r08 convention).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..io.data import DataBatch, IIterator

# faked host count installed by set_dryrun_topology (0 = real topology)
_dryrun_hosts = 0


class HostTopology:
    """The (hosts, local devices) shape of the fleet.

    ``num_hosts``/``host_rank`` are the INPUT topology — what the
    reader shard map partitions over. For a real multi-process run
    they equal ``jax.process_count()``/``process_index()``; under the
    dryrun they are the faked host count (rank is meaningless: one
    process drives every virtual host). ``local_device_count`` is the
    per-host device count the model axis must stay within.
    """

    __slots__ = ("num_hosts", "host_rank", "local_device_count",
                 "dryrun")

    def __init__(self, num_hosts: int, host_rank: int,
                 local_device_count: int, dryrun: bool = False):
        self.num_hosts = int(num_hosts)
        self.host_rank = int(host_rank)
        self.local_device_count = int(local_device_count)
        self.dryrun = bool(dryrun)

    @property
    def world_devices(self) -> int:
        return self.num_hosts * self.local_device_count

    def describe(self) -> Dict[str, Any]:
        """Telemetry/snapshot-meta form (``dist_topology`` record and
        the snapshot ``topology`` entry both carry this)."""
        return {"hosts": self.num_hosts,
                "local_devices": self.local_device_count,
                "world_devices": self.world_devices,
                "dryrun": self.dryrun}


def set_dryrun_topology(num_hosts: int) -> HostTopology:
    """Install the faked multi-host topology: ``num_hosts`` virtual
    hosts partitioning this single process's devices. Requires a
    single-process runtime (a real multi-process run already HAS a
    topology) and a host count that divides the device count (each
    virtual host owns an equal local slice). Returns the topology;
    callers must :func:`clear_dryrun_topology` when done — main.py
    clears in its task ``finally`` so library users never inherit a
    stale fake."""
    global _dryrun_hosts
    import jax
    assert jax.process_count() == 1, \
        "dist_dryrun_hosts fakes a topology; a real multi-process " \
        "run already has one"
    ndev = len(jax.devices())
    n = int(num_hosts)
    if n < 1 or ndev % n != 0:
        raise ValueError(
            "dist_dryrun_hosts=%d must divide the %d available "
            "devices (each virtual host owns an equal local slice)"
            % (n, ndev))
    _dryrun_hosts = n
    return current_topology()


def clear_dryrun_topology() -> None:
    global _dryrun_hosts
    _dryrun_hosts = 0


def current_topology() -> HostTopology:
    """The active topology: faked when a dryrun is installed, else the
    real jax process topology."""
    import jax
    if _dryrun_hosts > 1:
        return HostTopology(_dryrun_hosts, 0,
                            len(jax.devices()) // _dryrun_hosts,
                            dryrun=True)
    return HostTopology(jax.process_count(), jax.process_index(),
                        len(jax.local_devices()))


# -- the dryrun batch assembler -------------------------------------------


class DryrunFeed(IIterator):
    """Assemble global batches from one batch-level iterator per
    virtual host, concatenated in host-rank order.

    Mirrors ``jax.make_array_from_process_local_data`` row order: the
    global batch's rows are host 0's local rows, then host 1's, ...
    With the batch-block shard map (:mod:`cxxnet_tpu.io.shard`) each
    host's slice is exactly its contiguous span of the single-host
    batch, so the assembled batch is BIT-IDENTICAL to the unsharded
    read — the dryrun's headline invariant.

    Per-host accounting rides along: real (non-padded) rows consumed
    per host and the wall time spent blocked on each host's chain
    (the per-host data-wait of the scaling record). Padding must form
    a suffix of the global batch (real rows fill positions in record
    order under the batch-block map); the assembler asserts it rather
    than silently mis-masking.
    """

    def __init__(self, host_iters: Sequence[IIterator]):
        assert len(host_iters) >= 1
        self.hosts: List[IIterator] = list(host_iters)
        self._out: Optional[DataBatch] = None
        self.rows_per_host = [0] * len(self.hosts)
        self.wait_s_per_host = [0.0] * len(self.hosts)
        self.batches = 0
        # last batch each host produced: the shape template for the
        # all-padding slice an exhausted high-rank host contributes
        # while lower ranks still hold the dataset's real tail
        self._template: List[Optional[DataBatch]] = \
            [None] * len(self.hosts)

    # set_param is deliberately absent from forwarding: the per-host
    # chains are fully configured by build_dryrun_feed before assembly

    def init(self) -> None:
        for it in self.hosts:
            it.init()

    def before_first(self) -> None:
        for it in self.hosts:
            it.before_first()

    def next(self) -> bool:
        got: List[Optional[DataBatch]] = []
        any_live = False
        for h, it in enumerate(self.hosts):
            t0 = time.perf_counter()
            ok = it.next()
            self.wait_s_per_host[h] += time.perf_counter() - t0
            if ok:
                b = it.value()
                self._template[h] = b
                got.append(b)
                any_live = True
            else:
                got.append(None)
        if not any_live:
            return False
        # a dataset whose size is not a batch multiple leaves the
        # final global batch's high-position slices empty: those
        # hosts' chains exhaust one batch early, but the fleet must
        # still dispatch the batch in lockstep (a real rank does —
        # every rank pads; see trainer._mask). Exhausted hosts
        # contribute an all-padding slice shaped like their last
        # batch. The batch-block map guarantees only HIGH ranks can
        # exhaust early (real records fill positions in order), so a
        # live host after an exhausted one is a shard-config bug.
        parts: List[DataBatch] = []
        seen_dead = False
        for h, b in enumerate(got):
            if b is None:
                if not seen_dead and any(x is not None
                                         for x in got[h + 1:]):
                    raise AssertionError(
                        "dryrun host %d exhausted while a later host "
                        "still produces — the batch-block shard map "
                        "never does this (foreign shard config?)" % h)
                seen_dead = True
                tpl = self._template[h]
                if tpl is None:
                    # this host never owned a single record (dataset
                    # smaller than its first slice): borrow any live
                    # host's shapes — all local slices are equal-sized
                    tpl = next(x for x in got if x is not None)
                parts.append(DataBatch(
                    data=np.zeros_like(np.asarray(tpl.data)),
                    label=np.zeros_like(np.asarray(tpl.label)),
                    inst_index=None if tpl.inst_index is None
                    else np.zeros_like(np.asarray(tpl.inst_index)),
                    num_batch_padd=np.asarray(tpl.data).shape[0],
                    extra_data=[np.zeros_like(np.asarray(e))
                                for e in tpl.extra_data]))
            else:
                parts.append(b)
        padd = 0
        for h, b in enumerate(parts):
            real = b.batch_size - b.num_batch_padd
            if padd and real:
                raise AssertionError(
                    "dryrun host %d contributes %d real rows after an "
                    "earlier host padded — per-host padding must form "
                    "a suffix of the global batch (is round_batch=0 "
                    "and shuffle off on every host chain?)" % (h, real))
            padd += b.num_batch_padd
            self.rows_per_host[h] += real
        idx = None
        if all(b.inst_index is not None for b in parts):
            idx = np.concatenate([np.asarray(b.inst_index)
                                  for b in parts])
        n_extra = len(parts[0].extra_data)
        self._out = DataBatch(
            data=np.concatenate([np.asarray(b.data) for b in parts]),
            label=np.concatenate([np.asarray(b.label) for b in parts]),
            inst_index=idx,
            num_batch_padd=padd,
            extra_data=[np.concatenate(
                [np.asarray(b.extra_data[j]) for b in parts])
                for j in range(n_extra)])
        # the concatenates above copied out of any ring buffers; hand
        # the per-host leases back so each chain can reuse its buffers
        for b in parts:
            if b.release is not None:
                b.release()
        self.batches += 1
        return True

    def value(self) -> DataBatch:
        return self._out

    def close(self) -> None:
        for it in self.hosts:
            it.close()

    def accounting(self) -> Dict[str, Any]:
        """Per-host input-shard accounting since construction — the
        ``dist_shard`` record fields and the MULTICHIP
        records-consumed-per-host column (sums exactly to the real
        rows of the dataset per epoch)."""
        return {"hosts": len(self.hosts),
                "rows_per_host": list(self.rows_per_host),
                "wait_ms_per_host": [round(w * 1e3, 3)
                                     for w in self.wait_s_per_host],
                "batches": self.batches}

    def reset_accounting(self) -> None:
        self.rows_per_host = [0] * len(self.hosts)
        self.wait_s_per_host = [0.0] * len(self.hosts)
        self.batches = 0


def localize_block(pairs, hosts: int):
    """Divide every ``batch_size`` in an iterator block's config by the
    host count — each virtual host's chain produces its 1/hosts slice
    of the GLOBAL batch, the same rule main.py applies per process
    under real multi-process dp."""
    if hosts == 1:
        return list(pairs)
    out = []
    for k, v in pairs:
        if k == "batch_size":
            assert int(v) % hosts == 0, \
                "batch_size %s must divide evenly across %d hosts" \
                % (v, hosts)
            v = str(int(v) // hosts)
        out.append((k, v))
    return out


# knobs neutralized on every per-host dryrun chain: the bit-identity
# and exactly-once invariants need deterministic record order (no
# shuffle) and zero-padded tails (round_batch=1 wraps the tail with
# epoch-start records, which would double-count them in the shard
# accounting)
DRYRUN_NEUTRAL = (("shuffle", "0"), ("shuffle_chunk", "0"),
                  ("round_batch", "0"))


def build_dryrun_feed(block_cfg, batch_cfg, hosts: int,
                      global_batch: int,
                      start_record: int = 0) -> DryrunFeed:
    """Build the H per-host iterator chains + assembler for one data
    block — the ONE construction main.py's train path and the bench
    scaling sweep share, so the measured path is the shipped path.

    Each host chain gets the deterministic batch-block shard params
    (``shard_kind = batch``: host h owns rows [h*b, (h+1)*b) of every
    global batch — :mod:`cxxnet_tpu.io.shard`), its 1/H local
    batch_size, and the dryrun neutralizations (shuffle off,
    zero-padded tails)."""
    its = []
    for h in range(hosts):
        cfg_h = localize_block(block_cfg, hosts) + list(DRYRUN_NEUTRAL)
        cfg_h += [("shard_kind", "batch"),
                  ("part_index", str(h)),
                  ("num_parts", str(hosts)),
                  ("shard_global_batch", str(global_batch)),
                  ("shard_start_record", str(start_record))]
        from ..io import create_iterator
        its.append(create_iterator(cfg_h,
                                   localize_block(batch_cfg, hosts)))
    return DryrunFeed(its)

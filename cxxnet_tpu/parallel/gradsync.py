"""Layerwise-overlapped gradient sync: reduction groups + boundaries.

The reference's headline scaling trick is one async updater per weight
tensor that pushes that layer's gradient the moment its backprop
completes, with parameter-server priority = ``-layer_index`` so top
layers sync first (async_updater-inl.hpp; SURVEY.md §2.7). The SPMD
port had, until this module, the degenerate version: XLA inserts ONE
gradient all-reduce wherever its scheduler likes, usually after the
whole backward — correct, but the cross-host (DCN) traffic serializes
behind backprop instead of hiding under it.

This module is the structured equivalent:

* :func:`partition_groups` splits the weight tree into **reduction
  groups** ordered by REVERSE layer index — per-layer groups by
  default, or size-bucketed (``grad_sync_bucket_mb``) so tiny layers
  amortize one collective's latency floor. Every tensor lands in
  exactly one group (property-tested), and group 0 holds the topmost
  layers — the ones whose backward finishes first.
* :func:`apply_group_boundaries` pins a ``jax.custom_vjp`` identity
  around each group's parameters inside the differentiated loss. The
  forward is a no-op; the backward joins the group's cotangents (the
  gradients) with one ``jax.lax.optimization_barrier``, making each
  group an atomic, independently schedulable unit: XLA can no longer
  fuse the per-group all-reduces into one tail collective, and its
  latency-hiding scheduler is free to issue group g's reduction the
  moment g's backward completes — while the remaining (earlier-layer)
  backprop still runs. The issue order is the backprop completion
  order, i.e. reverse layer index — exactly the reference's priority
  rule, now emergent from data flow instead of a priority queue.

Numerically the boundary is the identity, so ``grad_sync = overlap``
is bit-identical to ``fused`` — same semantics, different schedule —
pinned by the dryrun parity tests at H=2 and H=4
(tests/test_gradsync.py).

:func:`measure_step_breakdown` is the measurement half: the
schema-validated ``step_breakdown`` record (backprop ms, reduce ms,
overlap ratio, optimizer-state bytes/host) behind ``bench.py --hosts``
and :mod:`.scaling`. A CPU dryrun's collectives are shared-memory
copies, not DCN — the record says so; device columns stay pending a
chip window (ROADMAP item 5).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Mapping, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .topology import current_topology

GroupKey = Tuple[str, str]               # (layer key, weight tag)


@dataclass(frozen=True)
class ReductionGroup:
    """One reduction group: a contiguous run of the reverse-layer-
    ordered weight list that syncs as a single collective unit."""
    index: int                           # issue order (0 syncs first)
    keys: Tuple[GroupKey, ...]           # (layer, tag) members
    nbytes: int                          # summed logical bytes
    layer_span: Tuple[int, int]          # (max, min) layer index


def _leaf_bytes(leaf) -> int:
    return int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize


def partition_groups(params: Mapping[str, Mapping[str, Any]],
                     layer_index: Mapping[str, int],
                     bucket_mb: float = 0.0
                     ) -> List[ReductionGroup]:
    """Partition the weight tree into reduction groups.

    Weights are ordered by reverse layer index (top layers first — the
    reference's PS priority = ``-layer_index``), tie-broken by (layer
    key, tag) so the partition is deterministic for any dict order.
    ``bucket_mb <= 0``: one group per layer (all of a layer's tags sync
    together). ``bucket_mb > 0``: greedy size bucketing — a group
    closes once it holds at least ``bucket_mb`` MB, so sub-bucket
    layers merge into one collective (the latency floor of a DCN
    all-reduce dwarfs a small tensor's payload) while a tensor is
    never split across groups. Every (layer, tag) lands in exactly one
    group at any bucket size (tests/test_gradsync.py property test).
    """
    order = sorted(
        ((lk, tag) for lk, pt in params.items() for tag in pt),
        key=lambda kt: (-int(layer_index[kt[0]]), kt[0], kt[1]))
    groups: List[ReductionGroup] = []
    cur: List[GroupKey] = []
    cur_bytes = 0
    bucket_bytes = float(bucket_mb) * (1 << 20)

    def close():
        nonlocal cur, cur_bytes
        if not cur:
            return
        lis = [int(layer_index[lk]) for lk, _ in cur]
        groups.append(ReductionGroup(
            index=len(groups), keys=tuple(cur), nbytes=cur_bytes,
            layer_span=(max(lis), min(lis))))
        cur, cur_bytes = [], 0

    prev_li = None
    for lk, tag in order:
        li = int(layer_index[lk])
        if bucket_bytes <= 0 and prev_li is not None and li != prev_li:
            close()                      # per-layer mode: layer edge
        cur.append((lk, tag))
        cur_bytes += _leaf_bytes(params[lk][tag])
        prev_li = li
        if bucket_bytes > 0 and cur_bytes >= bucket_bytes:
            close()
    close()
    return groups


# -- the boundary: numeric identity, scheduling unit ----------------------

@jax.custom_vjp
def _group_boundary(xs):
    return xs


def _group_boundary_fwd(xs):
    return xs, None


def _group_boundary_bwd(_, cts):
    # joint barrier over the group's cotangents: the gradients become
    # one atomic bundle the scheduler places as a unit, and the
    # SPMD-inserted all-reduce that consumes them hangs off the bundle
    # as an independently issuable collective. Identity numerics.
    return (jax.lax.optimization_barrier(cts),)


_group_boundary.defvjp(_group_boundary_fwd, _group_boundary_bwd)


def apply_group_boundaries(params, groups: Sequence[ReductionGroup]):
    """Thread each group's parameters through its boundary; returns a
    tree with identical structure and values. Call INSIDE the
    differentiated loss so the backward barriers land in the gradient
    graph. Keys absent from ``params`` (a pruned tree) are skipped —
    the boundary set follows the tree it is applied to."""
    out = {lk: dict(pt) for lk, pt in params.items()}
    for g in groups:
        keys = [(lk, tag) for lk, tag in g.keys
                if lk in out and tag in out[lk]]
        if not keys:
            continue
        marked = _group_boundary(tuple(out[lk][tag] for lk, tag in keys))
        for (lk, tag), v in zip(keys, marked):
            out[lk][tag] = v
    return out


# -- byte accounting ------------------------------------------------------

def tree_logical_bytes(tree) -> int:
    """Summed logical (unsharded) bytes of every array leaf."""
    return sum(_leaf_bytes(x) for x in jax.tree_util.tree_leaves(tree)
               if hasattr(x, "shape"))


def host_resident_bytes(tree) -> int:
    """Distinct bytes of ``tree`` resident on ONE host: unique shard
    slices across host 0's device block (the dryrun partitions
    ``jax.devices()`` into equal rank-ordered blocks; a real
    multi-process run's addressable shards are already one host's).
    Replicated leaves count once — each of the host's devices holds
    the same slice; ZeRO-sharded leaves count the host's disjoint
    1/world slices, i.e. ~1/hosts of the logical bytes."""
    topo = current_topology()
    host0 = set(jax.devices()[:topo.local_device_count])
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if not isinstance(leaf, jax.Array):
            if hasattr(leaf, "shape"):
                total += _leaf_bytes(leaf)
            continue
        seen = set()
        for s in leaf.addressable_shards:
            if s.device not in host0:
                continue
            key = tuple((sl.start, sl.stop, sl.step) for sl in s.index)
            if key in seen:
                continue
            seen.add(key)
            total += int(np.prod(s.data.shape)) \
                * np.dtype(s.data.dtype).itemsize
    return total


def frozen_group_count(opt_state) -> int:
    """(layer, tag) groups whose optimizer state was skipped (the
    ``lr_mult = 0`` frozen-group allocation skip, doc/updater.md)."""
    return sum(1 for tags in opt_state.values()
               for st in tags.values() if not st)


# -- the step_breakdown measurement ---------------------------------------

def _time_ms(fn: Callable[[], Any], repeats: int) -> float:
    """Best-of-``repeats`` wall of ``fn`` (first call warms/compiles
    outside the timed window), blocking on the result."""
    jax.block_until_ready(fn())
    best = float("inf")
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


def measure_step_breakdown(trainer, batch, repeats: int = 3
                           ) -> Dict[str, Any]:
    """Measure the ``step_breakdown`` record on a live trainer.

    Times three programs on the trainer's current weights and the given
    batch: the gradient program alone (forward + backward + the grads'
    own reduction), a reduction-only program over gradient-shaped
    buffers (the collective at the mode's group granularity — one
    barrier-joined ``psum`` bundle per reduction group), and the full
    train step via real ``trainer.update`` dispatches. The overlap
    ratio is the fraction of a standalone reduce pass the full step
    hides: ``clamp01((backprop_ms + reduce_ms - step_ms) /
    reduce_ms)``. Optimizer-state bytes report both the logical
    (unsharded) footprint and the distinct bytes resident per host —
    under ``optim_shard = 1`` the per-host number drops to ~1/hosts.

    Honesty: this advances the trainer by ``repeats + 1`` real updates
    (call it at a measurement boundary, as bench/scaling do), and on a
    CPU dryrun every collective is a shared-memory copy, not DCN — the
    timings bound the schedule shape only; device columns stay pending
    a chip window (doc/distributed.md).
    """
    data, labels, mask, extra = trainer._device_batch(batch)
    net = trainer.net
    mesh = trainer.mesh
    key = trainer._base_key
    net_state = trainer.net_state
    groups = getattr(trainer, "_sync_groups", None)
    if groups is None:                   # fused: one monolithic group
        groups = partition_groups(trainer.params, trainer._layer_index,
                                  bucket_mb=float("inf"))
    overlap = trainer.grad_sync == "overlap"

    def _loss(p):
        loss, _aux = net.loss_fn(
            p, net_state, data, labels, mask, extra=extra, rng=key,
            collect_nodes=())
        return loss

    def _grad_only(p):
        if overlap:
            p = apply_group_boundaries(p, groups)
        return jax.grad(_loss)(p)

    grad_prog = jax.jit(_grad_only)

    def _reduce_only(grads):
        def per_shard(g):
            out = {lk: dict(pt) for lk, pt in g.items()}
            for grp in groups:
                keys = [(lk, tag) for lk, tag in grp.keys
                        if lk in out and tag in out[lk]]
                if not keys:
                    continue
                red = jax.lax.optimization_barrier(tuple(
                    jax.lax.psum(out[lk][tag], "data")
                    for lk, tag in keys))
                for (lk, tag), v in zip(keys, red):
                    out[lk][tag] = v
            return out
        from jax.experimental.shard_map import shard_map
        return shard_map(per_shard, mesh=mesh,
                         in_specs=P(), out_specs=P())(grads)

    reduce_prog = jax.jit(_reduce_only)

    grads = grad_prog(trainer.params)
    backprop_ms = _time_ms(lambda: grad_prog(trainer.params), repeats)
    reduce_ms = _time_ms(lambda: reduce_prog(grads), repeats)

    def one_step():
        trainer.update(batch)
        return trainer.params

    step_ms = _time_ms(one_step, repeats)
    overlap_ratio = 0.0
    if reduce_ms > 0:
        overlap_ratio = max(0.0, min(
            1.0, (backprop_ms + reduce_ms - step_ms) / reduce_ms))
    opt_unsharded = tree_logical_bytes(trainer.opt_state)
    return {
        "hosts": current_topology().num_hosts,
        "grad_sync": trainer.grad_sync,
        "optim_shard": int(trainer.shard_optimizer),
        "groups": len(groups),
        "bucket_mb": float(trainer.grad_sync_bucket_mb),
        "backprop_ms": round(backprop_ms, 4),
        "reduce_ms": round(reduce_ms, 4),
        "step_ms": round(step_ms, 4),
        "overlap_ratio": round(overlap_ratio, 4),
        "grad_bytes": tree_logical_bytes(grads),
        "opt_state_bytes_unsharded": opt_unsharded,
        "opt_state_bytes_per_host": host_resident_bytes(
            trainer.opt_state),
        "frozen_groups": frozen_group_count(trainer.opt_state),
    }

"""Parallelism: device mesh, shardings, multi-host init.

This module replaces the reference's entire parallel stack — per-GPU
worker threads + semaphores (neural_net-inl.hpp:325-658), the layerwise
async parameter server (mshadow-ps, async_updater-inl.hpp), and the
rabit/ps-lite distributed backends (SURVEY.md §2.7) — with the TPU-native
equivalent: ONE SPMD XLA program over a ``jax.sharding.Mesh``.

Capability mapping (reference -> here):
- multi-GPU batch split + local PS gradient sum  -> batch sharded on the
  'data' mesh axis; XLA inserts the all-reduce over ICI during autodiff
- layerwise async push/pull overlap (priority = -layer_index) -> XLA's
  latency-hiding scheduler overlaps those same collectives with compute
- fullc_gather (ship activations, recompute full grad) -> sharded matmul:
  fullc weights sharded on the 'model' axis, XLA all-gathers activations
- update_on_server (optimizer state on server) -> optimizer state sharded
  across 'data' (ZeRO-style), toggled per config
- rabit eval-metric allreduce -> process-group sum over DCN
- multi-node launch (dmlc tracker/MPI) -> jax.distributed.initialize
"""

from __future__ import annotations

import os
import time
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .topology import (HostTopology, clear_dryrun_topology,
                       current_topology, set_dryrun_topology)


def force_virtual_cpu(n_devices: int) -> None:
    """Run this process on ``n_devices`` virtual CPU devices — the
    ps-lite local-mode analogue (SURVEY.md §4.5) used by tests and the
    driver's multichip dry-run to exercise sharding without TPU chips.

    Must be called before the jax backend initializes.  Uses jax.config
    (not env vars): this environment preloads jax at interpreter start,
    so JAX_PLATFORMS in os.environ is read too late, and config wins
    over a conflicting --xla_force_host_platform_device_count.

    jax builds that predate the ``jax_num_cpu_devices`` option (< 0.5)
    fall back to the XLA flag, which those builds DO read at backend
    init even when jax was imported earlier.
    """
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except AttributeError:
        # replace (not keep) any conflicting count — this function must
        # win, same as the jax.config path above
        flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append(
            "--xla_force_host_platform_device_count=%d" % n_devices)
        os.environ["XLA_FLAGS"] = " ".join(flags)


def make_mesh(n_data: Optional[int] = None, n_model: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a topology-aware (data, model) mesh.

    Default: all devices on the data axis — the TPU analogue of
    ``dev = gpu:0-3`` (nnet_impl-inl.hpp:374-391). Topology rule
    (doc/distributed.md): the **data axis spans hosts x local
    devices** and the **model axis stays within one host** — model
    collectives run every layer and belong on ICI, never on DCN. With
    ``jax.devices()`` returning devices in process-major order (and
    the dryrun partitioning that order into equal virtual-host
    blocks), a model group of ``n_model`` consecutive devices sits
    within one host exactly when ``n_model`` divides the per-host
    local device count — enforced here, so a config cannot silently
    stripe its every-layer collectives across the slow interconnect.
    """
    if devices is None:
        devices = jax.devices()
    total = len(devices)
    if n_data is None:
        n_data = total // n_model
    use = n_data * n_model
    if use > total:
        raise ValueError("mesh wants %d devices, have %d" % (use, total))
    topo = current_topology()
    if n_model > 1 and topo.num_hosts > 1 \
            and topo.local_device_count % n_model != 0:
        raise ValueError(
            "model axis %d does not divide the %d local devices per "
            "host (%d hosts): the model axis must stay within a host "
            "(ICI before DCN) — shrink n_model or repartition"
            % (n_model, topo.local_device_count, topo.num_hosts))
    arr = np.asarray(devices[:use]).reshape(n_data, n_model)
    return Mesh(arr, ("data", "model"))


def default_data_axis(batch_size: int,
                      n_devices: Optional[int] = None) -> int:
    """The trainer's default mesh rule: the largest data-axis size
    that divides the global batch (the reference similarly drops
    devices that would get an empty slice, nnet_impl-inl.hpp:378-387).
    One definition shared by ``NetTrainer._post_init`` and bench.py's
    ``--compare`` topology guard, so the recorded and expected
    topologies cannot drift."""
    if n_devices is None:
        n_devices = len(jax.devices())
    return max(d for d in range(1, n_devices + 1)
               if batch_size % d == 0)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Batch-dim sharding for input arrays."""
    return NamedSharding(mesh, P("data"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def param_sharding(mesh: Mesh, params, model_parallel_min: int = 0):
    """Sharding pytree for parameters.

    Weights stay replicated except 2-D fullc weights whose output dim is
    divisible by the 'model' axis and exceeds ``model_parallel_min`` —
    those shard on the output dim (the fullc_gather analogue: XLA
    all-gathers the activations and each shard computes its slice).
    """
    msize = mesh.shape["model"]

    def spec(path, leaf):
        if (msize > 1 and model_parallel_min > 0 and hasattr(leaf, "ndim")
                and leaf.ndim == 2
                and leaf.shape[-1] % msize == 0
                and leaf.shape[-1] >= model_parallel_min):
            return NamedSharding(mesh, P(None, "model"))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(spec, params)


def opt_state_sharding(leaf_shape, param_spec: P, mesh: Mesh,
                       shard_data: bool) -> NamedSharding:
    """Sharding for one optimizer-state leaf (momentum / adam moments).

    Default: mirror its weight's sharding. With ``shard_data`` (the
    ``update_on_server=1`` capability analogue — optimizer state leaves
    the replicated pool, like it lived on the server in the reference),
    leaves whose first dim divides the 'data' axis are ZeRO-1 sharded
    across it; XLA then keeps the optimizer update sharded and
    all-gathers only the weights.
    """
    if shard_data:
        dsize = mesh.shape["data"]
        if (len(leaf_shape) >= 1 and leaf_shape[0] % dsize == 0
                and leaf_shape[0] >= dsize
                and (len(param_spec) == 0 or param_spec[0] is None)):
            # compose with the weight's own axes (a model-sharded fullc
            # weight's momentum shards on BOTH 'data' and 'model')
            rest = tuple(param_spec)[1:] if len(param_spec) > 1 else ()
            spec = ("data",) + rest + (None,) * (
                len(leaf_shape) - 1 - len(rest))
            return NamedSharding(mesh, P(*spec))
    return NamedSharding(mesh, P(*param_spec))


_distributed_up = False


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host bring-up over DCN (the rabit::Init / ps-lite tracker
    equivalent, cxxnet_main.cpp:74-91). No-op when single-process or when
    env vars are absent.

    Must run before ANY backend-initializing jax API — so this function
    deliberately reads only the environment (never jax.process_count(),
    which would initialize the backend single-process and lock out
    jax.distributed.initialize).
    """
    global _distributed_up
    if _distributed_up:
        return
    coordinator = coordinator or os.environ.get("CXXNET_COORDINATOR")
    try:  # a launcher may have called jax.distributed.initialize itself
        from jax._src import distributed as _jdist
        if getattr(_jdist.global_state, "client", None) is not None:
            _distributed_up = True
            return
    except Exception as e:
        # only worth a warning when an initialize is actually coming:
        # a single-process run (no coordinator) returns right below
        # and must not print scary distributed warnings
        if coordinator:
            from ..monitor import warn_once
            warn_once("distributed_probe_failed",
                      "cannot probe jax distributed state (%s); if a "
                      "launcher already initialized it, the "
                      "initialize below may fail" % e)
    if not coordinator:
        return
    if num_processes is None:
        env = os.environ.get("CXXNET_NUM_PROCESSES")
        num_processes = int(env) if env else None
    if process_id is None:
        env = os.environ.get("CXXNET_PROCESS_ID")
        process_id = int(env) if env else None
    try:
        # num_processes/process_id may stay None: managed runtimes
        # (TPU pods) let jax.distributed autodetect them — the
        # "env-autodetected where the runtime provides them" half of
        # the dist_* launch contract (doc/distributed.md)
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=None if num_processes is None
            else int(num_processes),
            process_id=None if process_id is None else int(process_id))
    except RuntimeError as e:
        # a launcher beat us to it (the private-module probe above can
        # miss on future jax versions); already-initialized is success
        if "already" not in str(e):
            raise
    _distributed_up = True


# bounded retries for the host-side process-group collectives (the
# eval-metric allreduce): a transient DCN hiccup re-enters the
# collective instead of failing the round. stream_retry-style opt-out:
# set 0 to fail fast (main.py wires `dist_allreduce_retry`, default 2)
_allreduce_retry = 2
_ALLREDUCE_BACKOFF_MS = 50.0


def set_allreduce_retry(n: int) -> None:
    global _allreduce_retry
    _allreduce_retry = max(0, int(n))


def allreduce_host_sum(x: np.ndarray) -> np.ndarray:
    """Sum a small host array across processes (metric reduction — the
    rabit Allreduce in metric.h:60-68) via a process allgather.

    Transient failures (collective timeout, coordination-service
    blips — the DCN failure modes that surface as RuntimeError/OSError
    on every participant) retry up to ``set_allreduce_retry`` times
    with exponential backoff, warn once, and emit a ``dist_retry``
    record on recovery. Retrying a collective is only sound when all
    ranks retry: these transport failures DO surface fleet-wide, and a
    lone rank whose peers somehow advanced times out again, exhausts
    its budget, and raises — the metric layer then falls back to
    process-local values as before (utils/metric.py). Exhaustion
    re-raises; this is a bounded retry, not a swallow."""
    if jax.process_count() == 1:
        return x
    from jax.experimental import multihost_utils
    attempts = 0
    while True:
        try:
            out = np.asarray(
                multihost_utils.process_allgather(x).sum(axis=0))
        except (RuntimeError, OSError) as e:
            attempts += 1
            if attempts > _allreduce_retry:
                raise
            from ..monitor import warn_once
            warn_once("allreduce_retry",
                      "process-group allreduce failed transiently "
                      "(%s: %s); retrying up to %d time(s)"
                      % (type(e).__name__, e, _allreduce_retry))
            time.sleep(_ALLREDUCE_BACKOFF_MS * (2 ** (attempts - 1))
                       / 1e3)
            continue
        if attempts:
            from ..monitor import get_global
            mon = get_global()
            if mon is not None and mon.enabled:
                mon.emit("dist_retry", what="allreduce_host_sum",
                         attempts=attempts, recovered=True)
        return out


def synced_batches(it, window: int = 1):
    """Iterate a per-rank data iterator in lockstep across processes.

    Under multi-process dp, rank-strided sharding can leave ranks with
    local row counts differing by one; when that crosses a local-batch
    multiple, ranks would emit different batch counts and the SPMD
    collectives inside the train/eval step would deadlock. Each rank
    buffers up to ``window`` batches, allgathers its available count
    (ONE host collective per window — pass the train loop's
    dispatch_period to amortize), and the loop yields the cross-rank
    minimum, stopping when any rank comes up short; a richer rank drops
    at most its last ``window`` tail batches per round. Single-process:
    passthrough with zero overhead.
    """
    if jax.process_count() == 1:
        yield from it
        return
    from jax.experimental import multihost_utils
    src = iter(it)
    while True:
        buf = []
        while len(buf) < window:
            try:
                buf.append(next(src))
            except StopIteration:
                break
        counts = np.asarray(multihost_utils.process_allgather(
            np.asarray([len(buf)], np.int32)))
        nmin = int(counts.min())
        for b in buf[:nmin]:
            yield b
        if nmin < window:
            return


def rank() -> int:
    return jax.process_index()


def world_size() -> int:
    return jax.process_count()


def is_root() -> bool:
    """Only rank 0 saves/logs (cxxnet_main.cpp:424-435,501-503)."""
    return jax.process_index() == 0

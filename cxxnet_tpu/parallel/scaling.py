"""The dryrun scaling sweep behind ``bench.py --hosts`` and the
``MULTICHIP_r*.json`` records.

Runs the SAME multi-host input path the CLI trains through
(:func:`cxxnet_tpu.parallel.topology.build_dryrun_feed` — one
batch-block-sharded reader chain per virtual host, assembled in
host-rank order) at a series of faked world sizes, and measures what a
single-process dryrun can honestly measure:

- **throughput** (examples/sec from the trainer's own telemetry
  counters — the same numbers a monitored training run reports),
- **per-host data-wait** (wall time the assembler spent blocked on
  each host's chain) and the data-wait share of step wall time,
- **per-host input-shard accounting** — rows consumed per host, which
  must sum exactly to the dataset's real rows (the exactly-once
  invariant, counted per sweep point),
- **loss parity** — the final loss must be bit-identical across every
  world size (the assembled global batch IS the single-host batch),
- **zero recompiles** after the accounted precompile window.

What it can NOT measure — and says so in the record: cross-host
collective time. A dryrun runs one process with zero DCN traffic, so
the on-chip scaling curve is marked pending a device window (the
r07/r08 convention for device-only columns).
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from . import current_topology, set_dryrun_topology, \
    clear_dryrun_topology
from .topology import build_dryrun_feed

_SCALE_NET = """
netconfig = start
layer[0->1] = fullc:fc1
  nhidden = 64
layer[1->2] = relu
layer[2->3] = fullc:fc2
  nhidden = %(classes)d
layer[3->3] = softmax
netconfig = end
input_shape = 1,1,%(features)d
batch_size = %(batch)d
eta = 0.1
seed = 7
eval_train = 0
silent = 1
"""


def _write_csv(path: str, rows: int, features: int,
               classes: int) -> None:
    rng = np.random.RandomState(11)
    X = rng.rand(rows, features).astype(np.float32)
    y = (X @ rng.randn(features, classes)).argmax(1)
    with open(path, "w") as f:
        for i in range(rows):
            f.write(",".join([str(int(y[i]))]
                             + ["%g" % v for v in X[i]]) + "\n")


def dryrun_scaling_sweep(host_counts: Sequence[int], rows: int = 512,
                         features: int = 64, classes: int = 8,
                         global_batch: int = 64, rounds: int = 2,
                         monitor=None,
                         workdir: Optional[str] = None,
                         grad_sync: str = "fused",
                         grad_sync_bucket_mb: float = 0.0,
                         optim_shard: int = 0
                         ) -> Dict[str, Any]:
    """Measure the dryrun input-sharding path at each world size in
    ``host_counts`` (each must divide the device count and the global
    batch). Emits one schema-validated ``scaling_point`` record per
    world size on ``monitor`` (when enabled) and returns the
    MULTICHIP-style record dict. ``grad_sync`` / ``optim_shard`` run
    the sweep trainer under the overlapped-reduction and ZeRO-1 knobs
    (doc/distributed.md, doc/updater.md); each point then carries a
    ``step_breakdown`` sub-record (also emitted on ``monitor``) with
    the backprop/reduce/step walls, the hidden-reduce overlap ratio,
    and the per-host optimizer-state bytes."""
    from . import gradsync
    from ..monitor import MemorySink, Monitor
    from ..monitor.schema import validate_records
    from ..nnet.trainer import NetTrainer
    from ..utils.config import parse_config
    import jax
    import time as _time

    own_dir = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="cxxnet_scaling_")
    csv = os.path.join(workdir, "scaling.csv")
    _write_csv(csv, rows, features, classes)
    conf = _SCALE_NET % {"features": features, "classes": classes,
                         "batch": global_batch}
    block_cfg = [("iter", "csv"), ("filename", csv),
                 ("input_shape", "1,1,%d" % features),
                 ("label_width", "1"), ("silent", "1")]
    batch_cfg = [("batch_size", str(global_batch)),
                 ("input_shape", "1,1,%d" % features),
                 ("label_width", "1")]

    points: List[Dict[str, Any]] = []
    losses: List[float] = []
    for hosts in host_counts:
        hosts = int(hosts)
        feed = None
        try:
            if hosts > 1:
                set_dryrun_topology(hosts)
            topo = current_topology()
            feed = build_dryrun_feed(block_cfg, batch_cfg, hosts,
                                     global_batch)
            feed.init()
            sink = MemorySink()
            t = NetTrainer(parse_config(conf) + [
                ("grad_sync", grad_sync),
                ("grad_sync_bucket_mb", str(grad_sync_bucket_mb)),
                ("optim_shard", str(int(optim_shard)))])
            t.init_model()
            t.set_monitor(Monitor(sink))
            t.precompile(window=1)
            last_batch = None
            for r in range(rounds):
                t.start_round(r)
                t_wait = _time.perf_counter()
                for batch in feed:
                    t.note_data_wait(_time.perf_counter() - t_wait)
                    t.update(batch)
                    last_batch = batch
                    t_wait = _time.perf_counter()
                t.end_round()
            validate_records(sink.records)
            steps = [r for r in sink.records if r["event"] == "step"]
            wall = sum(r["wall_ms"] for r in steps)
            wait = sum(r["data_wait_ms"] for r in steps)
            share = wait / (wall + wait) if wall + wait > 0 else 0.0
            acc = feed.accounting()
            point = {
                "hosts": hosts,
                "local_devices": topo.local_device_count,
                "global_batch": global_batch,
                "examples_per_sec": round(
                    t.last_round_examples_per_sec, 1),
                "data_wait_share": round(min(1.0, share), 4),
                "rows_per_host": [n // rounds
                                  for n in acc["rows_per_host"]],
                "wait_ms_per_host": [round(w / rounds, 3)
                                     for w in acc["wait_ms_per_host"]],
                "zero_recompiles": not any(r["compile"]
                                           for r in steps),
            }
            losses.append(float(t.last_loss))
            # breakdown AFTER the loss capture: the measurement drives
            # real update dispatches (documented in gradsync), so the
            # parity loss above must be read first
            bd = gradsync.measure_step_breakdown(t, last_batch)
            point["step_breakdown"] = bd
            points.append(point)
            if monitor is not None and monitor.enabled:
                monitor.emit("scaling_point", **point)
                monitor.emit("step_breakdown", **bd)
        finally:
            if feed is not None:
                feed.close()
            clear_dryrun_topology()

    record = {
        "metric": "dryrun examples/sec vs faked world size "
                  "(single-process multi-host input sharding)",
        "dryrun": True,
        "dataset_rows": rows,
        "rounds": rounds,
        "points": points,
        # bit-identity across world sizes: the assembled global batch
        # is the single-host batch, so the final loss must agree to
        # the last bit at every point
        "loss_parity": bool(losses) and all(
            x == losses[0] for x in losses),
        "final_loss": losses[0] if losses else None,
        # exactly-once, counted: per-host consumed rows sum to the
        # dataset at every world size (every record is a real row;
        # tail padding is synthetic and never counted)
        "exactly_once": all(sum(p["rows_per_host"]) == rows
                            for p in points),
        "on_chip": "pending a device window: a dryrun runs one "
                   "process with zero DCN traffic, so this curve "
                   "measures shard math and per-host input cost, "
                   "never interconnect (doc/distributed.md)",
        "grad_sync": grad_sync,
        "grad_sync_bucket_mb": float(grad_sync_bucket_mb),
        "optim_shard": int(optim_shard),
        "breakdown_caveat":
            "step_breakdown walls come from the same dryrun: its "
            "collectives are shared-memory copies, not DCN, so "
            "overlap_ratio bounds the schedule shape only — device "
            "timings pending a window (doc/distributed.md "
            "'Overlapped gradient sync'). Byte columns are exact.",
    }
    if own_dir:
        try:
            os.remove(csv)
            os.rmdir(workdir)
        except OSError:
            pass  # cxxlint: disable=CXL006 -- best-effort tempdir cleanup after the sweep
    return record

"""Post-training low-precision inference: calibration, scales, dequant.

The reference never shipped a quantized path — its speed came from
codegen'd fused f32 kernels. On the MXU the remaining inference lever
is operand width: int8 contractions run at twice the bf16 MAC rate and
a quarter of the f32 HBM bytes (fp8 similarly where the backend
supports it). This module owns everything between a trained f32
snapshot and a servable quantized graph:

* **calibration** (:class:`Calibrator`) — stream an eval iterator
  through the frozen net and record per-channel activation amax at the
  input of every quantizable contraction (conv / fullc), plus
  per-out-channel weight amax over the *eval-folded* weights (the
  ``bn_fold_eval`` fold is part of the served graph, so ranges are
  taken over what serving will actually contract).
* **scales in the snapshot** — ranges ride as ``quant/<layer>/...``
  arrays inside the npz, so the PR 5 content digest covers them and
  ``ckpt_verify`` treats a quantized snapshot as a first-class
  verified artifact; the summary (dtype, batch count, fold state)
  rides in ``__meta__["quantized"]``.
* **activation** (:func:`attach`) — ``serve_dtype = int8|fp8|bf16``
  turns the recorded ranges into symmetric scales (per-tensor for
  activations, per-out-channel for weights) and pins a
  :class:`QuantSpec` on each quantizable layer object; the eval
  forward then quantizes operands on device, contracts in the low
  dtype (int32 / f32 accumulation), and folds the per-channel dequant
  into the conv epilogue (``layers/pallas_kernels.conv_epilogue``).
  Training forwards never consult the spec.

Fallbacks are part of the contract: a backend without native int8/fp8
contraction support still *computes the quantized numbers* (operands
round through the quantized grid but contract in f32 — bit-identical
values, no speedup), and ``serve_dtype = fp8`` on a backend without an
fp8 dtype falls back to int8 scales with one warning. Parity against
the f32 eval output is gated by ``task = quantize``
(doc/perf_profile.md "Low-precision inference").
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

# symmetric quantization grids: int8 keeps -128 out so +/-amax map to
# +/-127 with one scale; fp8 e4m3 saturates at its max finite 448
QMAX = {"int8": 127.0, "fp8": 448.0}

SERVE_DTYPES = ("float32", "bfloat16", "int8", "fp8")

QUANT_PREFIX = "quant/"

# graph layer types whose contraction quantizes (pallas_fullc keeps its
# own kernel path; the torch oracle layer is a test fixture)
_QUANT_TYPES = {"conv": "conv", "fullc": "dot"}

# amax floor: a dead channel (all-zero weights/activations) must not
# produce a zero scale (dequant would divide by it)
_AMAX_FLOOR = 1e-8


def normalize_serve_dtype(val: str) -> str:
    """Canonical ``serve_dtype`` value (accepts the short aliases)."""
    alias = {"f32": "float32", "bf16": "bfloat16", "float8": "fp8",
             "float8_e4m3": "fp8"}
    v = alias.get(val, val)
    if v not in SERVE_DTYPES:
        raise ValueError("serve_dtype must be one of %s (got %r)"
                         % ("|".join(SERVE_DTYPES), val))
    return v


def fp8_dtype():
    """The fp8 storage dtype, or None when this jax build has none."""
    return getattr(jnp, "float8_e4m3fn", None)


_NATIVE_CACHE: Dict[tuple, bool] = {}


def backend_native(dtype: str, op: str) -> bool:
    """True when the backend contracts ``dtype`` operands natively
    (``op`` = 'dot' | 'conv'). Probed once with a tiny op; a backend
    that rejects the dtype falls back to the f32-simulated contraction
    — same values, no speedup."""
    key = (dtype, op, jax.default_backend())
    if key in _NATIVE_CACHE:
        return _NATIVE_CACHE[key]
    ok = False
    try:
        if dtype == "int8":
            qt = jnp.int8
            acc = jnp.int32
        else:
            qt = fp8_dtype()
            acc = jnp.float32
        if qt is not None:
            if op == "dot":
                a = jnp.ones((8, 8), qt)
                out = jnp.dot(a, a, preferred_element_type=acc)
            else:
                x = jnp.ones((1, 4, 4, 8), qt)
                w = jnp.ones((3, 3, 8, 8), qt)
                out = jax.lax.conv_general_dilated(
                    x, w, window_strides=(1, 1), padding="VALID",
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                    preferred_element_type=acc)
            jax.block_until_ready(out)   # one-time capability probe
            ok = True
    except Exception:
        ok = False                       # unsupported: simulate in f32
    _NATIVE_CACHE[key] = ok
    return ok


class QuantSpec:
    """Per-layer runtime recipe, pinned on the layer object by
    :func:`attach`. ``dtype`` is the *effective* quantized dtype
    ('int8' | 'fp8' | 'bfloat16'); scales are symmetric — per-tensor
    for the activation, per-out-channel for the weight."""

    __slots__ = ("dtype", "x_scale", "w_scale", "native")

    def __init__(self, dtype: str, x_scale: float = 1.0,
                 w_scale=None, native: bool = False):
        self.dtype = dtype
        self.x_scale = x_scale
        self.w_scale = w_scale           # jnp (out,) vector, or None
        self.native = native

    @property
    def is_affine(self) -> bool:
        return self.dtype in ("int8", "fp8")

    def dequant_vec(self) -> jnp.ndarray:
        """Per-out-channel dequantization factors (f32): the epilogue
        multiplies the raw accumulator by ``x_scale * w_scale``."""
        return (self.w_scale * jnp.float32(self.x_scale)).astype(
            jnp.float32)

    def quantize_x(self, x: jnp.ndarray) -> jnp.ndarray:
        return quantize_tensor(x, jnp.float32(self.x_scale), self.dtype,
                               self.native)

    def quantize_w(self, w: jnp.ndarray) -> jnp.ndarray:
        return quantize_tensor(w, self.w_scale.astype(jnp.float32),
                               self.dtype, self.native)

    def acc_dtype(self):
        """preferred_element_type for the quantized contraction."""
        if self.native and self.dtype == "int8":
            return jnp.int32
        return jnp.float32


def quantize_tensor(v: jnp.ndarray, scale, dtype: str,
                    native: bool) -> jnp.ndarray:
    """Symmetric quantization onto the ``dtype`` grid. ``scale``
    broadcasts over the last (out-channel) axis for weights or is a
    scalar for activations. Non-native backends keep the values on the
    quantized grid but store them f32, so the simulated contraction
    computes the same numbers the native one would (int8 exactly; fp8
    modulo the accumulator — both inside the parity gate)."""
    qmax = QMAX[dtype]
    vf = v.astype(jnp.float32) / scale
    if dtype == "int8":
        q = jnp.clip(jnp.round(vf), -qmax, qmax)
        return q.astype(jnp.int8) if native else q
    q = jnp.clip(vf, -qmax, qmax)
    f8 = fp8_dtype()
    q = q.astype(f8)                     # e4m3 mantissa rounding
    return q if native else q.astype(jnp.float32)


class QuantTarget(NamedTuple):
    li: int                              # layer (connection) index
    lkey: str                            # param layer key (table key)
    in_node: int                         # activation node calibrated
    kind: str                            # 'conv' | 'dot'


def quantizable(net) -> List[QuantTarget]:
    """The net's quantizable contractions: conv / fullc layers that own
    their params (shared layers and shared primaries are excluded —
    one shared weight serving two sites would need two activation
    scales) and carry no channel-alignment annotations (the padded
    physical layout and the per-channel scales would have to agree
    channel-for-channel; channel_pad is a training-bench knob, serving
    graphs run unpadded)."""
    g = net.graph
    shared_primaries = set(info.primary_layer_index
                           for info in g.layers if info.type == "share")
    out = []
    for li, info in enumerate(g.layers):
        kind = _QUANT_TYPES.get(info.type)
        if kind is None or li in shared_primaries:
            continue
        layer = net.layer_objs[li]
        if (getattr(layer, "_in_layout", None) is not None
                or getattr(layer, "_out_pad", 0)
                or getattr(layer, "_layout", None) is not None):
            continue
        out.append(QuantTarget(li, g.layer_key(li), info.nindex_in[0],
                               kind))
    return out


def folded_weight(trainer, li: int, lkey: str) -> np.ndarray:
    """Host copy of the weight exactly as the eval graph contracts it:
    under ``bn_fold_eval`` the BN partner's running-stats scale is
    folded in (conv.py applies ``w * _fold_scale``), so weight ranges
    are taken over the folded tensor."""
    net = trainer.net
    w = np.asarray(trainer.params[lkey]["wmat"], np.float32)
    if net._bn_fold_eval and li in net._fold_pairs:
        bn_li = net._fold_pairs[li]
        bn = net.layer_objs[bn_li]
        bkey = net.graph.layer_key(net.graph.param_layer_index(bn_li))
        bw = np.asarray(trainer.params[bkey]["wmat"], np.float32)
        bv = np.asarray(trainer.net_state[bkey]["running_var"],
                        np.float32)
        w = w * (bw / np.sqrt(bv + bn.eps))
    return w


class Calibrator:
    """Streams eval batches through the net, recording per-channel
    activation amax at every quantizable layer input. One jitted
    program computes ALL the amax vectors in a single forward per
    batch (registered in ``lint/config.py PROGRAM_BUILDERS``)."""

    def __init__(self, trainer):
        assert trainer._initialized, "calibrate after load_model"
        self.trainer = trainer
        self.targets = quantizable(trainer.net)
        self._amax: Dict[str, np.ndarray] = {}
        self._prog = None
        self.batches = 0

    def _build_amax_program(self):
        net = self.trainer.net
        nodes = tuple(t.in_node for t in self.targets)

        def amax_step(params, net_state, data, mask):
            vals, _, _ = net.forward(params, net_state, data,
                                     is_train=False, mask=mask)
            out = []
            for ni in nodes:
                v = net.depad_node(ni, vals[ni]).astype(jnp.float32)
                axes = tuple(range(v.ndim - 1))
                out.append(jnp.max(jnp.abs(v), axis=axes))
            return out
        return jax.jit(amax_step)

    def observe(self, batch) -> None:
        """Fold one batch's activation ranges in. Padded tail rows are
        zeros — they can never raise an amax, so no mask gymnastics."""
        t = self.trainer
        if self._prog is None:
            self._prog = self._build_amax_program()
        vecs = self._prog(t.params, t.net_state,
                          t._put_batch_array(batch.data),
                          t._put_mask(batch))
        for tgt, v in zip(self.targets, vecs):
            a = np.asarray(v)            # tiny per-channel D2H, offline
            cur = self._amax.get(tgt.lkey)
            self._amax[tgt.lkey] = a if cur is None \
                else np.maximum(cur, a)
        self.batches += 1

    def finish(self) -> Dict[str, Dict[str, np.ndarray]]:
        """Range tables: per-channel activation amax + per-out-channel
        amax of the eval-folded weights. Scales derive at attach time
        (one calibration serves both int8 and fp8)."""
        assert self.batches > 0, "calibrate on at least one batch"
        tables: Dict[str, Dict[str, np.ndarray]] = {}
        for tgt in self.targets:
            w = folded_weight(self.trainer, tgt.li, tgt.lkey)
            w_amax = np.max(np.abs(w), axis=tuple(range(w.ndim - 1)))
            tables[tgt.lkey] = {
                "x_amax": self._amax[tgt.lkey].astype(np.float32),
                "w_amax": w_amax.astype(np.float32),
            }
        return tables


def tables_from_blob(blob) -> Dict[str, Dict[str, np.ndarray]]:
    """Collect ``quant/<layer>/<field>`` arrays from a snapshot blob
    (they are digest-covered like every other array)."""
    tables: Dict[str, Dict[str, np.ndarray]] = {}
    for k in blob:
        if not k.startswith(QUANT_PREFIX):
            continue
        lkey, field = k[len(QUANT_PREFIX):].rsplit("/", 1)
        tables.setdefault(lkey, {})[field] = np.asarray(blob[k])
    return tables


def attach(trainer) -> Dict[str, Any]:
    """Activate the trainer's ``serve_dtype`` on its layer objects.

    Returns the report behind the ``quantized_model`` telemetry record:
    effective dtype, quantized layer count, fallback count (targets
    without a table entry), and whether the backend contracts natively.
    float32 clears every spec; bfloat16 needs no tables; int8/fp8
    require a calibrated snapshot and raise without one.
    """
    net = trainer.net
    for layer in net.layer_objs:
        layer._quant = None
    dtype = trainer.serve_dtype
    if dtype == "float32":
        return {"active": False}
    targets = quantizable(net)
    report = {"active": True, "dtype": dtype, "layers": 0,
              "fallback_layers": 0, "native": False}
    if dtype == "bfloat16":
        for tgt in targets:
            net.layer_objs[tgt.li]._quant = QuantSpec("bfloat16")
            report["layers"] += 1
        report["native"] = True
        return report
    tables = trainer.quant_tables
    if not tables:
        raise ValueError(
            "serve_dtype=%s needs a calibrated snapshot: run "
            "task=quantize over this model first (doc/perf_profile.md "
            "\"Low-precision inference\")" % dtype)
    eff = dtype
    if dtype == "fp8" and fp8_dtype() is None:
        from ..monitor import warn_once
        warn_once("fp8_unsupported",
                  "serve_dtype=fp8: this jax build has no fp8 dtype; "
                  "falling back to int8 scales")
        eff = "int8"
    report["dtype"] = eff
    qmax = QMAX[eff]
    meta_fold = trainer.quant_meta.get("bn_fold_eval")
    if meta_fold is not None and bool(meta_fold) != net._bn_fold_eval:
        from ..monitor import warn_once
        warn_once("quant_fold_mismatch",
                  "snapshot was calibrated with bn_fold_eval=%s but "
                  "this config runs bn_fold_eval=%s; weight scales "
                  "were taken over the other graph"
                  % (meta_fold, net._bn_fold_eval))
    natives = []
    for tgt in targets:
        tab = tables.get(tgt.lkey)
        if tab is None or "x_amax" not in tab or "w_amax" not in tab:
            report["fallback_layers"] += 1
            continue
        x_scale = float(max(float(np.max(tab["x_amax"])),
                            _AMAX_FLOOR) / qmax)
        w_scale = np.maximum(tab["w_amax"].astype(np.float32),
                             _AMAX_FLOOR) / qmax
        native = backend_native(eff, tgt.kind)
        if (tgt.kind == "conv"
                and net.layer_objs[tgt.li].param.num_group > 1):
            # the capability probe runs ungrouped; grouped low-dtype
            # conv support varies by backend — simulate (same values)
            native = False
        natives.append(native)
        net.layer_objs[tgt.li]._quant = QuantSpec(
            eff, x_scale=x_scale, w_scale=jnp.asarray(w_scale),
            native=native)
        report["layers"] += 1
    report["native"] = bool(natives) and all(natives)
    return report

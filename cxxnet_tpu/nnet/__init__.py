from .net import FuncNet
from .trainer import NetTrainer

__all__ = ["FuncNet", "NetTrainer"]

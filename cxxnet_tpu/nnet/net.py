"""The functional net: graph -> pure init/forward/loss functions.

This replaces the reference's mutable ``NeuralNet`` (node buffers +
in-place layer Forward/Backprop, ``neural_net-inl.hpp:24-318``) with a
single pure function over pytrees. Backprop is ``jax.grad`` of
``loss_fn`` — there is no hand-written backward pass; gradient
accumulation, data parallelism, and optimizer updates compose around
this function inside one jitted XLA program.

Weight tying (kSharedLayer, neural_net-inl.hpp:259-265): shared
connections reuse the primary layer's parameter subtree; autodiff sums
the gradients from every use site automatically (the reference relied on
gwmat accumulation across connections for the same effect).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..graph import NetGraph
from ..layers import Layer, Shape3, create_layer

Params = Dict[str, Dict[str, jnp.ndarray]]
NetState = Dict[str, Dict[str, jnp.ndarray]]


class FuncNet:
    """Layer instances + shape inference for a NetGraph."""

    def __init__(self, graph: NetGraph, batch_size: int):
        self.graph = graph
        self.batch_size = batch_size
        self.layer_objs: List[Layer] = []
        self.node_shapes: List[Optional[Shape3]] = \
            [None] * graph.num_nodes
        self._build()

    # -- construction ----------------------------------------------------

    def _build(self) -> None:
        g = self.graph
        self.node_shapes[0] = Shape3(*g.input_shape)
        for i in range(g.extra_data_num):
            self.node_shapes[1 + i] = Shape3(*g.extra_shape[i])
        for li, info in enumerate(g.layers):
            pli = g.param_layer_index(li)
            if info.type == "share":
                layer = self.layer_objs[pli]
                # re-apply nothing: primary layer's params govern
            else:
                cfg = list(g.defcfg) + list(g.layercfg[li])
                kwargs = {}
                if g.effective_type(li) == "split":
                    kwargs["n_out"] = len(info.nindex_out)
                layer = create_layer(info.type, cfg, **kwargs)
                if layer.is_loss and layer.batch_size == 0:
                    layer.batch_size = self.batch_size
            self.layer_objs.append(layer)
            # shape inference for this connection
            in_shapes = []
            for ni in info.nindex_in:
                s = self.node_shapes[ni]
                if s is None:
                    raise ValueError(
                        "layer %d reads node %d before it is produced"
                        % (li, ni))
                in_shapes.append(s)
            if layer.self_loop or info.nindex_in == info.nindex_out:
                if info.nindex_in != info.nindex_out:
                    raise ValueError(
                        "layer %d (%s) is a self-loop layer"
                        % (li, info.type))
            out_shapes = layer.infer_shape(in_shapes)
            for ni, s in zip(info.nindex_out, out_shapes):
                prev = self.node_shapes[ni]
                if prev is not None and ni not in info.nindex_in:
                    if prev != s:
                        raise ValueError(
                            "node %d shape conflict: %s vs %s"
                            % (ni, prev, s))
                self.node_shapes[ni] = s

    # -- init ------------------------------------------------------------

    def init(self, key: jax.Array) -> Tuple[Params, NetState]:
        g = self.graph
        params: Params = {}
        state: NetState = {}
        for li, info in enumerate(g.layers):
            if info.type == "share":
                continue
            lkey = g.layer_key(li)
            p = self.layer_objs[li].init_params(
                jax.random.fold_in(key, li))
            if p:
                params[lkey] = p
            s = self.layer_objs[li].init_state()
            if s:
                state[lkey] = s
        return params, state

    # -- forward ---------------------------------------------------------

    def forward(self, params: Params, state: NetState,
                data: jnp.ndarray,
                extra: Sequence[jnp.ndarray] = (),
                is_train: bool = False,
                rng: Optional[jax.Array] = None,
                collect_logits: bool = False,
                mask: Optional[jnp.ndarray] = None):
        """Run all connections in config order.

        Returns (node_values, new_state, loss_inputs) where loss_inputs
        maps layer index -> pre-transform logits of each loss layer
        (only when collect_logits).
        """
        g = self.graph
        nodes: List[Optional[jnp.ndarray]] = [None] * g.num_nodes
        if not jnp.issubdtype(data.dtype, jnp.floating):
            # uint8 pipeline: pixels ship to the device raw and are
            # normalized here (4x less host->device traffic)
            data = data.astype(jnp.float32)
        nodes[0] = data
        for i in range(g.extra_data_num):
            nodes[1 + i] = extra[i]
        new_state: NetState = dict(state)
        loss_inputs: Dict[int, jnp.ndarray] = {}
        for li, info in enumerate(g.layers):
            layer = self.layer_objs[li]
            pkey = g.layer_key(g.param_layer_index(li))
            p = params.get(pkey, {})
            s = new_state.get(pkey, {})
            ins = [nodes[ni] for ni in info.nindex_in]
            lrng = (jax.random.fold_in(rng, li)
                    if rng is not None else None)
            if collect_logits and layer.is_loss:
                loss_inputs[li] = ins[0]
            if layer.needs_mask:
                outs, s2 = layer.forward(p, s, ins, is_train, lrng,
                                         mask=mask)
            else:
                outs, s2 = layer.forward(p, s, ins, is_train, lrng)
            if s2:
                new_state[pkey] = s2
            for ni, v in zip(info.nindex_out, outs):
                nodes[ni] = v
        return nodes, new_state, loss_inputs

    # -- loss ------------------------------------------------------------

    def loss_fn(self, params: Params, state: NetState,
                data: jnp.ndarray, labels: jnp.ndarray,
                mask: jnp.ndarray,
                extra: Sequence[jnp.ndarray] = (),
                rng: Optional[jax.Array] = None,
                collect_nodes: Sequence[int] = ()):
        """Total training loss (sum over loss layers) + aux.

        labels: (batch, label_width) matrix; each loss layer's ``target``
        selects its column range via the graph's label_vec map.
        Returns (loss, (new_state, collected)) where collected holds the
        post-forward values of ``collect_nodes`` (for on-the-fly train
        metrics, nnet_impl-inl.hpp:191-197).
        """
        nodes, new_state, loss_inputs = self.forward(
            params, state, data, extra=extra, is_train=True, rng=rng,
            collect_logits=True, mask=mask)
        slices = {name: (a, b) for name, a, b in self.graph.label_slices()}
        total = jnp.float32(0.0)
        for li, logit in loss_inputs.items():
            layer = self.layer_objs[li]
            assert layer.is_loss
            if layer.target not in slices:
                raise ValueError("loss layer: unknown target=%s"
                                 % layer.target)
            a, b = slices[layer.target]
            total = total + layer.loss_value(logit, labels[:, a:b], mask)
        collected = [nodes[ni] for ni in collect_nodes]
        return total, (new_state, collected)

    # -- utilities -------------------------------------------------------

    def loss_layer_indices(self) -> List[int]:
        return [li for li, l in enumerate(self.layer_objs)
                if l.is_loss]

    def node_index_by_name(self, name: str) -> int:
        g = self.graph
        if name in g.node_name_map:
            return g.node_name_map[name]
        # allow "top[-k]" addressing like ExtractFeature
        # (nnet_impl-inl.hpp:217-240): top = last node
        if name.startswith("top"):
            k = 0
            if name != "top":
                k = int(name[4:-1]) if name[3] == "[" else 0
            return g.num_nodes - 1 + k
        raise ValueError("unknown node name %r" % name)

    def print_shapes(self) -> str:
        lines = []
        for i, s in enumerate(self.node_shapes):
            nm = self.graph.node_names[i] if i < len(
                self.graph.node_names) else str(i)
            lines.append("node %s: %s" % (nm, tuple(s) if s else None))
        return "\n".join(lines)

"""The functional net: graph -> pure init/forward/loss functions.

This replaces the reference's mutable ``NeuralNet`` (node buffers +
in-place layer Forward/Backprop, ``neural_net-inl.hpp:24-318``) with a
single pure function over pytrees. Backprop is ``jax.grad`` of
``loss_fn`` — there is no hand-written backward pass; gradient
accumulation, data parallelism, and optimizer updates compose around
this function inside one jitted XLA program.

Weight tying (kSharedLayer, neural_net-inl.hpp:259-265): shared
connections reuse the primary layer's parameter subtree; autodiff sums
the gradients from every use site automatically (the reference relied on
gwmat accumulation across connections for the same effect).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..graph import NetGraph
from ..layers import Layer, Shape3, create_layer

Params = Dict[str, Dict[str, jnp.ndarray]]
NetState = Dict[str, Dict[str, jnp.ndarray]]


class FuncNet:
    """Layer instances + shape inference for a NetGraph."""

    def __init__(self, graph: NetGraph, batch_size: int):
        self.graph = graph
        self.batch_size = batch_size
        self.layer_objs: List[Layer] = []
        self.node_shapes: List[Optional[Shape3]] = \
            [None] * graph.num_nodes
        self._build()

    # -- construction ----------------------------------------------------

    def _build(self) -> None:
        g = self.graph
        self.node_shapes[0] = Shape3(*g.input_shape)
        for i in range(g.extra_data_num):
            self.node_shapes[1 + i] = Shape3(*g.extra_shape[i])
        for li, info in enumerate(g.layers):
            pli = g.param_layer_index(li)
            if info.type == "share":
                layer = self.layer_objs[pli]
                # re-apply nothing: primary layer's params govern
            else:
                cfg = list(g.defcfg) + list(g.layercfg[li])
                kwargs = {}
                if g.effective_type(li) == "split":
                    kwargs["n_out"] = len(info.nindex_out)
                layer = create_layer(info.type, cfg, **kwargs)
                if layer.is_loss and layer.batch_size == 0:
                    layer.batch_size = self.batch_size
            self.layer_objs.append(layer)
            # shape inference for this connection
            in_shapes = []
            for ni in info.nindex_in:
                s = self.node_shapes[ni]
                if s is None:
                    raise ValueError(
                        "layer %d reads node %d before it is produced"
                        % (li, ni))
                in_shapes.append(s)
            if layer.self_loop or info.nindex_in == info.nindex_out:
                if info.nindex_in != info.nindex_out:
                    raise ValueError(
                        "layer %d (%s) is a self-loop layer"
                        % (li, info.type))
            out_shapes = layer.infer_shape(in_shapes)
            for ni, s in zip(info.nindex_out, out_shapes):
                prev = self.node_shapes[ni]
                if prev is not None and ni not in info.nindex_in:
                    if prev != s:
                        raise ValueError(
                            "node %d shape conflict: %s vs %s"
                            % (ni, prev, s))
                self.node_shapes[ni] = s
        self._fusion_passes()
        from .layout import plan_channel_layouts
        plan_channel_layouts(self)

    # -- graph-level fusion passes ---------------------------------------

    _BN_TYPES = ("batch_norm", "pallas_batch_norm")

    def _net_flag(self, name: str, default: int = 0) -> int:
        """Net-level knob from the global (default) layer config."""
        val = default
        for n, v in self.graph.defcfg:
            if n == name:
                val = int(v)
        return val

    def _fusion_passes(self) -> None:
        """Epilogue fusion over the built graph.

        ``bn_fuse_relu = 1``: a relu that is the SOLE consumer of a
        batch-norm output runs inside the BN layer (one fused epilogue
        — and one Pallas pass under bn_pallas) and the relu connection
        becomes identity. Same math, exactly: relu(bn(x)).

        ``bn_fold_eval = 1``: on the eval/pred path, a moving-average
        batch_norm that solely consumes a conv's output folds its
        running-stats scale/shift into the conv weights (w*scale is a
        small per-out-channel multiply); the BN connection runs as
        identity. Training is untouched — running stats keep updating
        from batch moments. Parity is pinned by tests (reassociation-
        level rounding only: the scale multiplies the weight before
        the contraction instead of the output after it).

        ``pool_concat_pallas = 1``: an Inception-tower ``ch_concat``
        whose pool branch is a k*k stride-1 SAME (pad = k//2) max/avg
        pool consumed ONLY by the concat fuses into one Pallas pass
        (pallas_kernels.pool_concat): the pool layer passes its input
        through unpooled and the concat reduces the window while
        writing the channel segments — the pooled intermediate and the
        concat copy both disappear. Gated by the VMEM applicability
        probe and off under ``channel_pad`` (the alignment pass owns
        concat layout there). Same math both directions (custom VJP
        with reference unpool tie semantics).

        Both fusions change what INTERIOR nodes hold (the BN output
        node carries the post-relu value; at eval the conv output node
        carries the folded conv+BN value) — extraction or metrics
        bound to those interior nodes read the fused values. Logical
        net outputs are identical; the knobs are opt-in.
        """
        g = self.graph
        self._identity_layers = set()     # relus folded into their BN
        self._fold_pairs = {}             # conv li -> bn li (eval fold)
        self._fold_bns = set()
        self._bn_fold_eval = bool(self._net_flag("bn_fold_eval"))
        consumers = g.node_consumers()
        # a SHARED layer reuses its primary's object: mutating the
        # primary (fuse_relu) would drag the fusion to every share
        # site, whose consumers may not be relus — exclude them
        shared_primaries = set(info.primary_layer_index
                               for info in g.layers
                               if info.type == "share")
        if self._net_flag("bn_fuse_relu"):
            for li, info in enumerate(g.layers):
                if info.type not in self._BN_TYPES + ("batch_norm_no_ma",):
                    continue
                if li in shared_primaries:
                    continue
                out = info.nindex_out[0]
                cons = consumers.get(out, [])
                if len(cons) != 1:
                    continue
                lj = cons[0]
                if g.layers[lj].type == "relu":
                    self.layer_objs[li].fuse_relu = True
                    self._identity_layers.add(lj)
        if self._net_flag("bn_fold_eval"):
            for li, info in enumerate(g.layers):
                if info.type != "conv":
                    continue
                out = info.nindex_out[0]
                cons = consumers.get(out, [])
                if len(cons) != 1:
                    continue
                lj = cons[0]
                if (g.layers[lj].type in self._BN_TYPES
                        and self.layer_objs[lj].moving_avg):
                    self._fold_pairs[li] = lj
                    self._fold_bns.add(lj)
        self._pool_passthrough = set()    # pools fused into their concat
        self._pool_concat = {}            # concat li -> (pos, k, mode)
        if (self._net_flag("pool_concat_pallas")
                and not self._net_flag("channel_pad")):
            self._plan_pool_concat(consumers, shared_primaries)

    def _plan_pool_concat(self, consumers, shared_primaries) -> None:
        """Mark Inception-tower ch_concat layers whose pool branch can
        fuse (see _fusion_passes docstring for the conditions)."""
        from ..layers.conv import InsanityPoolingLayer, PoolingLayer
        from ..layers.pallas_kernels import pool_concat_applicable
        g = self.graph
        producers = {}
        for li, info in enumerate(g.layers):
            for ni in info.nindex_out:
                producers.setdefault(ni, li)
        itemsize = 2 if any(n == "dtype" and v == "bfloat16"
                            for n, v in g.defcfg) else 4
        for li, info in enumerate(g.layers):
            if info.type != "ch_concat" or li in shared_primaries:
                continue
            out_shape = self.node_shapes[info.nindex_out[0]]
            for pos, ni in enumerate(info.nindex_in):
                pli = producers.get(ni)
                if pli is None or g.layers[pli].type not in (
                        "max_pooling", "avg_pooling"):
                    continue
                pool = self.layer_objs[pli]
                if (not isinstance(pool, PoolingLayer)
                        or isinstance(pool, InsanityPoolingLayer)
                        or pool.pre_relu):
                    continue
                pp = pool.param
                k = pp.kernel_height
                if (pp.stride != 1 or k != pp.kernel_width or k <= 1
                        or k % 2 == 0 or pp.pad_y != k // 2
                        or pp.pad_x != k // 2):
                    continue
                if consumers.get(ni, []) != [li]:
                    continue
                ins = self.node_shapes[g.layers[pli].nindex_in[0]]
                outs = self.node_shapes[ni]
                if (ins.y, ins.x) != (outs.y, outs.x):
                    continue              # not a SAME-size pool
                if not pool_concat_applicable(out_shape.y, out_shape.x,
                                              out_shape.ch, k,
                                              itemsize):
                    continue
                self._pool_concat[li] = (pos, k, pool.mode)
                self.layer_objs[li]._fused_pool = (pos, k, pool.mode)
                self._pool_passthrough.add(pli)
                break                     # one fused branch per concat

    def _fold_entries(self, params: Params, state: NetState,
                      conv_li: int):
        """Per-out-channel scale/shift the eval fold injects into a
        conv's params (from its BN partner's running stats)."""
        import jax.lax
        bn_li = self._fold_pairs[conv_li]
        bn = self.layer_objs[bn_li]
        bkey = self.graph.layer_key(self.graph.param_layer_index(bn_li))
        bp, bs = params[bkey], state[bkey]
        scale = bp["wmat"] * jax.lax.rsqrt(bs["running_var"] + bn.eps)
        shift = bp["bias"] - bs["running_exp"] * scale
        out = {"_fold_scale": scale, "_fold_shift": shift}
        if bn.fuse_relu:
            out["_fold_relu"] = True
        return out

    # -- init ------------------------------------------------------------

    def init(self, key: jax.Array) -> Tuple[Params, NetState]:
        g = self.graph
        params: Params = {}
        state: NetState = {}
        for li, info in enumerate(g.layers):
            if info.type == "share":
                continue
            lkey = g.layer_key(li)
            p = self.layer_objs[li].init_params(
                jax.random.fold_in(key, li))
            if p:
                params[lkey] = p
            s = self.layer_objs[li].init_state()
            if s:
                state[lkey] = s
        return params, state

    # -- forward ---------------------------------------------------------

    def forward(self, params: Params, state: NetState,
                data: jnp.ndarray,
                extra: Sequence[jnp.ndarray] = (),
                is_train: bool = False,
                rng: Optional[jax.Array] = None,
                collect_logits: bool = False,
                mask: Optional[jnp.ndarray] = None):
        """Run all connections in config order.

        Returns (node_values, new_state, loss_inputs) where loss_inputs
        maps layer index -> pre-transform logits of each loss layer
        (only when collect_logits).
        """
        g = self.graph
        nodes: List[Optional[jnp.ndarray]] = [None] * g.num_nodes
        if not jnp.issubdtype(data.dtype, jnp.floating):
            # uint8 pipeline: pixels ship to the device raw and are
            # normalized here (4x less host->device traffic)
            data = data.astype(jnp.float32)
        nodes[0] = data
        for i in range(g.extra_data_num):
            nodes[1 + i] = extra[i]
        new_state: NetState = dict(state)
        loss_inputs: Dict[int, jnp.ndarray] = {}
        fold_eval = self._bn_fold_eval and not is_train
        for li, info in enumerate(g.layers):
            if li in self._identity_layers \
                    or li in self._pool_passthrough \
                    or (fold_eval and li in self._fold_bns):
                # epilogue already ran fused inside the producer (relu
                # inside BN / BN inside the folded conv / pool inside
                # the fused concat): pass through
                v = nodes[info.nindex_in[0]]
                for ni in info.nindex_out:
                    nodes[ni] = v
                continue
            layer = self.layer_objs[li]
            pkey = g.layer_key(g.param_layer_index(li))
            p = params.get(pkey, {})
            s = new_state.get(pkey, {})
            if fold_eval and li in self._fold_pairs \
                    and "_fold_scale" not in p \
                    and "_r_shift" not in p \
                    and "_r_shift_relu" not in p:
                # inject the fold scale/shift computed in-graph — UNLESS
                # the frozen serve weight tree already carries them (or
                # the pre-folded weight + effective shift) as leaves
                # (trainer.freeze_serve_weights)
                p = dict(p)
                p.update(self._fold_entries(params, new_state, li))
            if li in self._depad_layers:
                # layout barrier: this layer sees logical channels
                ins = [self.depad_node(ni, nodes[ni])
                       for ni in info.nindex_in]
            else:
                ins = [nodes[ni] for ni in info.nindex_in]
            lrng = (jax.random.fold_in(rng, li)
                    if rng is not None else None)
            if collect_logits and layer.is_loss:
                loss_inputs[li] = ins[0]
            if layer.needs_mask:
                outs, s2 = layer.forward(p, s, ins, is_train, lrng,
                                         mask=mask)
            else:
                outs, s2 = layer.forward(p, s, ins, is_train, lrng)
            if s2:
                new_state[pkey] = s2
            for ni, v in zip(info.nindex_out, outs):
                nodes[ni] = v
        return nodes, new_state, loss_inputs

    # -- loss ------------------------------------------------------------

    def loss_fn(self, params: Params, state: NetState,
                data: jnp.ndarray, labels: jnp.ndarray,
                mask: jnp.ndarray,
                extra: Sequence[jnp.ndarray] = (),
                rng: Optional[jax.Array] = None,
                collect_nodes: Sequence[int] = ()):
        """Total training loss (sum over loss layers) + aux.

        labels: (batch, label_width) matrix; each loss layer's ``target``
        selects its column range via the graph's label_vec map.
        Returns (loss, (new_state, collected)) where collected holds the
        post-forward values of ``collect_nodes`` (for on-the-fly train
        metrics, nnet_impl-inl.hpp:191-197).
        """
        nodes, new_state, loss_inputs = self.forward(
            params, state, data, extra=extra, is_train=True, rng=rng,
            collect_logits=True, mask=mask)
        slices = {name: (a, b) for name, a, b in self.graph.label_slices()}
        total = jnp.float32(0.0)
        for li, logit in loss_inputs.items():
            layer = self.layer_objs[li]
            assert layer.is_loss
            if layer.target not in slices:
                raise ValueError("loss layer: unknown target=%s"
                                 % layer.target)
            a, b = slices[layer.target]
            total = total + layer.loss_value(logit, labels[:, a:b], mask)
        collected = [self.depad_node(ni, nodes[ni])
                     for ni in collect_nodes]
        return total, (new_state, collected)

    # -- utilities -------------------------------------------------------

    def depad_node(self, ni: int, v):
        """Slice a node value back to its logical channels (identity
        for plain nodes) — extraction, metrics and layout barriers all
        read logical tensors."""
        from .layout import is_padded, take_valid
        lay = self.node_layouts[ni] if ni < len(self.node_layouts) \
            else None
        if v is None or not is_padded(lay):
            return v
        return take_valid(v, lay)

    def analytic_flops_per_example(self) -> float:
        """Analytic forward FLOPs per example (2*MACs over the logical
        conv/dense contractions; a training step is ~3x — one forward
        plus two backward GEMMs per contraction). XLA's own
        cost_analysis undercounts fused TPU convolutions ~15x
        (doc/perf_profile.md), so MFU telemetry uses this count."""
        g = self.graph
        total = 0
        for li in range(len(g.layers)):
            layer = self.layer_objs[li]
            t = g.effective_type(li)
            if t == "conv":
                p = layer.param
                out = layer.out_shapes[0]
                total += (2 * p.kernel_height * p.kernel_width
                          * (p.num_input_channel // p.num_group)
                          * out.ch * out.y * out.x)
            elif t in ("fullc", "pallas_fullc", "fixconn"):
                p = layer.param
                total += 2 * p.num_input_node * p.num_hidden
        return float(total)

    def loss_layer_indices(self) -> List[int]:
        return [li for li, l in enumerate(self.layer_objs)
                if l.is_loss]

    def node_index_by_name(self, name: str) -> int:
        g = self.graph
        if name in g.node_name_map:
            return g.node_name_map[name]
        # allow "top[-k]" addressing like ExtractFeature
        # (nnet_impl-inl.hpp:217-240): top = last node
        if name.startswith("top"):
            k = 0
            if name != "top":
                k = int(name[4:-1]) if name[3] == "[" else 0
            return g.num_nodes - 1 + k
        raise ValueError("unknown node name %r" % name)

    def print_shapes(self) -> str:
        lines = []
        for i, s in enumerate(self.node_shapes):
            nm = self.graph.node_names[i] if i < len(
                self.graph.node_names) else str(i)
            lines.append("node %s: %s" % (nm, tuple(s) if s else None))
        return "\n".join(lines)

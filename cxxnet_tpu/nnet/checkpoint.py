"""Crash-safe checkpoints: atomic commit, digests, async writes, resume.

The reference's snapshot path (``CXXNetLearnTask::SaveModel`` +
``SyncLastestModel``, cxxnet_main.cpp:167-215) trusts the filesystem:
it writes the model straight to its final name on the training thread
and resume assumes every ``NNNN.model.npz`` on disk is complete. On
preemptible capacity that assumption is the first thing to die — a
SIGKILL mid-``np.savez`` leaves a truncated npz that ``continue=1``
then picks as "latest" and crashes on. This module owns everything
between ``NetTrainer.gather_snapshot()`` and durable bytes:

* **atomic two-phase commit** — local paths write a ``.tmp`` sibling,
  fsync, then ``os.replace`` (readers see the old snapshot or the new
  one, never a torn file); remote URI schemes write the payload and
  then a tiny ``<name>.ok`` commit manifest — a payload without its
  manifest is uncommitted and invisible to resume.
* **content digests** — sha256 over every array's bytes, stored in
  ``__meta__`` and re-verified on every load (trainer resume, finetune
  copy, serve ``model_in``) and by ``tools/ckpt_verify.py``.
* **async snapshots** — :class:`CheckpointManager` lets the training
  thread pay only the device->host gather; one background writer
  serializes, commits, emits telemetry, and garbage-collects.
* **validated auto-resume** — :func:`find_latest_valid` scans a model
  dir newest-first, quarantines corrupt candidates, and returns the
  newest snapshot that actually loads.

Failure semantics are part of the contract: an async (or managed sync)
snapshot failure warns and keeps training — a long run must survive a
full disk — while the direct ``NetTrainer.save_model`` API raises.
See doc/checkpointing.md; the fault matrix is pinned by
tests/test_checkpoint.py via ``utils/faultfs.py``.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..utils.stream import (list_stream_dir, local_path, open_stream,
                            read_stream_bytes, remove_stream,
                            stream_exists, uri_scheme)

# format_version 2 = digest-carrying snapshots (this module); 1 = the
# pre-checkpoint-subsystem layout (no content_digest — still loadable).
FORMAT_VERSION = 2

MODEL_RE = re.compile(r"^(\d{4})\.model\.npz$")
_TMP_RE = re.compile(r"^\d{4}\.model\.npz\.tmp$")

OK_SUFFIX = ".ok"
QUARANTINE_SUFFIX = ".quarantined"


class SnapshotError(IOError):
    """Base for snapshot read failures."""


class SnapshotIntegrityError(SnapshotError):
    """Snapshot is unreadable, truncated, or fails its digest."""


class SnapshotFormatError(SnapshotError):
    """Snapshot was written by a newer format than this build reads."""


# -- digest ---------------------------------------------------------------


def compute_digest(arrays: Dict[str, np.ndarray]) -> str:
    """Order-independent sha256 over every array's identity (name,
    dtype, shape) and bytes; ``__meta__`` is excluded — the digest
    lives inside it."""
    h = hashlib.sha256()
    for k in sorted(arrays):
        if k == "__meta__":
            continue
        a = np.ascontiguousarray(arrays[k])
        h.update(k.encode())
        h.update(str(a.dtype).encode())
        h.update(repr(a.shape).encode())
        h.update(a.tobytes())
    return "sha256:" + h.hexdigest()


def _serialize(arrays: Dict[str, np.ndarray],
               meta: Dict[str, Any]) -> Tuple[bytes, str]:
    """Digest the arrays, stamp the digest + format version into
    ``__meta__``, and return (npz bytes, digest)."""
    digest = compute_digest(arrays)
    meta = dict(meta)
    meta["format_version"] = FORMAT_VERSION
    meta["content_digest"] = digest
    out = dict(arrays)
    out["__meta__"] = np.frombuffer(json.dumps(meta).encode(), np.uint8)
    buf = io.BytesIO()
    np.savez(buf, **out)
    return buf.getvalue(), digest


# -- atomic commit --------------------------------------------------------


def write_snapshot(path: str, arrays: Dict[str, np.ndarray],
                   meta: Dict[str, Any],
                   fsync: bool = True) -> Dict[str, Any]:
    """Serialize and atomically commit a snapshot; returns timing/size
    stats for the ``checkpoint`` telemetry record.

    Local paths: write ``<path>.tmp``, flush+fsync, ``os.replace`` to
    the final name, fsync the directory — a crash at any point leaves
    either the previous committed snapshot or the new one. Remote
    schemes: write the payload, then the ``<path>.ok`` commit manifest
    (bytes + file sha256 + content digest); resume and GC treat a
    manifest-less payload as uncommitted.
    """
    t0 = time.perf_counter()
    payload, digest = _serialize(arrays, meta)
    t1 = time.perf_counter()
    fsync_s = 0.0
    if uri_scheme(path):
        # re-writing a committed counter (emergency snapshots reuse
        # the in-progress round's number): drop the old manifest FIRST
        # so a kill mid-overwrite leaves an *uncommitted* payload, not
        # a torn payload a stale manifest still vouches for
        remove_stream(path + OK_SUFFIX)
        with open_stream(path, "wb") as f:
            f.write(payload)
        manifest = {
            "format_version": FORMAT_VERSION,
            "bytes": len(payload),
            "file_sha256": hashlib.sha256(payload).hexdigest(),
            "content_digest": digest,
        }
        with open_stream(path + OK_SUFFIX, "w") as f:
            f.write(json.dumps(manifest))
        # a re-written counter must not stay masked by a stale
        # quarantine marker from a previous resume scan
        remove_stream(path + QUARANTINE_SUFFIX)
        t2 = time.perf_counter()
    else:
        p = local_path(path)
        d = os.path.dirname(p)
        if d and not os.path.isdir(d):
            os.makedirs(d, exist_ok=True)
        tmp = p + ".tmp"
        try:
            with open(tmp, "wb") as f:
                f.write(payload)
                f.flush()
                if fsync:
                    tf = time.perf_counter()
                    os.fsync(f.fileno())
                    fsync_s += time.perf_counter() - tf
            os.replace(tmp, p)
        except BaseException:
            # leave no droppings: the tmp sibling is garbage by
            # definition (resume ignores it, but ENOSPC recovery
            # should not have to wait for the next scan)
            try:
                os.remove(tmp)
            except OSError:
                pass  # cxxlint: disable=CXL006 -- best-effort cleanup; the commit failure below is what the caller must see
            raise
        if fsync and d:
            # the rename itself must be durable: fsync the directory
            tf = time.perf_counter()
            try:
                dfd = os.open(d, os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            except OSError as e:
                # some filesystems refuse dir fsync: the rename may
                # not be power-loss durable — warn once, keep going
                from ..monitor import warn_once
                warn_once("dir_fsync_refused",
                          "directory fsync of %r failed (%s); the "
                          "snapshot rename is not guaranteed durable "
                          "across power loss on this filesystem"
                          % (d, e))
            fsync_s += time.perf_counter() - tf
        t2 = time.perf_counter()
    # optimizer-state share of the payload (save_optimizer=1 snapshots
    # carry opt/<layer>/<tag>/<key> arrays): snapshots always store the
    # GATHERED global state — a ZeRO-sharded (optim_shard=1) run
    # allgathers its shards at save and re-shards at load, so the
    # artifact stays topology-portable (an H=4 emergency snapshot
    # resumes at H=2 unchanged, doc/updater.md) — which also means
    # opt_bytes reports the full logical state, not one host's shard
    opt_bytes = sum(int(a.nbytes) for k, a in arrays.items()
                    if k.startswith("opt/"))
    return {
        "bytes": len(payload),
        "opt_bytes": opt_bytes,
        "digest": digest,
        "serialize_ms": (t1 - t0) * 1e3,
        "write_ms": max(0.0, (t2 - t1) * 1e3 - fsync_s * 1e3),
        "fsync_ms": fsync_s * 1e3,
    }


# -- verified read --------------------------------------------------------


def read_snapshot(path: str, verify: bool = True, raw: bytes = None,
                  ) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    """Load a snapshot into (arrays, meta), raising
    :class:`SnapshotIntegrityError` on truncation/corruption/digest
    mismatch and :class:`SnapshotFormatError` on a future
    ``format_version``. v1 snapshots (pre-digest) load with a warn-once
    instead of failing — old fleets must stay resumable. ``raw`` lets a
    caller that already holds the payload bytes (verify_snapshot's
    manifest cross-check) skip a second full fetch."""
    if raw is None:
        try:
            raw = read_stream_bytes(path)
        except (IOError, OSError) as e:
            raise SnapshotIntegrityError(
                "snapshot %r is unreadable: %s" % (path, e)) from e
    try:
        blob = dict(np.load(io.BytesIO(raw), allow_pickle=False))
    except Exception as e:
        raise SnapshotIntegrityError(
            "snapshot %r is corrupt or truncated (%d bytes): %s"
            % (path, len(raw), e)) from e
    if "__meta__" not in blob:
        raise SnapshotIntegrityError(
            "snapshot %r has no __meta__ record" % path)
    try:
        meta = json.loads(bytes(blob["__meta__"]).decode())
    except Exception as e:
        raise SnapshotIntegrityError(
            "snapshot %r has an unparseable __meta__: %s"
            % (path, e)) from e
    fv = int(meta.get("format_version", 1))
    if fv > FORMAT_VERSION:
        raise SnapshotFormatError(
            "snapshot %r was written by format_version %d but this "
            "build reads <= %d; upgrade cxxnet_tpu (or re-export the "
            "snapshot) instead of guessing at the layout"
            % (path, fv, FORMAT_VERSION))
    if verify:
        digest = meta.get("content_digest")
        if digest:
            got = compute_digest(blob)
            if got != digest:
                raise SnapshotIntegrityError(
                    "snapshot %r fails its content digest (stored %s, "
                    "recomputed %s) — the file was modified or "
                    "corrupted after commit" % (path, digest, got))
        else:
            from ..monitor import warn_once
            warn_once("snapshot_no_digest",
                      "snapshot %r carries no content digest "
                      "(format_version %d) — loading unverified"
                      % (path, fv))
    return blob, meta


def verify_snapshot(path: str) -> Dict[str, Any]:
    """Offline integrity report for one snapshot (the
    ``tools/ckpt_verify.py`` core): structural loadability + digest,
    plus the commit-manifest cross-check when one exists."""
    rep: Dict[str, Any] = {"path": path, "ok": False, "error": "",
                           "bytes": 0, "format_version": 0,
                           "digest": "missing"}
    try:
        raw = read_stream_bytes(path)
    except (IOError, OSError) as e:
        rep["error"] = "unreadable: %s" % e
        return rep
    rep["bytes"] = len(raw)
    if stream_exists(path + OK_SUFFIX):
        try:
            with open_stream(path + OK_SUFFIX, "r") as f:
                man = json.loads(f.read())
            if man.get("bytes") != len(raw):
                rep["error"] = ("manifest size mismatch: committed %s "
                                "bytes, found %d"
                                % (man.get("bytes"), len(raw)))
                return rep
            sha = hashlib.sha256(raw).hexdigest()
            if man.get("file_sha256") not in (None, sha):
                rep["error"] = "manifest file_sha256 mismatch"
                return rep
        except (IOError, OSError, ValueError) as e:
            rep["error"] = "unreadable commit manifest: %s" % e
            return rep
    try:
        blob, meta = read_snapshot(path, verify=False, raw=raw)
    except SnapshotError as e:
        rep["error"] = str(e)
        return rep
    rep["format_version"] = int(meta.get("format_version", 1))
    digest = meta.get("content_digest")
    if digest:
        if compute_digest(blob) == digest:
            rep["digest"] = "match"
        else:
            rep["digest"] = "mismatch"
            rep["error"] = "content digest mismatch"
            return rep
    rep["ok"] = True
    return rep


# -- model_dir scan / validated resume ------------------------------------


def snapshot_uri(model_dir: str, name: str) -> str:
    if uri_scheme(model_dir):
        return "%s/%s" % (model_dir.rstrip("/"), name)
    return os.path.join(local_path(model_dir), name)


def scan_snapshots(model_dir: str) -> List[Tuple[int, str]]:
    """Committed snapshot candidates in ``model_dir`` as
    (counter, basename), newest first. Remote dirs require the
    ``.ok`` commit manifest and skip quarantine-marked names; local
    dirs list every final-named file (the local commit IS the rename).
    Read-only: stale ``.tmp`` sweeping belongs to the resume scan
    (:func:`find_latest_valid`) — callers like ``tools/ckpt_verify.py``
    may be pointed at a model_dir a live run is committing into, and
    must never delete its in-flight tmp."""
    names = set(list_stream_dir(model_dir))
    remote = bool(uri_scheme(model_dir))
    out = []
    for n in names:
        m = MODEL_RE.match(n)
        if not m:
            continue
        if remote:
            if n + OK_SUFFIX not in names:
                continue                 # uncommitted payload
            if n + QUARANTINE_SUFFIX in names:
                continue                 # marked bad by a prior resume
        out.append((int(m.group(1)), n))
    out.sort(reverse=True)
    return out


class ResumeReport:
    """Outcome of a validated resume scan."""

    __slots__ = ("path", "counter", "scanned", "quarantined")

    def __init__(self, path: Optional[str], counter: Optional[int],
                 scanned: int, quarantined: List[str]):
        self.path = path
        self.counter = counter
        self.scanned = scanned
        self.quarantined = quarantined


def quarantine_snapshot(model_dir: str, name: str) -> None:
    """Move a corrupt candidate out of resume's way, preserving the
    bytes for forensics: local files rename to ``<name>.quarantined``
    (with a numeric suffix if that exists); remote objects get a
    ``<name>.quarantined`` marker object beside them."""
    uri = snapshot_uri(model_dir, name)
    if uri_scheme(model_dir):
        try:
            with open_stream(uri + QUARANTINE_SUFFIX, "w") as f:
                f.write("quarantined by resume scan\n")
        except (IOError, OSError) as e:
            # skip-only quarantine on read-only remote stores: the
            # resume scan still skips the corrupt snapshot, but every
            # future scan re-verifies it — worth saying once
            from ..monitor import warn_once
            warn_once("quarantine_failed:%s" % uri,
                      "could not write quarantine marker for %s (%s); "
                      "the snapshot is skipped but will be re-verified "
                      "on every scan" % (uri, e))
        return
    dst = uri + QUARANTINE_SUFFIX
    n = 0
    while os.path.exists(dst):
        n += 1
        dst = "%s%s.%d" % (uri, QUARANTINE_SUFFIX, n)
    try:
        os.replace(uri, dst)
    except OSError as e:
        from ..monitor import warn_once
        warn_once("quarantine_failed:%s" % uri,
                  "could not quarantine corrupt snapshot %s (%s); it "
                  "stays in place and every scan re-verifies it"
                  % (uri, e))


def find_latest_valid(model_dir: str, monitor=None,
                      quarantine: bool = True) -> ResumeReport:
    """Scan ``model_dir`` newest-first and return the newest snapshot
    that actually verifies; corrupt candidates are quarantined (and
    warned about once) instead of crashing ``continue=1``. Resume owns
    the model_dir (no live writer), so stale local ``.tmp`` siblings
    left by a kill mid-commit are swept here."""
    if not uri_scheme(model_dir):
        for n in list_stream_dir(model_dir):
            if _TMP_RE.match(n):
                try:
                    os.remove(snapshot_uri(model_dir, n))
                except OSError:
                    pass  # cxxlint: disable=CXL006 -- stale .tmp sweep is an optimization; resume ignores tmp files either way
    bad: List[str] = []
    scanned = 0
    for counter, name in scan_snapshots(model_dir):
        scanned += 1
        uri = snapshot_uri(model_dir, name)
        rep = verify_snapshot(uri)
        if rep["ok"]:
            return ResumeReport(uri, counter, scanned, bad)
        bad.append(name)
        if quarantine:
            quarantine_snapshot(model_dir, name)
        if monitor is not None:
            monitor.warn_once(
                "snapshot_quarantined:%s" % name,
                "resume: snapshot %s is invalid (%s); %s"
                % (uri, rep["error"],
                   "quarantined" if quarantine else "skipped"))
    return ResumeReport(None, None, scanned, bad)


# -- retention ------------------------------------------------------------


def retention_sweep(model_dir: str, keep: int) -> List[str]:
    """Delete committed snapshots beyond the newest ``keep`` (never
    fewer than one survives). Remote deletes drop the commit manifest
    first so a partial sweep can never leave a committed-but-missing
    payload. Returns the basenames removed."""
    if keep <= 0:
        return []
    removed = []
    for _, name in scan_snapshots(model_dir)[keep:]:
        uri = snapshot_uri(model_dir, name)
        if uri_scheme(model_dir):
            remove_stream(uri + OK_SUFFIX)
        remove_stream(uri)
        removed.append(name)
    return removed


# -- async writer / manager -----------------------------------------------


class _Writer:
    """Single in-flight background commit thread: ``submit`` joins the
    previous write (bounding buffered snapshots to one) and starts the
    next; ``close`` drains."""

    def __init__(self):
        self._thread: Optional[threading.Thread] = None

    def submit(self, fn: Callable[[], None]) -> None:
        self.wait()
        t = threading.Thread(target=fn, name="ckpt-writer",
                             daemon=True)
        t.start()
        self._thread = t

    def wait(self) -> None:
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None


class CheckpointManager:
    """The train loop's checkpoint front end.

    ``save(counter)`` gathers device arrays to host on the calling
    (training) thread — the only part that must see a quiescent update
    boundary — and hands serialization + atomic commit + retention GC
    to the background writer (``checkpoint_async = 0`` runs them
    inline). Commit failures warn and keep training; crash-safety
    means surviving ENOSPC, not dying on it. All ranks must call
    ``save`` (the optimizer-state gathers are collective); only root
    touches files.
    """

    def __init__(self, trainer, path_for: Callable[[int], str],
                 model_dir: str = "", monitor=None, async_: bool = True,
                 fsync: bool = True, keep: int = 0):
        self.trainer = trainer
        self.path_for = path_for
        self.model_dir = model_dir
        self._mon = monitor
        self.async_ = bool(async_)
        self.fsync = bool(fsync)
        self.keep = int(keep)
        self._writer = _Writer()
        # commits/failures are written on the background writer thread
        # and read by the training thread (tests, the emergency path's
        # accounting) — guarded, so a reader never sees a torn update
        self._lock = threading.Lock()
        self.failures = 0
        self.commits = 0

    # root-rank check is late-bound: tests monkeypatch process_index
    @staticmethod
    def _is_root() -> bool:
        import jax
        return jax.process_index() == 0

    def save(self, counter: int, emergency: bool = False) -> None:
        t0 = time.perf_counter()
        arrays, meta = self.trainer.gather_snapshot()
        gather_ms = (time.perf_counter() - t0) * 1e3
        if not self._is_root():
            return
        path = self.path_for(counter)

        def _commit():
            stats = {"bytes": 0, "opt_bytes": 0, "digest": "",
                     "serialize_ms": 0.0, "write_ms": 0.0,
                     "fsync_ms": 0.0}
            status, err = "ok", ""
            try:
                stats = write_snapshot(path, arrays, meta,
                                       fsync=self.fsync)
                with self._lock:
                    self.commits += 1
            except Exception as e:
                # commit failures (ENOSPC, auth, a backend bug) warn
                # and keep training — and must never escape as an
                # unhandled exception on the writer thread
                status, err = "failed", str(e)
                with self._lock:
                    self.failures += 1
                if self._mon is not None:
                    self._mon.warn_once(
                        "checkpoint_write_failed",
                        "snapshot %s failed (%s); training continues "
                        "on the previous committed snapshot"
                        % (path, e))
            if self._mon is not None and self._mon.enabled:
                self._mon.emit(
                    "checkpoint", path=path, counter=int(counter),
                    status=status, error=err,
                    emergency=bool(emergency),
                    async_write=self.async_, gather_ms=gather_ms,
                    **{k: (round(v, 3) if isinstance(v, float) else v)
                       for k, v in stats.items()})
            if status == "ok" and self.keep > 0 and self.model_dir:
                removed = retention_sweep(self.model_dir, self.keep)
                if removed and self._mon is not None \
                        and self._mon.enabled:
                    self._mon.emit("checkpoint_gc",
                                   removed=len(removed),
                                   kept=self.keep, names=removed)

        if self.async_ and not emergency:
            self._writer.submit(_commit)
        else:
            # emergency snapshots commit inline: the process is about
            # to exit and MUST NOT race its own daemon writer
            self._writer.wait()
            _commit()

    def wait(self) -> None:
        """Block until the in-flight commit (if any) is durable."""
        self._writer.wait()

    def close(self) -> None:
        self._writer.wait()

"""The trainer: INetTrainer-equivalent over one jitted SPMD program.

Replaces the reference's ``CXXNetThreadTrainer`` + ``NeuralNetThread``
machinery (nnet_impl-inl.hpp:22-496, neural_net-inl.hpp:325-658): instead
of per-device worker threads, semaphore job loops, and an async parameter
server, the whole train step — forward, backward, gradient accumulation,
cross-device reduction, optimizer update — is ONE jitted XLA program
sharded over the mesh. The batch is sharded on the 'data' axis (the
``dev = gpu:0-3`` batch split, nnet_impl-inl.hpp:162-189); XLA's autodiff
inserts the gradient all-reduce over ICI, and its latency-hiding
scheduler overlaps it with compute — the capability the reference built
the layerwise async PS for (SURVEY.md §2.7.6).

API parity (nnet.h:18-92): set_param / init_model / save_model /
load_model / start_round / update / evaluate / predict / extract_feature
/ copy_model_from / set_weight / get_weight.

Semantics kept exactly:
- ``update_period`` gradient accumulation with the loss pre-scaled by
  grad_scale/batch_size and the accumulated gradient divided by
  update_period at apply time — algebraically identical to the
  reference's 1/(batch*update_period) pre-scaling
  (loss_layer_base-inl.hpp:61, nnet_impl-inl.hpp:166-167).
- per-(layer, tag) updaters with tag-scoped hyper-params; LR schedule
  evaluated host-side per applied update (epoch = update counter).
- optimizer state is NOT checkpointed (parity with the reference
  snapshot format, SURVEY.md §5 Checkpoint).
- train metrics accumulated from the training forward pass when
  ``eval_train`` (nnet_impl-inl.hpp:191-197).
"""

from __future__ import annotations

import re
import time
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..artifact import registry as _areg
from ..graph import NetGraph
from ..io.data import DataBatch
from ..parallel import (batch_sharding, make_mesh, opt_state_sharding,
                        param_sharding, replicated)
from ..updater import create_updater
from ..utils.config import ConfigPairs
from ..utils.metric import MetricSet
from .net import FuncNet

_RE_METRIC = re.compile(r"^metric(?:\[([^\]]*)\])?$")


class FinetuneShapeError(ValueError):
    """A finetune source holds a parameter whose shape no longer
    matches the configured net and the layer was NOT declared in
    ``finetune_remap`` — the message names the layer so the fix is one
    config line. ``layer`` / ``tag`` carry the offending group."""

    def __init__(self, layer: str, tag: str, saved_shape, new_shape):
        self.layer = layer
        self.tag = tag
        super().__init__(
            "finetune: layer %r param %r changed shape %s -> %s but is "
            "not listed in finetune_remap — declare it "
            "(finetune_remap = %s) for a fresh re-init, or fix the net "
            "config (finetune_strict = 0 restores the silent "
            "skip-and-reinit behavior)"
            % (layer, tag, tuple(saved_shape), tuple(new_shape), layer))

# the one non-f32 float staging dtype _ship passes through unconverted
# (bf16-warmed serve ladders; numpy spells it via ml_dtypes through jnp)
_BF16 = np.dtype(jnp.bfloat16)


def _tree_add(a, b):
    return jax.tree_util.tree_map(jnp.add, a, b)


def _tree_zeros_like(t):
    return jax.tree_util.tree_map(jnp.zeros_like, t)


class NetTrainer:
    def __init__(self, cfg: ConfigPairs = (), mesh=None):
        self.cfg: List[Tuple[str, str]] = list(cfg)
        self.mesh = mesh
        # trainer-global knobs
        self.batch_size = 0
        self.update_period = 1
        self.eval_train = 1
        self.seed = 0
        self.silent = 0
        self.model_parallel_min = 0      # 0 = no model-parallel sharding
        self.shard_optimizer = 0         # ZeRO-1 (update_on_server analogue)
        self.grad_sync = "fused"         # overlap: per-group gradient
        #                                  reduction boundaries so
        #                                  cross-host sync overlaps the
        #                                  remaining backprop
        #                                  (parallel/gradsync.py); bit-
        #                                  identical to fused by
        #                                  construction
        self.grad_sync_bucket_mb = 0.0   # 0: one reduction group per
        #                                  layer; >0: greedy size
        #                                  buckets of at least this
        #                                  many MB (reverse-layer order
        #                                  either way)
        self.grad_dtype = "float32"      # bfloat16: bf16 cotangents +
        #                                  bf16 grad all-reduce, f32
        #                                  master weights in the updater
        self.save_optimizer = 0          # 1: checkpoint momentum/adam
        #                                  state for seamless resume
        self.remat = "none"              # rematerialization policy for
        #                                  the backward pass: none |
        #                                  full | dots | conv (see
        #                                  _wrap_loss_fn)
        self.remat_barrier = 1           # 0: drop checkpoint's CSE
        #                                  barriers (XLA then undoes
        #                                  the recompute — see
        #                                  _wrap_loss_fn)
        self.dispatch_period = 8         # multi-process lockstep window
        #                                  (shared with the CLI loop's
        #                                  windowed dispatch)
        self.compile_cache_dir = ""      # persistent XLA compilation
        #                                  cache (compile once per
        #                                  machine, not per run)
        self.precompile_dtype = "float32"  # input dtype precompile()
        #                                  lowers for (uint8 pipelines
        #                                  set precompile_dtype=uint8)
        self.serve_dtype = "float32"     # eval/pred/serve compute
        #                                  dtype: float32 | bfloat16 |
        #                                  int8 | fp8 — int8/fp8 need a
        #                                  calibrated snapshot
        #                                  (task=quantize); training
        #                                  dispatch never consults it
        self.quant_tables = {}           # quant/<layer> range arrays
        self.quant_meta = {}             # __meta__["quantized"]
        self.quant_report = {"active": False}
        self.serve_weight_residency = 1  # 0: legacy per-dispatch weight
        #                                  fold/quantize in the traced
        #                                  eval graph; 1: fold+quantize
        #                                  ONCE at load into a device-
        #                                  resident serve weight tree
        #                                  shared by every pred
        #                                  executable (doc/serving.md
        #                                  "Device memory accounting")
        self.serve_device_mem_budget = 0.0  # MB; >0 rejects a model
        #                                  whose resident weight bytes
        #                                  exceed it (typed
        #                                  ResidencyBudgetError, not an
        #                                  OOM). 0 = unlimited
        self.serve_donate = 1            # donate the pred data/mask
        #                                  buffers to the serve-ladder
        #                                  executables (XLA may reuse
        #                                  them for outputs)
        self.input_layout = "none"       # rowmajor: pin the batch
        #                                  input's device layout with
        #                                  channels minor (lane dim) so
        #                                  the compiler cannot pick the
        #                                  batch-minor cliff layout;
        #                                  applied through precompile's
        #                                  AOT lowering + device_put
        self.dist_topology_check = "warn"  # snapshot-vs-runtime
        #                                  topology comparison at load
        #                                  (doc/distributed.md): warn
        #                                  surfaces a changed mesh /
        #                                  world size (the elastic
        #                                  resume path), strict raises,
        #                                  off is silent
        self.resumed_topology = None     # the loaded snapshot's sealed
        #                                  topology dict, when present
        self.topology_changed = False    # load-time mismatch flag (the
        #                                  CLI emits dist_resize off it)
        self.sample_counter = 0          # within accumulation window
        self.update_counter = 0          # applied updates (schedule epoch)
        self.round = 0
        self._initialized = False
        # observability. Counters are always-on host ints (the wrapper
        # progress-poll surface); everything time-based lives behind
        # the monitor so monitor=none adds NO host<->device syncs to
        # the step path.
        self._mon = None                 # monitor.Monitor or None
        self._steps_total = 0            # dispatches (telemetry step id)
        self._examples_total = 0         # real (non-padded) local rows
        self._round_examples = 0
        self._round_t0 = None            # set by start_round
        self.last_round_examples_per_sec = 0.0   # of the closed round
        self._pending_data_wait = 0.0    # loop-measured iterator wait
        self.last_round_examples = 0     # set by end_round
        self.last_round_wall_s = 0.0
        # the program registry: every AOT executable this trainer owns,
        # keyed by (kind,) + dispatch signature, plus the compile-event
        # signature set and the sealed-artifact hit/rebuild accounting
        # (cxxnet_tpu.artifact.registry — serve/bench/pred consume it
        # through this trainer). Empty = every dispatch goes through jit
        self.programs = _areg.ProgramRegistry()
        self.precompile_wall_s = 0.0
        self.precompile_programs = 0

    # -- config ----------------------------------------------------------

    def set_param(self, name: str, val: str) -> None:
        self.cfg.append((name, val))

    def _absorb_globals(self) -> None:
        self.metric_cfg: List[Tuple[str, str, str]] = []  # (name,field,node)
        for name, val in self.cfg:
            if name == "batch_size":
                self.batch_size = int(val)
            if name == "update_period":
                self.update_period = int(val)
            if name in ("eval_train", "train_eval"):
                self.eval_train = int(val)
            if name == "seed":
                self.seed = int(val)
            if name == "silent":
                self.silent = int(val)
            if name == "model_parallel_min":
                self.model_parallel_min = int(val)
            if name == "grad_dtype":
                if val not in ("float32", "bfloat16"):
                    raise ValueError(
                        "grad_dtype must be float32 or bfloat16")
                self.grad_dtype = val
            if name == "save_optimizer":
                self.save_optimizer = int(val)
            if name == "remat":
                if val not in ("none", "0", "full", "dots", "conv"):
                    raise ValueError("remat must be none|full|dots|conv")
                self.remat = "none" if val == "0" else val
            if name == "remat_barrier":
                self.remat_barrier = int(val)
            if name == "dispatch_period":
                self.dispatch_period = max(1, int(val))
            if name == "compile_cache_dir":
                self.compile_cache_dir = val
            if name == "precompile_dtype":
                if val not in ("float32", "uint8"):
                    raise ValueError(
                        "precompile_dtype must be float32 or uint8")
                self.precompile_dtype = val
            if name == "input_layout":
                if val not in ("none", "rowmajor"):
                    raise ValueError(
                        "input_layout must be none or rowmajor")
                self.input_layout = val
            if name == "serve_dtype":
                from .quantize import normalize_serve_dtype
                self.serve_dtype = normalize_serve_dtype(val)
            if name == "serve_weight_residency":
                self.serve_weight_residency = int(val)
            if name == "serve_device_mem_budget":
                self.serve_device_mem_budget = float(val)
            if name == "serve_donate":
                self.serve_donate = int(val)
            if name == "dist_topology_check":
                if val not in ("off", "warn", "strict"):
                    raise ValueError(
                        "dist_topology_check must be off|warn|strict")
                self.dist_topology_check = val
            if name in ("shard_optimizer", "update_on_server",
                        "optim_shard"):
                # update_on_server=1 meant "optimizer state lives off the
                # workers" (nnet_ps_server.cpp); here it means "optimizer
                # state is ZeRO-sharded across the data axis".
                # optim_shard is the ZeRO-1 spelling (doc/updater.md)
                self.shard_optimizer = int(val)
            if name == "grad_sync":
                if val not in ("fused", "overlap"):
                    raise ValueError("grad_sync must be fused|overlap")
                self.grad_sync = val
            if name == "grad_sync_bucket_mb":
                self.grad_sync_bucket_mb = float(val)
                if self.grad_sync_bucket_mb < 0:
                    raise ValueError("grad_sync_bucket_mb must be >= 0")
            m = _RE_METRIC.match(name)
            if m:
                spec = m.group(1)
                field, node = "label", ""
                if spec:
                    parts = [p.strip() for p in spec.split(",")]
                    field = parts[0] or "label"
                    if len(parts) > 1:
                        node = parts[1]
                self.metric_cfg.append((val, field, node))

    # -- model lifecycle -------------------------------------------------

    def init_model(self) -> None:
        self._absorb_globals()
        self.graph = NetGraph()
        self.graph.configure(self.cfg)
        if self.batch_size == 0:
            self.batch_size = self.graph.batch_size
        assert self.batch_size > 0, "batch_size must be set"
        self.net = FuncNet(self.graph, self.batch_size)
        key = jax.random.PRNGKey(self.seed)
        self.params, self.net_state = self.net.init(key)
        self._post_init()

    def _post_init(self) -> None:
        """Everything shared by init_model and load_model."""
        self._enable_persistent_cache()
        g = self.graph
        # one updater per (param layer, tag)
        self.updaters: Dict[str, Dict[str, Any]] = {}
        self._layer_index: Dict[str, int] = {}
        for lkey, ptree in self.params.items():
            li = g.layer_index(lkey) if lkey in g.layer_name_map \
                else int(lkey[5:])
            self._layer_index[lkey] = li
            self.updaters[lkey] = {}
            for tag in ptree:
                self.updaters[lkey][tag] = create_updater(
                    g.updater_type, tag, g.defcfg, g.layercfg[li])
        self.opt_state = {
            lk: {tag: self.updaters[lk][tag].init_state(w)
                 for tag, w in pt.items()}
            for lk, pt in self.params.items()}
        if self.mesh is None:
            from ..parallel import default_data_axis
            self.mesh = make_mesh(default_data_axis(self.batch_size), 1)
        # metric bindings -> node indices
        self._metrics = MetricSet()
        self._train_metrics = MetricSet()
        self._metric_nodes: List[int] = []
        top = self.graph.num_nodes - 1
        for mname, field, node in self.metric_cfg:
            self._metrics.add_metric(mname, field, node)
            self._train_metrics.add_metric(mname, field, node)
            ni = self.net.node_index_by_name(node) if node else top
            self._metric_nodes.append(ni)
        self._label_slices = self.graph.label_slices()
        # serve_dtype activation BEFORE the programs build: the specs
        # live on the layer objects and must be pinned before any
        # forward traces (nnet/quantize.attach)
        self._attach_quant()
        self._build_steps()
        self._put_all()
        self._initialized = True
        self._emit_model_records()

    def _attach_quant(self) -> None:
        from .quantize import attach
        self.quant_report = attach(self)

    def set_quantization(self, tables, meta,
                         dtype: Optional[str] = None) -> None:
        """Install calibration range tables (and optionally switch the
        serve dtype), then rebuild the dispatch programs so the next
        eval/pred traces the quantized graph. The tables ride in every
        subsequent snapshot as digest-covered ``quant/`` arrays
        (task=quantize is the canonical caller)."""
        assert self._initialized, "call init_model/load_model first"
        self.quant_tables = dict(tables)
        self.quant_meta = dict(meta)
        if dtype is not None:
            from .quantize import normalize_serve_dtype
            self.serve_dtype = normalize_serve_dtype(dtype)
        self._attach_quant()
        self._build_steps()
        self._put_all()
        self._emit_model_records()

    def _put_all(self) -> None:
        """Place params/state on the mesh with their shardings."""
        self.params = jax.device_put(self.params, self._p_shard)
        self.net_state = jax.device_put(
            self.net_state,
            jax.tree_util.tree_map(lambda _: self._repl, self.net_state))
        # optimizer state mirrors its weight's sharding (momentum of a
        # model-sharded fullc weight shards the same way), or is ZeRO-1
        # sharded across 'data' when shard_optimizer is set
        self.opt_state = jax.device_put(self.opt_state, self._o_shard)
        if self.update_period > 1:
            self.grad_acc = jax.device_put(
                _tree_zeros_like(self.params), self._p_shard)
        else:
            self.grad_acc = None

    # -- jitted programs -------------------------------------------------

    def _build_steps(self) -> None:
        mesh = self.mesh
        self.programs.reset()            # rebuilt programs orphan any
        #                                  earlier AOT executables
        self._b_shard = batch_sharding(mesh)
        self._probe_input_layout()
        self._repl = replicated(mesh)
        self._repl_leaf = self._repl
        self._p_shard = param_sharding(mesh, self.params,
                                       self.model_parallel_min)
        # optimizer-state shardings (ZeRO-1 over 'data' when enabled)
        self._o_shard = {
            lk: {tag: jax.tree_util.tree_map(
                lambda leaf, _ps=self._p_shard[lk][tag]: opt_state_sharding(
                    leaf.shape, _ps.spec, mesh,
                    bool(self.shard_optimizer)),
                st)
                for tag, st in tags.items()}
            for lk, tags in self.opt_state.items()}
        net = self.net
        metric_nodes = tuple(self._metric_nodes)
        update_period = self.update_period
        # stable (layer, tag) -> row in the packed hyper array; packing
        # all per-step host float scalars (lr/momentum/wd) into ONE
        # small array keeps host->device traffic to a single transfer
        # per step (tunnel/PCIe latency dominates tiny transfers). The
        # epoch rides as its own uint32 scalar beside it — a float32
        # slot silently rounds integers past 2^24, skewing Adam's bias
        # correction on long runs (same fix pattern as the RNG `step`)
        self._hyper_index = [(lk, tag)
                             for lk, tags in sorted(self.updaters.items())
                             for tag in sorted(tags)]
        self._base_key = jax.random.PRNGKey(self.seed + 1)

        def unpack_hyper(hyper_arr, idx, epoch):
            return {"learning_rate": hyper_arr[idx, 0],
                    "momentum": hyper_arr[idx, 1],
                    "wd": hyper_arr[idx, 2],
                    "epoch": epoch}

        hyper_row = {(lk, tag): i
                     for i, (lk, tag) in enumerate(self._hyper_index)}

        def apply_updates(params, opt_state, grads, hyper_arr, epoch):
            new_p, new_o = {}, {}
            for lk, ptree in params.items():
                new_p[lk], new_o[lk] = {}, {}
                for tag, w in ptree.items():
                    if not opt_state[lk][tag]:
                        # frozen group (lr_mult = 0): state allocation
                        # was skipped, the weight passes through
                        # untouched — bit-exact vs the pinned freeze
                        new_p[lk][tag] = w
                        new_o[lk][tag] = {}
                        continue
                    upd = self.updaters[lk][tag]
                    g = grads[lk][tag]
                    if update_period > 1:
                        g = g / float(update_period)
                    w2, s2 = upd.apply(
                        w, g, opt_state[lk][tag],
                        unpack_hyper(hyper_arr, hyper_row[(lk, tag)],
                                     epoch))
                    new_p[lk][tag] = w2
                    new_o[lk][tag] = s2
            return new_p, new_o

        grad_bf16 = self.grad_dtype == "bfloat16"
        if grad_bf16 and not any(
                k == "dtype" and v == "bfloat16" for k, v in self.cfg):
            raise ValueError(
                "grad_dtype=bfloat16 requires dtype=bfloat16 (layers "
                "must consume the bf16 weight shadow)")

        def _grad_cast(params):
            """bf16 shadow of the f32 master weights to differentiate
            against: cotangents then flow (and all-reduce across the
            'data' axis) in bf16 — half the gradient HBM/ICI bytes —
            while apply_updates reads the f32 masters (SURVEY §7 step 8
            mixed precision)."""
            if not grad_bf16:
                return params
            return jax.tree_util.tree_map(
                lambda w: w.astype(jnp.bfloat16)
                if w.dtype == jnp.float32 else w, params)

        def _grad_f32(grads):
            if not grad_bf16:
                return grads
            return jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), grads)

        def _wrap_loss_fn():
            """Rematerialization policy over the shared loss body.

            The reference trades compute for memory under an explicit
            budget (im2col chunking via temp_col_max,
            convolution_layer-inl.hpp:189-204); the TPU analogue is
            ``jax.checkpoint`` over the loss function, trading backward
            HBM activation traffic for recompute on the (mostly idle —
            doc/perf_profile.md roofline) MXU:

            * full — save only the step inputs; backward recomputes the
              entire forward.
            * dots — save dot_general (FC) outputs; recompute
              everything else (convs included — they are not dots).
            * conv — save ONLY conv-layer outputs (tagged ``conv_out``
              in layers/conv.py); FC dots, BN, activations and pools
              are recomputed.

            remat_barrier=0 drops the optimization barriers
            (prevent_cse=False). Measured (doc/perf_profile.md r5):
            the forward and its backward recompute live in the SAME
            XLA computation here (value_and_grad inside one step), so
            without barriers XLA CSEs the recompute against the stored
            forward and the program returns to the remat=none baseline
            — no cost, but no memory savings either. Barriers stay the
            default because guaranteed recompute is the knob's purpose
            (HBM capacity).
            """
            fn = (lambda p, s, d, l, m, e, r:
                  net.loss_fn(p, s, d, l, m, extra=e, rng=r,
                              collect_nodes=metric_nodes))
            if self.remat == "none":
                return fn
            barrier = bool(self.remat_barrier)
            if self.remat == "full":
                return jax.checkpoint(fn, prevent_cse=barrier)
            if self.remat == "dots":
                return jax.checkpoint(
                    fn, prevent_cse=barrier,
                    policy=jax.checkpoint_policies
                    .dots_with_no_batch_dims_saveable)
            policy = jax.checkpoint_policies.save_only_these_names(
                "conv_out")
            return jax.checkpoint(fn, prevent_cse=barrier, policy=policy)

        loss_fn = _wrap_loss_fn()
        # grad_sync = overlap: thread each reduction group's params
        # through an identity custom-vjp boundary INSIDE the
        # differentiated loss. The backward barriers make each group's
        # gradients (and the SPMD all-reduce that consumes them) an
        # atomic schedulable unit, so XLA issues group g's cross-host
        # reduction as soon as g's backward finishes — overlapping DCN
        # traffic with the remaining (earlier-layer) backprop. Identity
        # numerics: bit parity with fused is by construction (pinned in
        # tests/test_gradsync.py at H=2,4).
        self._sync_groups = None
        if self.grad_sync == "overlap":
            from ..parallel import gradsync as _gradsync
            self._sync_groups = _gradsync.partition_groups(
                self.params, self._layer_index,
                bucket_mb=self.grad_sync_bucket_mb)
            _fused_loss = loss_fn
            _groups = self._sync_groups

            def loss_fn(p, s, d, l, m, e, r):
                return _fused_loss(
                    _gradsync.apply_group_boundaries(p, _groups),
                    s, d, l, m, e, r)

        def scan_step(params, opt_state, net_state, grad_acc,
                      data, labels, mask, extra, hyper_row, epoch,
                      do_up, step, base_key, collect):
            """The ONE train-step body all dispatch paths share
            (update / update_many / run_steps — a single definition so
            the math cannot drift between them). do_up may be traced
            (scan windows) or a static bool (per-batch update); the
            hyper row is per-step so the LR/momentum schedule advances
            inside scanned dispatches. ``step`` and ``epoch`` ride as
            their own uint32 scalars — a float32 hyper-array slot
            silently rounds past 2^24, repeating dropout/insanity RNG
            streams (step) and skewing Adam's bias correction (epoch)
            on long runs."""
            rng = jax.random.fold_in(base_key, step)
            (loss, (new_state, preds)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(
                    _grad_cast(params), net_state, data, labels, mask,
                    extra, rng)
            preds = [p.astype(jnp.float32) for p in preds] if collect \
                else []
            if update_period == 1:
                params, opt_state = apply_updates(
                    params, opt_state, _grad_f32(grads), hyper_row,
                    epoch)
                return (params, opt_state, new_state, grad_acc, loss,
                        preds)
            # accumulate in f32 regardless of gradient dtype
            grad_acc = _tree_add(grad_acc, _grad_f32(grads))

            def do_apply(args):
                p, o, acc = args
                p2, o2 = apply_updates(p, o, acc, hyper_row, epoch)
                return p2, o2, _tree_zeros_like(acc)

            params, opt_state, grad_acc = jax.lax.cond(
                do_up, do_apply, lambda a: a,
                (params, opt_state, grad_acc))
            return params, opt_state, new_state, grad_acc, loss, preds

        def train_step(params, opt_state, net_state, grad_acc,
                       data, labels, mask, extra, hyper_arr, epoch,
                       step, base_key, do_update):
            return scan_step(params, opt_state, net_state, grad_acc,
                             data, labels, mask, extra, hyper_arr,
                             epoch, do_update, step, base_key, True)

        donate = (0, 1, 3) if update_period > 1 else (0, 1)
        # pin output shardings: without this, GSPMD propagation from the
        # ZeRO-sharded optimizer state drifts the *weights* into a
        # data-sharded layout too (ZeRO-3-like), forcing an all-gather
        # in every forward pass
        ns_shard = jax.tree_util.tree_map(lambda _: self._repl,
                                          self.net_state)
        acc_shard = self._p_shard if update_period > 1 else None
        out_shardings = (self._p_shard, self._o_shard, ns_shard,
                         acc_shard, self._repl, self._b_shard)
        self._train_step = jax.jit(train_step, donate_argnums=donate,
                                   static_argnames=("do_update",),
                                   out_shardings=out_shardings)

        def multi_step(params, opt_state, net_state, grad_acc, data,
                       labels, mask, extra, hyper_k, epoch_k, do_up_k,
                       step, base_key):
            """n_steps train steps in ONE dispatch (lax.scan over the
            same resident batch) — host dispatch latency amortizes to
            zero. hyper_k is (n_steps, n_updaters, 3): the schedule
            advances per step in-scan; epoch_k/do_up_k carry the exact
            uint32 epochs and the accumulation-window apply flags, so
            ``update_period > 1`` closes its windows in-scan exactly
            like the per-batch dispatch path."""
            def body(carry, xs):
                p, o, s, acc = carry
                hyper_i, epoch_i, do_up, i = xs
                p, o, s, acc, loss, _ = scan_step(
                    p, o, s, acc, data, labels, mask, extra, hyper_i,
                    epoch_i, do_up, step + i, base_key, False)
                return (p, o, s, acc), loss
            n = hyper_k.shape[0]
            carry, losses = jax.lax.scan(
                body, (params, opt_state, net_state, grad_acc),
                (hyper_k, epoch_k, do_up_k,
                 jnp.arange(n, dtype=jnp.uint32)))
            params, opt_state, net_state, grad_acc = carry
            return params, opt_state, net_state, grad_acc, losses[-1]

        self._multi_step = jax.jit(
            multi_step, donate_argnums=donate,
            out_shardings=(self._p_shard, self._o_shard, ns_shard,
                           acc_shard, self._repl))

        # K-batch window sharding: leading axis = scan step, batch rows
        # sharded on 'data' as usual
        self._kb_shard = NamedSharding(mesh, P(None, "data"))
        self._stack_k = jax.jit(lambda *xs: jnp.stack(xs),
                                out_shardings=self._kb_shard)

        def many_step(params, opt_state, net_state, grad_acc,
                      data_k, labels_k, mask_k, extra_k, hyper_k,
                      epoch_k, do_up_k, step, base_key, collect):
            """K REAL batches in one dispatch: scan over the stacked
            window. Schedule-correct (per-step hyper rows + exact
            uint32 epochs) and update_period-correct (traced apply
            flags)."""
            def body(carry, xs):
                p, o, s, acc = carry
                (data, labels, mask, extra, hyper_i, epoch_i, do_up,
                 i) = xs
                p, o, s, acc, loss, preds = scan_step(
                    p, o, s, acc, data, labels, mask, extra, hyper_i,
                    epoch_i, do_up, step + i, base_key, collect)
                return (p, o, s, acc), (loss, preds)
            K = hyper_k.shape[0]
            carry, (losses, preds_k) = jax.lax.scan(
                body, (params, opt_state, net_state, grad_acc),
                (data_k, labels_k, mask_k, extra_k, hyper_k, epoch_k,
                 do_up_k, jnp.arange(K, dtype=jnp.uint32)))
            params, opt_state, net_state, grad_acc = carry
            return (params, opt_state, net_state, grad_acc, losses[-1],
                    preds_k)

        self._many_step = jax.jit(
            many_step, donate_argnums=donate,
            static_argnames=("collect",),
            out_shardings=(self._p_shard, self._o_shard, ns_shard,
                           acc_shard, self._repl, self._kb_shard))

        def pred_step(params, net_state, data, mask, extra,
                      nodes_wanted):
            node_vals, _, _ = net.forward(params, net_state, data,
                                          extra=extra,
                                          is_train=False, rng=None,
                                          mask=mask)
            # metrics/extraction read f32 LOGICAL tensors regardless of
            # compute dtype / channel padding
            return [net.depad_node(i, node_vals[i]).astype(jnp.float32)
                    for i in nodes_wanted]

        self._pred_step = jax.jit(pred_step,
                                  static_argnames=("nodes_wanted",))
        # the serve-ladder variant donates the batch data/mask buffers
        # (consumed exactly once per dispatch) so XLA may reuse them
        # for outputs; compiled only by precompile_pred(donate=True) —
        # results are identical, so the two variants are interchangeable
        self._pred_step_donate = jax.jit(pred_step,
                                         static_argnames=("nodes_wanted",),
                                         donate_argnums=(2, 3))
        self._build_resident_prep()

    def _probe_input_layout(self) -> None:
        """input_layout = rowmajor support probe: a tiny device_put
        with an explicit major-to-minor layout. Unsupported backends /
        jax builds fall back to unpinned with one warning — the knob
        must never break a run, only bias the compiler away from the
        batch-minor cliff layout (doc/perf_profile.md: batch 160 put
        the batch on the 128-lane minor dim, 5,082 -> 3,088 img/s)."""
        self._layout_cls = None
        if self.input_layout != "rowmajor":
            return
        if jax.process_count() > 1:
            # multi-process batches come through
            # make_array_from_process_local_data, which takes no layout
            # — an AOT program lowered with a pinned input layout would
            # then mismatch every dispatched array. Pin single-process
            # only.
            from ..monitor import warn_once
            warn_once("input_layout_multiprocess",
                      "input_layout=rowmajor is single-process only; "
                      "inputs stay unpinned under multi-process dp")
            return
        try:
            from jax.experimental.layout import (DeviceLocalLayout,
                                                 Layout)
            probe = jax.device_put(
                np.zeros((2, 2, 2, 2), np.float32),
                Layout(DeviceLocalLayout(major_to_minor=(0, 1, 2, 3)),
                       self._b_shard))
            jax.block_until_ready(probe)
            self._layout_cls = (DeviceLocalLayout, Layout)
        except Exception as e:
            from ..monitor import warn_once
            warn_once("input_layout_unsupported",
                      "input_layout=rowmajor is not supported by this "
                      "backend/jax build (%s); inputs stay unpinned"
                      % e)

    def _pin_layout(self, sharding, ndim: int):
        """Row-major (channels-minor) layout pin for a batch input, or
        the plain sharding when pinning is off/unsupported."""
        if self._layout_cls is None or ndim < 4:
            return sharding
        dll, layout = self._layout_cls
        return layout(dll(major_to_minor=tuple(range(ndim))), sharding)

    # -- device-resident serve weights (doc/serving.md) ------------------

    def _resident_plan(self) -> List[Dict[str, Any]]:
        """Static per-layer plan of the eval-graph weight work that can
        hoist out of the per-dispatch traced graph into a one-time
        freeze: ``bn_fold_eval`` weight folds, int8/fp8 weight
        quantization, bf16 weight casts, and the per-channel epilogue
        vectors. Channel-alignment-annotated layers keep the legacy
        in-graph path (channel_pad is a training-bench knob; serving
        graphs run unpadded). Empty plan = the serve tree IS the master
        tree (nothing to hoist, nothing extra resident)."""
        net, g = self.net, self.graph
        shared_primaries = set(info.primary_layer_index
                               for info in g.layers
                               if info.type == "share")
        plan: List[Dict[str, Any]] = []
        for li, info in enumerate(g.layers):
            if info.type not in ("conv", "fullc") \
                    or li in shared_primaries:
                continue
            lkey = g.layer_key(li)
            if lkey not in self.params \
                    or "wmat" not in self.params[lkey]:
                continue
            layer = net.layer_objs[li]
            if (getattr(layer, "_in_layout", None) is not None
                    or getattr(layer, "_out_pad", 0)
                    or getattr(layer, "_layout", None) is not None):
                continue
            q = getattr(layer, "_quant", None)
            quant = q is not None and q.is_affine
            bf16 = (layer.param.compute_dtype == "bfloat16"
                    or (q is not None and q.dtype == "bfloat16"))
            fold = (info.type == "conv" and net._bn_fold_eval
                    and li in net._fold_pairs)
            # with conv_pallas_epilogue the fold factor applies to the
            # conv OUTPUT (no per-dispatch weight work exists): only
            # the scale/shift vectors precompute, the weight stays raw
            epifold = (fold and not quant
                       and bool(layer.param.conv_pallas_epilogue))
            prefold = fold and not epifold
            if not (quant or bf16 or prefold or epifold):
                continue
            relu = False
            if fold:
                relu = bool(net.layer_objs[net._fold_pairs[li]]
                            .fuse_relu)
            plan.append({"li": li, "lkey": lkey, "kind": info.type,
                         "q": q, "quant": quant, "bf16": bf16,
                         "prefold": prefold, "epifold": epifold,
                         "relu": relu,
                         "has_bias": layer.param.no_bias == 0})
        return plan

    def _build_resident_prep(self) -> None:
        """The ONE-time serve-weight transformation program: folds,
        quantizes and casts the eval weight tree on device at freeze
        (registered in ``lint/config.py PROGRAM_BUILDERS``). Returns
        only the NEW leaves — untransformed weights alias the masters
        so they are never duplicated on device."""
        self._serve_plan = self._resident_plan()
        self._serve_prep = None
        if not self._serve_plan:
            return
        net = self.net
        plan = self._serve_plan

        def prep(params, net_state):
            out: Dict[str, Dict[str, Any]] = {}
            for item in plan:
                p = params[item["lkey"]]
                new: Dict[str, Any] = {}
                w = p["wmat"]
                b = p.get("bias") if item["has_bias"] else None
                eff = None
                if item["prefold"] or item["epifold"]:
                    fe = net._fold_entries(params, net_state,
                                           item["li"])
                    scale, shift = fe["_fold_scale"], fe["_fold_shift"]
                    if item["prefold"]:
                        w = w * scale
                        eff = shift if b is None else shift + b * scale
                    else:
                        new["_fold_scale"] = scale
                        new["_fold_shift"] = shift
                        if item["relu"]:
                            # value never read — key presence is the
                            # (static) relu flag, as on the legacy path
                            new["_fold_relu"] = jnp.ones((),
                                                         jnp.float32)
                if item["quant"]:
                    q = item["q"]
                    w = q.quantize_w(w)
                    dq = q.dequant_vec()
                    new["_r_dequant"] = dq
                    if item["kind"] == "conv":
                        shift_vec = eff if eff is not None \
                            else (b if b is not None
                                  else jnp.zeros_like(dq))
                        new["_r_shift_relu" if item["relu"]
                            else "_r_shift"] = shift_vec
                elif item["prefold"]:
                    new["_r_shift_relu" if item["relu"]
                        else "_r_shift"] = eff
                if item["bf16"] and not item["quant"]:
                    w = w.astype(jnp.bfloat16)
                if item["quant"] or item["prefold"] or item["bf16"]:
                    new["wmat"] = w
                out[item["lkey"]] = new
            return out

        self._serve_prep = jax.jit(prep)

    def _predict_resident_extra(self) -> int:
        """Bytes the serve tree will add beyond the masters, computed
        from the plan WITHOUT touching the device — so a budget breach
        rejects before the upload, not as an OOM during it."""
        extra = 0
        for item in self._serve_plan:
            w = self.params[item["lkey"]]["wmat"]
            n = int(np.prod(w.shape))
            if item["quant"]:
                extra += n if item["q"].native else 4 * n
            elif item["bf16"]:
                extra += 2 * n
            elif item["prefold"]:
                extra += 4 * n
            # per-channel vectors are noise next to the weight tensors
        return extra

    def freeze_serve_weights(self, force: bool = False):
        """Build (or return) the device-resident serve weight tree:
        eval folds applied, int8/fp8 weights quantized, bf16 weights
        cast — exactly once — and install it in the program registry
        with honest byte accounting against
        ``serve_device_mem_budget``. Every subsequent pred dispatch
        passes the tree as arguments, so all bucket executables share
        one copy per model. Returns the
        :class:`~cxxnet_tpu.artifact.registry.WeightResidency` (None
        when ``serve_weight_residency = 0``). Any weight mutation
        (update/set_weight/copy_model_from/program rebuild) invalidates
        the tree; the next pred dispatch re-freezes against the same
        executables (identical avals — no recompile)."""
        assert self._initialized, "call init_model/load_model first"
        if not self.serve_weight_residency:
            return None
        reg = self.programs
        if reg.residency is not None and not force:
            return reg.residency
        budget = int(self.serve_device_mem_budget * 1e6)

        def tree_bytes(pytrees, seen):
            tot = 0
            for tr in pytrees:
                for pt in tr.values():
                    for v in pt.values():
                        if id(v) in seen:
                            continue
                        seen.add(id(v))
                        tot += int(getattr(v, "nbytes", 0) or 0)
            return tot

        seen: set = set()
        master = tree_bytes((self.params, self.net_state), seen)
        extra = self._predict_resident_extra()
        if budget and master + extra > budget:
            raise _areg.ResidencyBudgetError(
                "model needs ~%d resident bytes (masters %d + serve "
                "tree extra %d) but serve_device_mem_budget allows %d"
                % (master + extra, master, extra, budget))
        t0 = time.perf_counter()
        if self._serve_prep is not None:
            new = self._serve_prep(self.params, self.net_state)
            jax.block_until_ready(new)
            tree = {lk: ({**pt, **new[lk]} if lk in new else pt)
                    for lk, pt in self.params.items()}
        else:
            tree = self.params
        quantize_ms = (time.perf_counter() - t0) * 1e3
        tb = tree_bytes((tree,), set())
        # ``seen`` already holds every master buffer: only the leaves
        # the prep program materialized add to the deduped total
        total = master + tree_bytes((tree,), seen)
        res = _areg.WeightResidency(
            tree, tb, master, total, quantize_ms,
            len(self._serve_plan), self.serve_dtype,
            bool(self._serve_plan))
        reg.install_weights(res, budget)
        if self._mon_on():
            self._mon.emit("weight_residency", **res.record())
        return res

    def _pred_operands(self):
        """The (params, net_state) every eval/pred dispatch passes:
        the device-resident serve tree under weight residency (frozen
        lazily), the raw masters otherwise. One definition so
        precompile keys and dispatch operands can never disagree on
        the calling convention."""
        if self.serve_weight_residency:
            res = self.programs.residency or self.freeze_serve_weights()
            if res is not None:
                return res.tree, self.net_state
        return self.params, self.net_state

    @property
    def _aot(self) -> Dict[tuple, Any]:
        """The registry's executable map — kept as a read surface for
        the serve engine's aot-hit accounting and tests; mutation goes
        through ``self.programs``."""
        return self.programs.aot

    @property
    def _seen_sigs(self) -> set:
        """Dispatch signatures seen (compile/recompile detection) —
        registry-owned so precompile seeding and bundle installs share
        one set with the dispatch-time accounting."""
        return self.programs.seen

    def _call_step(self, kind, sig, jit_fn, args, **static_kw):
        """Dispatch one program: the registry executable when this
        exact signature was precompiled (or installed from a sealed
        artifact — static args baked in either way), the jit function
        otherwise. One code path so a key-scheme change cannot
        silently strand a dispatch site on jit fallback."""
        aot = self.programs.get((kind,) + sig)
        if aot is not None:
            return aot(*args)
        return jit_fn(*args, **static_kw)

    # the pred dispatch signature (sans the leading "pred" kind): the
    # single definition — cxxnet_tpu.artifact.registry.pred_sig —
    # shared by `_call_pred`, `precompile_pred`, the serve engine's
    # compile-event accounting, and the sealed-bundle key encoding; a
    # key-scheme change cannot strand one of them on a stale scheme
    pred_sig = staticmethod(_areg.pred_sig)

    def _call_pred(self, data, mask, extra, nodes_wanted):
        params, net_state = self._pred_operands()
        sig = self.pred_sig(data.shape, data.dtype, mask is None,
                            len(extra), nodes_wanted)
        return self._call_step(
            "pred", sig, self._pred_step,
            (params, net_state, data, mask, extra),
            nodes_wanted=nodes_wanted)

    # -- AOT precompile --------------------------------------------------

    def _enable_persistent_cache(self) -> None:
        """Point jax at a persistent on-disk compilation cache
        (``compile_cache_dir``): recompiles across RUNS become cache
        deserializations — the first-round compile cost is paid once
        per (program, jaxlib, flags) per machine."""
        if not self.compile_cache_dir:
            return
        jax.config.update("jax_compilation_cache_dir",
                          self.compile_cache_dir)
        for k, v in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                     ("jax_persistent_cache_min_entry_size_bytes", -1)):
            try:
                jax.config.update(k, v)
            except Exception:            # knob not in this jax version
                pass  # cxxlint: disable=CXL006 -- optional cache-tuning knob; absence on older jax is expected and harmless
        try:
            # drop the 'cache disabled' state memoized by any compile
            # that ran before the dir was configured (library init,
            # net.init) — without this the dir is set but never written
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception as e:
            # the user configured compile_cache_dir: if the memoized
            # 'disabled' state cannot be dropped the cache may never
            # be written — say so once instead of silently not caching
            from ..monitor import warn_once
            warn_once("compile_cache_reset_failed",
                      "could not reset the jax compilation cache "
                      "state (%s); compile_cache_dir may not take "
                      "effect for programs compiled before init" % e)

    def precompile(self, window: int = 1, n_steps: int = 0,
                   per_batch: bool = True) -> int:
        """AOT-compile the dispatch programs for the shapes this run
        will use, before round 0 touches the device.

        ``.lower().compile()``s the per-batch train step, the K-window
        ``update_many`` step (``window`` > 1 — pass the CLI loop's
        dispatch_period), the eval/pred forward, and (``n_steps`` > 0)
        the ``run_steps`` scan, each for every mask variant the run can
        dispatch. The compiled executables are kept and dispatched
        directly (no jit-cache round trip), so the steady-state loop
        never sees a compile: the recompile stalls PR 1's telemetry
        records in round 0 move to a single accounted precompile window
        — and with ``compile_cache_dir`` set they amortize across runs.

        Shapes must be fully known: batch_size from the config, the
        instance shape from ``input_shape``, input dtype from
        ``precompile_dtype`` (uint8 for raw-pixel pipelines). Nets with
        ``extra_data`` inputs and eval iterators with a different
        batch_size fall back to the jit path for those dispatches —
        precompile never changes results, only when compilation
        happens. ``per_batch=False`` compiles ONLY the ``run_steps``
        program (the bench capture path — no wasted minutes on update/
        pred variants the capture never dispatches). With
        ``input_layout = rowmajor`` the lowered programs pin the batch
        input's device layout channels-minor. Returns the number of
        programs compiled."""
        assert self._initialized, "call init_model/load_model first"
        from ..io.data import inst_array_shape
        t_start = time.perf_counter()
        self._enable_persistent_cache()
        dtype = np.dtype(np.uint8 if self.precompile_dtype == "uint8"
                         else np.float32)
        # GLOBAL batch shapes: multi-process dispatch arrays come out of
        # make_array_from_process_local_data with the global leading dim
        # (each rank contributes batch_size/world rows), and the runtime
        # signature keys use those global shapes
        n = self.batch_size
        data_shape = (n,) + inst_array_shape(
            tuple(self.graph.input_shape))
        lw = max((b for _, _a, b in self._label_slices), default=1)
        label_shape = (n, lw)

        def sds(shape, dt, sharding=None):
            if sharding is None:
                return jax.ShapeDtypeStruct(shape, dt)
            return jax.ShapeDtypeStruct(shape, dt, sharding=sharding)

        data_s = sds(data_shape, dtype,
                     self._pin_layout(self._b_shard, len(data_shape)))
        labels_s = sds(label_shape, np.float32, self._b_shard)
        hyper_s = sds((len(self._hyper_index), 3), np.float32)
        step_s = sds((), np.uint32)
        epoch_s = sds((), np.uint32)
        # the None-mask specialization only exists single-process
        # (multi-process dp always materializes the mask — see _mask)
        mask_variants = [None, sds((n,), np.float32, self._b_shard)]
        if jax.process_count() > 1:
            mask_variants = [sds((n,), np.float32, self._b_shard)]
        do_up_variants = [True] if self.update_period == 1 \
            else [True, False]
        programs = []                    # (key, lower_thunk)

        for mask_v in (mask_variants if per_batch else []):
            for du in do_up_variants:
                key = ("update",) + _areg.update_sig(
                    data_shape, dtype, label_shape, mask_v is None, 0,
                    bool(du))
                programs.append((key, lambda m=mask_v, d=du:
                                 self._train_step.lower(
                                     self.params, self.opt_state,
                                     self.net_state, self.grad_acc,
                                     data_s, labels_s, m, (), hyper_s,
                                     epoch_s, step_s, self._base_key,
                                     do_update=d)))
            if window > 1:
                K = int(window)
                data_k_s = sds((K,) + data_shape, dtype, self._kb_shard)
                labels_k_s = sds((K,) + label_shape, np.float32,
                                 self._kb_shard)
                mask_k = None if mask_v is None \
                    else sds((K, n), np.float32, self._kb_shard)
                hyper_k_s = sds((K, len(self._hyper_index), 3),
                                np.float32)
                epoch_k_s = sds((K,), np.uint32)
                do_up_s = sds((K,), np.bool_)
                collect = bool(self.eval_train and self._metrics.evals)
                key = ("update_many",) + _areg.update_many_sig(
                    (K,) + data_shape, dtype, (K,) + label_shape,
                    mask_k is None, 0, K, collect)
                programs.append((key, lambda mk=mask_k, c=collect,
                                 ds=data_k_s, ls=labels_k_s,
                                 hs=hyper_k_s, es=epoch_k_s,
                                 us=do_up_s:
                                 self._many_step.lower(
                                     self.params, self.opt_state,
                                     self.net_state, self.grad_acc,
                                     ds, ls, mk, (), hs, es, us,
                                     step_s, self._base_key,
                                     collect=c)))
            if self._metric_nodes:
                nodes = tuple(self._metric_nodes)
                key = ("pred",) + self.pred_sig(
                    data_shape, dtype, mask_v is None, 0, nodes)
                # operands resolved at lower time: under weight
                # residency the eval dispatches pass the frozen serve
                # tree, so the precompiled program must take the same
                # pytree (one calling convention per trainer)
                programs.append((key, lambda m=mask_v, nw=nodes:
                                 self._pred_step.lower(
                                     *self._pred_operands(),
                                     data_s, m, (),
                                     nodes_wanted=nw)))

        if n_steps > 0:
            # run_steps is the bench/test_skipread mode: its mask
            # variant is known up front (None single-process, the
            # materialized mask under multi-process dp), so exactly ONE
            # program compiles — no wasted minutes on the other variant
            mask_rs = None if jax.process_count() == 1 \
                else mask_variants[0]
            ns = int(n_steps)
            hyper_k_s = sds((ns, len(self._hyper_index), 3),
                            np.float32)
            epoch_k_s = sds((ns,), np.uint32)
            do_up_k_s = sds((ns,), np.bool_)
            key = ("run_steps",) + _areg.run_steps_sig(
                data_shape, dtype, label_shape, mask_rs is None, 0, ns)
            programs.append((key, lambda m=mask_rs, hs=hyper_k_s,
                             es=epoch_k_s, us=do_up_k_s:
                             self._multi_step.lower(
                                 self.params, self.opt_state,
                                 self.net_state, self.grad_acc,
                                 data_s, labels_s, m, (), hs, es,
                                 us, step_s, self._base_key)))

        compiled = self._compile_programs(programs, "precompile_failed")
        self.precompile_wall_s = time.perf_counter() - t_start
        self.precompile_programs = compiled
        if self._mon_on():
            self._mon.emit("precompile",
                           wall_ms=self.precompile_wall_s * 1e3,
                           programs=compiled)
        return compiled

    def _compile_programs(self, programs, warn_code: str) -> int:
        """AOT-compile ``(key, lower-thunk)`` pairs into the program
        registry, skipping keys already present (precompiled earlier,
        or installed from a sealed artifact bundle). The registry's
        ``compile`` is the one loop behind ``precompile`` and
        ``precompile_pred`` — failure fallback, signature seeding and
        per-program telemetry cannot drift between the training and
        serving warmup paths."""
        return self.programs.compile(
            programs, warn_code,
            monitor=self._mon if self._mon_on() else None)

    def precompile_pred(self, batch_sizes: Sequence[int],
                        nodes_wanted: Optional[Sequence[int]] = None,
                        dtype=None, donate: bool = False) -> int:
        """AOT-compile the eval/pred forward at a set of batch-size
        buckets — the serve-engine warmup path (doc/serving.md).

        One executable per reachable (bucket, mask-variant): the
        exactly-full variant (mask None — the mask-free specialization
        every perfectly filled micro-batch dispatches) always, plus
        the padded variant (rows rounded up to the bucket ride a zero
        mask tail, the ``num_batch_padd`` machinery) for buckets a
        partial batch can actually land in — the smallest row count
        rounding up to bucket ``b`` is ``prev_bucket + 1``, so when
        that equals ``b`` the masked program is dead and is skipped.
        After this returns, a dispatch at any compiled bucket goes
        straight to its executable — steady-state serving records zero
        XLA compile events.

        ``nodes_wanted`` are node indices (default: the top node, the
        ``predict`` output); compile one call per distinct node set you
        will serve. Failures fall back to the jit path with a one-time
        warning — warmup must never take a server down. Returns the
        number of programs compiled."""
        assert self._initialized, "call init_model/load_model first"
        from ..io.data import inst_array_shape
        t_start = time.perf_counter()
        self._enable_persistent_cache()
        nodes = (self.graph.num_nodes - 1,) if nodes_wanted is None \
            else tuple(nodes_wanted)
        dt = np.dtype(np.float32 if dtype is None else dtype)
        inst = inst_array_shape(tuple(self.graph.input_shape))
        from ..serve.bucketing import reachable_variants
        # one resolve up front: freezes the serve weight tree (weight
        # residency on) so every bucket executable below is lowered
        # against the SAME shared device tree — and a
        # serve_device_mem_budget breach rejects here, at warmup, with
        # the typed error instead of an OOM mid-request
        params_t, state_t = self._pred_operands()
        pred_jit = self._pred_step_donate \
            if donate and self.serve_donate else self._pred_step
        programs = []
        data_structs = {}
        for n, rows in reachable_variants(batch_sizes):
            data_shape = (n,) + inst
            if n not in data_structs:
                data_structs[n] = jax.ShapeDtypeStruct(
                    data_shape, dt,
                    sharding=self._pin_layout(self._b_shard,
                                              len(data_shape)))
            mask_s = None if rows == n else jax.ShapeDtypeStruct(
                (n,), np.float32, sharding=self._b_shard)
            key = ("pred",) + self.pred_sig(
                data_shape, dt, mask_s is None, 0, nodes)
            programs.append((key, lambda ds=data_structs[n], m=mask_s,
                             pj=pred_jit:
                             pj.lower(params_t, state_t, ds,
                                      m, (), nodes_wanted=nodes)))
        compiled = self._compile_programs(programs,
                                          "precompile_pred_failed")
        if self._mon_on():
            self._mon.emit("precompile",
                           wall_ms=(time.perf_counter() - t_start) * 1e3,
                           programs=compiled)
        return compiled

    # -- hyper-params per step ------------------------------------------

    def _hyper(self, epoch: Optional[int] = None) -> np.ndarray:
        """Packed (n_updaters, 3) array: lr, momentum, wd. The epoch is
        NOT packed here — a float32 slot rounds integers past 2^24, so
        it rides separately as an exact uint32 (see _epoch_u32)."""
        if epoch is None:
            epoch = self.update_counter
        arr = np.zeros((len(self._hyper_index), 3), np.float32)
        for i, (lk, tag) in enumerate(self._hyper_index):
            upd = self.updaters[lk][tag]
            upd.param.schedule_epoch(epoch)
            arr[i] = (upd.param.learning_rate, upd.param.momentum,
                      upd.param.wd)
        return arr

    def _epoch_u32(self, epoch: Optional[int] = None) -> np.uint32:
        """Exact device-side epoch (applied-update counter) for Adam's
        bias correction — uint32, the same fix pattern as the RNG
        ``step`` scalar."""
        if epoch is None:
            epoch = self.update_counter
        return np.uint32(epoch)

    def _step_scalar(self) -> np.uint32:
        """Global sample-step counter for RNG folding (exact uint32; a
        float32 slot loses integer precision past 2^24)."""
        return np.uint32(self.update_counter * self.update_period
                         + self.sample_counter)

    # -- batch plumbing --------------------------------------------------

    def _local_batch_size(self, batch: DataBatch) -> int:
        """Rows this process contributes. For an already-global array
        (placed by the prefetch transform) that is 1/world_size of its
        leading dim; for host arrays it is the array's own size."""
        n = batch.batch_size
        if (jax.process_count() > 1 and isinstance(batch.data, jax.Array)
                and batch.data.sharding == self._b_shard):
            n //= jax.process_count()
        return n

    def _mask(self, batch: DataBatch):
        """Row-validity mask, or None when every row is real — the
        None specialization lets BN stats and the loss skip the
        broadcast-mask multiplies on full-size activations (the
        no-padding case is every steady-state batch; only epoch-tail
        batches compile the masked variant).

        Multi-process dp always materializes the mask: the None/array
        choice selects between two compiled programs, and per-RANK
        padding can differ on the epoch tail — ranks dispatching
        structurally different SPMD programs would deadlock the
        gradient collectives."""
        if not batch.num_batch_padd and jax.process_count() == 1:
            return None
        n = self._local_batch_size(batch)
        m = np.ones((n,), np.float32)
        if batch.num_batch_padd:
            m[n - batch.num_batch_padd:] = 0.0
        return m

    def _label_fields(self, label: np.ndarray, nvalid: int):
        return {name: label[:nvalid, a:b]
                for name, a, b in self._label_slices}

    def _host_label(self, batch: DataBatch) -> np.ndarray:
        """This process's label rows as float32 numpy (device labels
        placed by the prefetch transform come back via local shards)."""
        if isinstance(batch.label, jax.Array):
            return self._local_rows(batch.label).astype(np.float32)
        return np.asarray(batch.label, np.float32)  # cxxlint: disable=CXL003 -- host ring-buffer labels; no device value involved

    def _ship(self, arr: np.ndarray, sharding) -> jnp.ndarray:
        """Cast-and-transfer policy shared by per-batch and K-window
        placement: u8 pixels ship raw (1/4 bytes, device casts), all
        else float32; under multi-process dp each rank contributes its
        local shard of the global batch (config batch_size is GLOBAL,
        split across ranks like the reference splits across PS
        workers). bf16 rows also ship raw — a bf16-warmed serve ladder
        staging through here must not silently up-cast (and recompile)
        on the H2D path."""
        if arr.dtype != np.uint8 and arr.dtype != _BF16:
            arr = np.asarray(arr, np.float32)  # cxxlint: disable=CXL003 -- host-side cast before the H2D ship; input is host numpy
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(sharding, arr)
        # spatial batches take the row-major layout pin (channels on
        # the minor/lane dim) when input_layout=rowmajor is active
        return jax.device_put(arr, self._pin_layout(sharding, arr.ndim))

    def _put_batch_array(self, x) -> jnp.ndarray:
        if isinstance(x, jax.Array) and x.sharding == self._b_shard:
            return x                      # already resident (test_skipread)
        return self._ship(np.asarray(x), self._b_shard)  # cxxlint: disable=CXL003 -- host staging of the input batch (jax.Array case returned above)

    def _put_mask(self, batch: DataBatch):
        m = self._mask(batch)
        return None if m is None else self._put_batch_array(m)

    def _device_batch(self, batch: DataBatch):
        data = self._put_batch_array(batch.data)
        labels = self._put_batch_array(batch.label)
        return (data, labels, self._put_mask(batch),
                self._device_extra(batch))

    def device_put_batch(self, batch: DataBatch) -> DataBatch:
        """Move a batch's arrays to the device with the batch sharding.
        Hand this to PrefetchIterator.set_transform so the transfer
        happens in the prefetch thread, overlapped with compute."""
        return DataBatch(
            data=self._put_batch_array(batch.data),
            label=self._put_batch_array(batch.label),
            # copy: the source may be a ring buffer that is released
            # (and refilled) once the device arrays are ready, while
            # this staged batch lives on until consumed
            inst_index=None if batch.inst_index is None
            else np.array(batch.inst_index),
            num_batch_padd=batch.num_batch_padd,
            extra_data=[self._put_batch_array(e)
                        for e in batch.extra_data])

    def _device_extra(self, batch: DataBatch):
        return tuple(self._put_batch_array(e) for e in batch.extra_data)

    def _put_window(self, arrs) -> jnp.ndarray:
        """Place a K-batch window as ONE (K, batch, ...) array sharded
        (None, 'data'). Host arrays stack host-side and ship in a
        single transfer (K separate device_puts cost K dispatch round
        trips); device-resident arrays (prefetch-transform batches,
        test_skipread) stack device-side."""
        if any(isinstance(a, jax.Array) for a in arrs):
            return self._stack_k(*[self._put_batch_array(a)
                                   for a in arrs])
        return self._ship(np.stack([np.asarray(a) for a in arrs]),  # cxxlint: disable=CXL003 -- host-side window stack; device arrays took the _stack_k branch above
                          self._kb_shard)

    def _local_rows(self, arr, flatten: bool = True,
                    axis: int = 0) -> np.ndarray:
        """Fetch this process's rows of a batch-sharded output.

        Single-process: the whole array. Multi-process dp: concatenate
        the addressable shards in global row order along the batch
        ``axis`` (0 for per-batch outputs, 1 for K-window outputs whose
        leading axis is the scan step), which is exactly the order of
        this rank's local input rows (make_array_from_process_local_data
        splits the local batch over local devices in ascending mesh
        position). Shards are deduped by row range: with a model axis
        >1, batch-sharded outputs are replicated across 'model', so
        each row slice appears once per model-axis device. ``flatten``
        collapses the trailing dims to the as_mat 2-D view."""
        if jax.process_count() == 1:
            out = np.asarray(arr)  # cxxlint: disable=CXL003 -- intentional D2H: _local_rows exists to fetch rows for host metrics/output
        else:
            uniq = {}
            for s in arr.addressable_shards:
                uniq.setdefault(s.index[axis].start or 0, s)
            out = np.concatenate(
                [np.asarray(uniq[k].data) for k in sorted(uniq)],  # cxxlint: disable=CXL003 -- intentional D2H of local shards (see above)
                axis=axis)
        if not flatten:
            return out
        lead = out.shape[:axis + 1]
        return out.reshape(lead + (-1,))

    # -- observability ---------------------------------------------------

    def set_monitor(self, mon) -> None:
        """Attach a monitor (cxxnet_tpu.monitor.Monitor). With an
        enabled sink, each dispatch is timed wall-clock INCLUDING a
        block on the loss scalar — an honest device-step time at the
        cost of losing dispatch/compute overlap (the observer effect;
        documented in doc/observability.md). A None/disabled monitor
        leaves the step path untouched."""
        self._mon = mon
        if self._initialized:
            self._emit_model_records()

    def _emit_model_records(self) -> None:
        """Static per-model telemetry: analytic FLOPs (the MFU
        denominator) and the layout/fusion pass decisions — schema-
        validated so BENCH records and monitor streams carry the same
        machine-readable perf context."""
        if not self._mon_on():
            return
        net = self.net
        n_params = sum(int(np.prod(w.shape))
                       for pt in self.params.values()
                       for w in pt.values())
        fwd = net.analytic_flops_per_example()
        self._mon.emit("model_info",
                       flops_per_example=fwd,
                       train_flops_per_example=3.0 * fwd,
                       params=n_params,
                       layers=len(net.graph.layers))
        self._mon.emit("layout",
                       input_layout=self.input_layout,
                       bn_fuse_relu=len(net._identity_layers),
                       bn_fold_eval_pairs=len(net._fold_pairs),
                       pool_concat_fused=len(net._pool_concat),
                       **net.layout_summary)
        if self.quant_report.get("active"):
            r = self.quant_report
            self._mon.emit("quantized_model", dtype=r["dtype"],
                           layers=r["layers"],
                           fallback_layers=r["fallback_layers"],
                           native=r["native"])

    def _mon_on(self) -> bool:
        return self._mon is not None and self._mon.enabled

    def note_data_wait(self, seconds: float) -> None:
        """The drive loop reports time it spent blocked on the data
        iterator since the last dispatch; the next step record carries
        it as data_wait_ms (the data-wait vs device-step split)."""
        self._pending_data_wait += seconds

    def _note_signature(self, kind: str, sig: tuple,
                        wall: float) -> bool:
        """First sighting of a dispatch signature means this wall time
        included an XLA compile (first-step) or recompile (a shape /
        static-arg change). Returns True when so, and emits the
        compile record."""
        key = (kind,) + sig
        if key in self._seen_sigs:
            return False
        first = not self._seen_sigs
        self._seen_sigs.add(key)
        self._mon.emit("compile",
                       kind="first" if first else "recompile",
                       wall_ms=wall * 1e3, signature=repr(key))
        return True

    def _emit_step(self, kind: str, n_batches: int, examples: int,
                   wall: float, sig: tuple, lr: float) -> None:
        compiled = self._note_signature(kind, sig, wall)
        wait, self._pending_data_wait = self._pending_data_wait, 0.0
        self._mon.emit(
            "step", step=self._steps_total, round=self.round,
            dispatch=kind, n_batches=n_batches, examples=examples,
            wall_ms=wall * 1e3, data_wait_ms=wait * 1e3,
            examples_per_sec=examples / wall if wall > 0 else 0.0,
            update_counter=self.update_counter, lr=lr,
            compile=compiled)

    def end_round(self) -> None:
        """Close the current round's counter window (idempotent):
        computes last_round_examples_per_sec for the wrapper poll
        surface and the round_end record."""
        if self._round_t0 is None:
            return
        dt = time.perf_counter() - self._round_t0
        if dt > 0:
            self.last_round_examples_per_sec = self._round_examples / dt
        self.last_round_examples = self._round_examples
        self.last_round_wall_s = dt
        self._round_t0 = None

    def counters_snapshot(self) -> Dict[str, float]:
        """Cheap progress snapshot (no device sync): total dispatches,
        total real examples consumed, and the throughput of the last
        completed round — the wrapper/C-ABI polling surface."""
        return {"steps": self._steps_total,
                "examples": self._examples_total,
                "last_round_examples_per_sec":
                    self.last_round_examples_per_sec}

    def _count_examples(self, examples: int) -> None:
        """One dispatch = one step id, however many batches it fused;
        ``examples`` counts the real (non-padded) LOCAL rows consumed
        (per-process under multi-process dp — run_start carries
        process_count for consumers that want global throughput)."""
        self._steps_total += 1
        self._examples_total += examples
        self._round_examples += examples

    # -- public API ------------------------------------------------------

    def start_round(self, r: int) -> None:
        self.end_round()                 # close the previous window
        self.round = r
        self._round_t0 = time.perf_counter()
        self._round_examples = 0

    def update(self, batch: DataBatch) -> None:
        assert self._initialized, "call init_model/load_model first"
        t0 = time.perf_counter() if self._mon_on() else 0.0
        data, labels, mask, extra = self._device_batch(batch)
        hyper = self._hyper()
        # step BEFORE the counter bump: batch i of the run folds RNG
        # with step U*period+S (0-based), the same index scan_step uses
        # as step0+i — so dropout/insanity masks are identical whether
        # batches go through update(), update_many, or run_steps
        step = self._step_scalar()
        self.sample_counter += 1
        do_update = self.sample_counter >= self.update_period
        sig = _areg.update_sig(data.shape, data.dtype, labels.shape,
                               mask is None, len(extra),
                               bool(do_update))
        out = self._call_step(
            "update", sig, self._train_step,
            (self.params, self.opt_state, self.net_state, self.grad_acc,
             data, labels, mask, extra, hyper, self._epoch_u32(), step,
             self._base_key),
            do_update=bool(do_update))
        (self.params, self.opt_state, self.net_state,
         self.grad_acc, loss, preds) = out
        self.programs.residency = None   # weights moved: the frozen
        #                                  serve tree is stale
        self._last_loss = loss
        ex = self._local_batch_size(batch) - batch.num_batch_padd
        self._count_examples(ex)
        if self._mon_on():
            jax.block_until_ready(loss)  # cxxlint: disable=CXL003 -- monitor-gated: wall_ms must cover device compute; unmonitored runs never sync
            wall = time.perf_counter() - t0
            self._emit_step("update", 1, ex, wall, sig,
                            float(hyper[0, 0]) if len(hyper) else 0.0)
        if do_update:
            self.sample_counter = 0
            self.update_counter += 1
        if self.eval_train and self._metrics.evals:
            nvalid = self._local_batch_size(batch) - batch.num_batch_padd
            pred_np = [self._local_rows(p)[:nvalid] for p in preds]
            self._train_metrics.add_eval(
                pred_np, self._label_fields(self._host_label(batch),
                                            nvalid))

    def run_steps(self, batch: DataBatch, n_steps: int) -> None:
        """Run n_steps train steps on one resident batch in a single
        dispatch (steady-state throughput measurement — the
        test_skipread mode, iter_batch_proc-inl.hpp:21). The LR/momentum
        schedule advances per step in-scan via a per-step hyper array
        (reference applies ScheduleEpoch every update, updater/param.h:
        96-117), and ``update_period > 1`` accumulation windows close
        in-scan via traced apply flags — the reference's canonical
        update_period=2 configs benchmark in this fused mode, equality-
        tested against the per-batch dispatch path."""
        assert self._initialized, "call init_model/load_model first"
        t0 = time.perf_counter() if self._mon_on() else 0.0
        data, labels, mask, extra = self._device_batch(batch)
        n = int(n_steps)
        period = self.update_period
        S, U = self.sample_counter, self.update_counter
        epochs = [U + (S + i) // period for i in range(n)]
        hyper_k = np.stack([self._hyper(e) for e in epochs])
        epoch_k = np.asarray(epochs, np.uint32)  # cxxlint: disable=CXL003 -- host python list of schedule epochs
        do_up_k = np.asarray([((S + i + 1) % period) == 0  # cxxlint: disable=CXL003 -- host python list of apply flags
                              for i in range(n)])
        sig = _areg.run_steps_sig(data.shape, data.dtype, labels.shape,
                                  mask is None, len(extra), n)
        out = self._call_step(
            "run_steps", sig, self._multi_step,
            (self.params, self.opt_state, self.net_state, self.grad_acc,
             data, labels, mask, extra, hyper_k, epoch_k, do_up_k,
             self._step_scalar(), self._base_key))
        (self.params, self.opt_state, self.net_state, self.grad_acc,
         loss) = out
        self.programs.residency = None
        self._last_loss = loss
        ex = (self._local_batch_size(batch) - batch.num_batch_padd) * n
        self._count_examples(ex)
        if self._mon_on():
            jax.block_until_ready(loss)  # cxxlint: disable=CXL003 -- monitor-gated: wall_ms must cover device compute; unmonitored runs never sync
            wall = time.perf_counter() - t0
            self._emit_step("run_steps", n, ex, wall, sig,
                            float(hyper_k[0, 0, 0]) if hyper_k.size
                            else 0.0)
        self.update_counter = U + (S + n) // period
        self.sample_counter = (S + n) % period

    def update_many(self, batches: Sequence[DataBatch]) -> None:
        """Train on K real batches in ONE jitted dispatch: host dispatch
        latency amortizes across the window while the schedule stays
        per-update correct (hyper rows advance in-scan) and
        update_period accumulation windows close in-scan (traced apply
        flags). Observable semantics are identical to K ``update()``
        calls — proven by an equality test across an LR-schedule
        boundary.

        The throughput intent of the reference's threadbuffer overlap
        (iter_batch_proc-inl.hpp:132-220) at the per-batch ScheduleEpoch
        semantics of updater/param.h:96-117."""
        assert self._initialized, "call init_model/load_model first"
        K = len(batches)
        if K == 1:
            return self.update(batches[0])
        t0 = time.perf_counter() if self._mon_on() else 0.0
        period = self.update_period
        S, U = self.sample_counter, self.update_counter
        epochs = [U + (S + i) // period for i in range(K)]
        hyper_k = np.stack([self._hyper(e) for e in epochs])
        epoch_k = np.asarray(epochs, np.uint32)  # cxxlint: disable=CXL003 -- host python list of schedule epochs
        do_up = np.asarray([((S + i + 1) % period) == 0  # cxxlint: disable=CXL003 -- host python list of apply flags
                            for i in range(K)])
        step0 = self._step_scalar()
        data_k = self._put_window([b.data for b in batches])
        labels_k = self._put_window([b.label for b in batches])
        masks = [self._mask(b) for b in batches]
        if all(m is None for m in masks):
            mask_k = None
        else:       # mixed window: materialize ones for unpadded rows
            mask_k = self._put_window(
                [np.ones((self._local_batch_size(b),), np.float32)
                 if m is None else m
                 for m, b in zip(masks, batches)])
        n_extra = len(batches[0].extra_data)
        extra_k = tuple(
            self._put_window([b.extra_data[j] for b in batches])
            for j in range(n_extra))
        collect = bool(self.eval_train and self._metrics.evals)
        sig = _areg.update_many_sig(data_k.shape, data_k.dtype,
                                    labels_k.shape, mask_k is None,
                                    n_extra, K, collect)
        out = self._call_step(
            "update_many", sig, self._many_step,
            (self.params, self.opt_state, self.net_state, self.grad_acc,
             data_k, labels_k, mask_k, extra_k, hyper_k, epoch_k, do_up,
             step0, self._base_key),
            collect=collect)
        (self.params, self.opt_state, self.net_state, self.grad_acc,
         loss, preds_k) = out
        self.programs.residency = None
        self._last_loss = loss
        ex = sum(self._local_batch_size(b) - b.num_batch_padd
                 for b in batches)
        self._count_examples(ex)
        if self._mon_on():
            jax.block_until_ready(loss)  # cxxlint: disable=CXL003 -- monitor-gated: wall_ms must cover device compute; unmonitored runs never sync
            wall = time.perf_counter() - t0
            self._emit_step("update_many", K, ex, wall, sig,
                            float(hyper_k[0, 0, 0]) if hyper_k.size
                            else 0.0)
        self.update_counter = U + (S + K) // period
        self.sample_counter = (S + K) % period
        if collect:
            preds_np = [self._local_rows(p, axis=1) for p in preds_k]
            for i, b in enumerate(batches):
                nvalid = self._local_batch_size(b) - b.num_batch_padd
                self._train_metrics.add_eval(
                    [p[i][:nvalid] for p in preds_np],
                    self._label_fields(self._host_label(b), nvalid))

    def train_metric_str(self, name: str = "train") -> str:
        res = self._train_metrics.results()
        self._train_metrics.clear()
        if self._mon_on() and res:
            self._mon.emit("eval", round=self.round, name=name,
                           metrics={t: float(v) for t, v in res})
        return MetricSet.format_line(name, res)

    def evaluate(self, data_iter, name: str) -> str:
        """Run a full eval pass; returns '\\t<name>-<metric>:<value>'."""
        return self.evaluate_metrics(data_iter, name)[0]

    def evaluate_metrics(self, data_iter, name: str
                         ) -> Tuple[str, Dict[str, float]]:
        """One eval pass returning BOTH the parity line and the
        ``{tag: value}`` dict — one reduction per metric serves the
        line, the structured ``eval`` record, and machine consumers
        (the continual loop's eval gate reads the dict; re-running
        ``results()`` would double the collective count under
        multi-process runs)."""
        if not self._metrics.evals:
            return "", {}
        self._metrics.clear()
        nodes_wanted = tuple(self._metric_nodes)
        from ..parallel import synced_batches
        # same lockstep window as the CLI train loop (dispatch_period),
        # not a private constant — multi-process ranks must agree on it
        for batch in synced_batches(data_iter,
                                    window=self.dispatch_period):
            # same input path as training: uint8 pixels ship raw (1/4
            # the H2D bytes) and pre-placed prefetch batches pass
            # through (reference evaluates through the training pipeline,
            # nnet_impl-inl.hpp:241-276)
            vals = self._call_pred(self._put_batch_array(batch.data),
                                   self._put_mask(batch),
                                   self._device_extra(batch),
                                   nodes_wanted)
            nvalid = self._local_batch_size(batch) - batch.num_batch_padd
            pred_np = [self._local_rows(v)[:nvalid] for v in vals]
            self._metrics.add_eval(
                pred_np, self._label_fields(self._host_label(batch),
                                            nvalid))
        res = self._metrics.results()
        vals = {t: float(v) for t, v in res}
        if self._mon_on() and res:
            # structured record beside the parity line; ONE reduction
            # per metric serves both (results() is collective under
            # multi-process runs)
            self._mon.emit("eval", round=self.round, name=name,
                           metrics=vals)
        return MetricSet.format_line(name, res), vals

    @staticmethod
    def rows_to_prediction(m: np.ndarray) -> np.ndarray:
        """Output rows -> per-row prediction: the single raw column, or
        the argmax class as float32 (nnet_impl-inl.hpp:317-330). The
        one definition of the predict convention — the serve engine and
        ``predict`` below must agree row for row."""
        m = m.reshape(m.shape[0], -1)
        if m.shape[1] == 1:
            return m[:, 0]
        return np.argmax(m, axis=1).astype(np.float32)

    def predict(self, batch: DataBatch) -> np.ndarray:
        """argmax class (or raw scalar) per row of the top node
        (nnet_impl-inl.hpp:317-330)."""
        top = self.graph.num_nodes - 1
        (val,) = self._call_pred(self._put_batch_array(batch.data),
                                 self._put_mask(batch),
                                 self._device_extra(batch), (top,))
        nvalid = self._local_batch_size(batch) - batch.num_batch_padd
        return self.rows_to_prediction(self._local_rows(val)[:nvalid])

    def extract_feature(self, batch: DataBatch, node: str) -> np.ndarray:
        ni = self.net.node_index_by_name(node)
        (val,) = self._call_pred(self._put_batch_array(batch.data),
                                 self._put_mask(batch),
                                 self._device_extra(batch), (ni,))
        nvalid = self._local_batch_size(batch) - batch.num_batch_padd
        return self._local_rows(val, flatten=False)[:nvalid]

    def check_weight_consistency(self, atol: float = 0.0) -> None:
        """Assert every device replica holds identical weights — the
        ``test_on_server=1`` audit (reference CheckWeight_,
        async_updater-inl.hpp:149-154). With SPMD + pinned replicated
        out-shardings this should hold bitwise; a mismatch means a
        sharding or donation bug. Partially-sharded weights (e.g.
        model-axis fullc) are compared within each replica group;
        identical NaNs count as equal (a numerical blow-up is not a
        replication bug). Under multi-process dp, fully-replicated
        weights are also cross-checked between ranks."""
        from collections import defaultdict

        def _differs(a, b):
            return not np.allclose(a, b, rtol=0.0, atol=atol,
                                   equal_nan=True)

        for lk, pt in self.params.items():
            for tag, w in pt.items():
                if not isinstance(w, jax.Array):
                    continue
                groups = defaultdict(list)
                for s in w.addressable_shards:
                    # slices are unhashable before py3.12; key on their
                    # fields
                    key = tuple((sl.start, sl.stop, sl.step)
                                for sl in s.index)
                    groups[key].append(s)
                for shards in groups.values():
                    ref = np.asarray(shards[0].data)
                    for s in shards[1:]:
                        if _differs(ref, np.asarray(s.data)):
                            raise AssertionError(
                                "weight %s:%s diverged between device "
                                "replicas %s and %s"
                                % (lk, tag, shards[0].device, s.device))
                if jax.process_count() > 1 and len(groups) == 1:
                    # fully replicated: audit across ranks too
                    from jax.experimental import multihost_utils
                    ref = np.asarray(w.addressable_shards[0].data)
                    allv = np.asarray(
                        multihost_utils.process_allgather(ref))
                    for r in range(allv.shape[0]):
                        if _differs(ref, allv[r]):
                            raise AssertionError(
                                "weight %s:%s diverged between process "
                                "ranks (rank %d vs %d)"
                                % (lk, tag, jax.process_index(), r))

    # -- weights ---------------------------------------------------------

    def get_weight(self, layer_name: str, tag: str) -> np.ndarray:
        """Weight in reference convention: fullc (out,in); conv
        (out_ch, in_pg*kh*kw); vectors 1-D (visitor.h:26-165)."""
        w = np.asarray(self.params[layer_name][tag])
        return self._to_ref_layout(w)

    def set_weight(self, layer_name: str, tag: str,
                   value: np.ndarray) -> None:
        cur = self.params[layer_name][tag]
        new = self._from_ref_layout(np.asarray(value, np.float32),
                                    cur.shape)
        p = dict(self.params)
        lp = dict(p[layer_name])
        lp[tag] = jax.device_put(new, self._repl) if cur.ndim == 1 \
            else jax.device_put(new,
                                self._p_shard[layer_name][tag])
        p[layer_name] = lp
        self.params = p
        self.programs.residency = None   # frozen serve tree is stale

    @staticmethod
    def _to_ref_layout(w: np.ndarray) -> np.ndarray:
        if w.ndim == 2:                      # fullc (in,out) -> (out,in)
            return w.T.copy()
        if w.ndim == 4:                      # HWIO -> (out, in*kh*kw)
            kh, kw, ipg, out = w.shape
            return w.transpose(3, 2, 0, 1).reshape(out, ipg * kh * kw)
        return w.copy()

    @staticmethod
    def _from_ref_layout(w: np.ndarray,
                         target_shape: Tuple[int, ...]) -> np.ndarray:
        if len(target_shape) == 2:
            return np.ascontiguousarray(w.T)
        if len(target_shape) == 4:
            kh, kw, ipg, out = target_shape
            return np.ascontiguousarray(
                w.reshape(out, ipg, kh, kw).transpose(2, 3, 1, 0))
        return w.reshape(target_shape)

    # -- checkpoint ------------------------------------------------------

    def gather_snapshot(self) -> Tuple[Dict[str, np.ndarray], Dict]:
        """Device->host gather of everything a snapshot holds, plus its
        metadata — the only checkpoint phase that must run on the
        training thread at an update boundary. Serialization and the
        atomic commit live in :mod:`.checkpoint` and can run on a
        background writer (CheckpointManager). Multi-process: the
        optimizer-state gathers are collective — call on ALL ranks."""
        arrays: Dict[str, np.ndarray] = {}
        for lk, pt in self.params.items():
            for tag, w in pt.items():
                arrays["param/%s/%s" % (lk, tag)] = np.asarray(w)
        for lk, st in self.net_state.items():
            for k, v in st.items():
                arrays["state/%s/%s" % (lk, k)] = np.asarray(v)
        if self.save_optimizer:
            # seamless-resume extension (the reference never checkpoints
            # momentum, nnet_impl-inl.hpp:98-116; off by default for
            # snapshot-format parity)
            def fetch(v):
                # ZeRO-1 leaves span processes under multi-host dp;
                # gather the global value before saving
                if isinstance(v, jax.Array) and \
                        not v.is_fully_addressable:
                    from jax.experimental import multihost_utils
                    v = multihost_utils.process_allgather(v, tiled=True)
                a = np.asarray(v)
                # npz can't represent bfloat16 (stored as opaque V2 and
                # unreadable on load); momentum_dtype=bfloat16 buffers
                # ship as f32 (exact) and load_model casts back per the
                # resuming config
                return a.astype(np.float32) if a.dtype == jnp.bfloat16 \
                    else a
            for lk, tags in self.opt_state.items():
                for tag, st in tags.items():
                    for k, v in st.items():
                        arrays["opt/%s/%s/%s" % (lk, tag, k)] = fetch(v)
        # calibration range tables ride as ordinary arrays so the
        # content digest covers them (a quantized snapshot is a
        # first-class verified artifact; nnet/quantize.py)
        for lkey, tab in self.quant_tables.items():
            for field, v in tab.items():
                arrays["quant/%s/%s" % (lkey, field)] = np.asarray(v)
        meta = {
            "update_counter": self.update_counter,
            "structure": self.graph.to_dict(),
            "cfg": self.cfg,
            # the topology this run trained under, sealed beside the
            # weights: resume compares it against the runtime so a
            # silently different mesh / world size cannot slip past
            # (dist_topology_check, doc/distributed.md) and the
            # elastic handoff can re-derive the reader shard map from
            # update_counter at the new world size
            "topology": self._topology_meta(),
        }
        if self.quant_meta:
            meta["quantized"] = dict(self.quant_meta)
        return arrays, meta

    def _topology_meta(self) -> Dict[str, Any]:
        """The topology dict sealed into snapshot meta: input topology
        (hosts/local devices, faked under the dryrun), mesh axis
        sizes, and the global batch the shard map partitions."""
        from ..parallel import current_topology
        topo = current_topology().describe()
        topo["mesh"] = {str(k): int(v)
                        for k, v in dict(self.mesh.shape).items()} \
            if self.mesh is not None else None
        topo["global_batch"] = int(self.batch_size)
        return topo

    def _check_loaded_topology(self, meta: Dict[str, Any],
                               path: str) -> None:
        """Compare a snapshot's sealed topology against this runtime
        (dist_topology_check): a changed mesh or world size is the
        elastic-resume path when intentional and a data-duplication /
        deadlock hazard when not — so it is never silent. ``warn``
        (default) warns once and lets the resume machinery re-derive
        the shard map; ``strict`` refuses the load."""
        saved = meta.get("topology")
        self.resumed_topology = saved
        self.topology_changed = False
        if not saved or self.dist_topology_check == "off":
            return
        cur = self._topology_meta()
        # a single-host mesh resize (train on 8 devices, serve on 1)
        # is routine and stays silent; mesh/local-device drift only
        # matters once hosts are (or were) in play — the world-size
        # axis itself is always compared
        keys = ("hosts",) if saved.get("hosts", 1) <= 1 \
            and cur.get("hosts", 1) <= 1 else \
            ("hosts", "local_devices", "mesh")
        diffs = [k for k in keys if saved.get(k) != cur.get(k)]
        if not diffs:
            return
        self.topology_changed = True
        desc = ", ".join("%s %r -> %r" % (k, saved.get(k), cur.get(k))
                         for k in diffs)
        if self.dist_topology_check == "strict":
            raise ValueError(
                "snapshot %s was written under a different topology "
                "(%s) and dist_topology_check=strict refuses the "
                "silent change; resume with dist_topology_check=warn "
                "to accept the elastic handoff" % (path, desc))
        from ..monitor import warn_once
        warn_once("dist_topology_changed",
                  "snapshot %s was written under a different topology "
                  "(%s); the reader shard map re-derives from the "
                  "resumed update counter at the new world size "
                  "(doc/distributed.md)" % (path, desc))

    def save_model(self, path: str) -> None:
        """Synchronous verified snapshot: gather, then atomically
        commit with a content digest (checkpoint.write_snapshot). The
        direct API raises on write failure; the train loop's managed
        path (CheckpointManager) downgrades failures to warnings."""
        from .checkpoint import write_snapshot
        arrays, meta = self.gather_snapshot()
        # multi-process: every rank participates in the gathers above
        # (call save_model on ALL ranks); only root touches the file
        if jax.process_index() != 0:
            return
        write_snapshot(path, arrays, meta)

    def load_model(self, path: str) -> None:
        # verified read: digest + format_version checked before any
        # array is trusted (checkpoint.read_snapshot). A sealed
        # artifact bundle (doc/artifacts.md) loads as its inner
        # snapshot, then installs its serialized executables once the
        # programs are rebuilt (_attach_bundle at the end).
        from .checkpoint import read_snapshot
        bundle = None
        from ..artifact import bundle as _ab
        if _ab.is_bundle(path):
            bundle = _ab.load_bundle(path)
            path = bundle.snapshot_uri
        # raw bytes ride from the bundle's verification pass so the
        # snapshot is read once; the content digest still re-verifies
        blob, meta = read_snapshot(
            path, raw=bundle.snapshot_raw if bundle else None)
        saved_graph = NetGraph.from_dict(meta["structure"])
        self._absorb_globals()
        # re-parse config against saved structure (Configure equality
        # check, nnet_config.h:263-267)
        self.graph = saved_graph
        self.graph.configure(self.cfg)
        if self.batch_size == 0:
            self.batch_size = self.graph.batch_size
        self.net = FuncNet(self.graph, self.batch_size)
        params, net_state = self.net.init(
            jax.random.PRNGKey(self.seed))
        for lk, pt in params.items():
            for tag in pt:
                k = "param/%s/%s" % (lk, tag)
                if k in blob:
                    pt[tag] = jnp.asarray(blob[k])
        for lk, st in net_state.items():
            for kk in st:
                k = "state/%s/%s" % (lk, kk)
                if k in blob:
                    st[kk] = jnp.asarray(blob[k])
        self.params, self.net_state = params, net_state
        self.update_counter = int(meta.get("update_counter", 0))
        # calibration ranges (task=quantize snapshots) load before
        # _post_init so serve_dtype activation sees them
        from .quantize import tables_from_blob
        self.quant_tables = tables_from_blob(blob)
        self.quant_meta = dict(meta.get("quantized", {}))
        self._post_init()
        # topology comparison AFTER _post_init: the check needs the
        # mesh this runtime actually built (dist_topology_check)
        self._check_loaded_topology(meta, path)
        # restore optimizer state when the snapshot carries it
        if any(k.startswith("opt/") for k in blob):
            for lk, tags in self.opt_state.items():
                for tag, st in tags.items():
                    new = dict(st)
                    for k in st:
                        key = "opt/%s/%s/%s" % (lk, tag, k)
                        if key in blob:
                            # cast to the dtype the CURRENT config
                            # initialized (snapshots store f32; the
                            # momentum_dtype of the resuming run wins)
                            new[k] = jnp.asarray(blob[key],
                                                 dtype=st[k].dtype)
                    self.opt_state[lk][tag] = new
            self.opt_state = jax.device_put(self.opt_state,
                                            self._o_shard)
        if bundle is not None:
            self._attach_bundle(bundle)

    def _attach_bundle(self, bundle) -> None:
        """Install a sealed bundle's serialized executables into the
        program registry — AFTER ``_post_init`` rebuilt the dispatch
        programs, so the installs land in the final registry. The
        fingerprint gate is exact dict equality: platform, jax/jaxlib
        versions, device kind+count, process count and mesh must all
        match what the bundle was sealed on, or every key falls back
        to re-lower+compile with one warning. Emits the honest
        ``artifact_load`` accounting (hits + rebuilds == programs)."""
        from ..artifact.bundle import runtime_fingerprint
        fp_ok = bundle.manifest.get("fingerprint") \
            == runtime_fingerprint(self.mesh)
        # the sealed executables' weight calling convention must match
        # this trainer's: a residency-sealed pred takes the frozen
        # serve tree as arguments, a legacy one the raw masters — a
        # mismatch would call an executable with the wrong pytree, so
        # it downgrades to the per-key re-lower fallback instead
        if int(bundle.manifest.get("weight_residency", 0)) \
                != int(bool(self.serve_weight_residency)):
            fp_ok = False
        rep = self.programs.install_serialized(
            bundle.programs, bundle.path, fp_ok, monitor=self._mon)
        if self._mon_on():
            self._mon.emit("artifact_load", **rep)

    @staticmethod
    def _read_source_blob(path: str):
        """Digest-verified (arrays, meta) of a finetune/reload source:
        a plain snapshot, or a sealed artifact bundle resolved to its
        inner snapshot (the bundle's member verification runs first,
        then the snapshot's own content digest — doc/artifacts.md)."""
        from ..artifact import bundle as _ab
        from .checkpoint import read_snapshot
        if _ab.is_bundle(path):
            b = _ab.load_bundle(path)
            return read_snapshot(b.snapshot_uri, raw=b.snapshot_raw)
        return read_snapshot(path)

    def finetune_from(self, path: str, remap: Sequence[str] = (),
                      strict: bool = True) -> Dict[str, Any]:
        """The ``task = finetune`` bootstrap (doc/tasks.md): carry
        weights over from a verified snapshot or sealed bundle into a
        freshly initialized net, remapping the layers named in
        ``remap`` (fresh init — the new-label-count output head) and
        digest-verifying everything carried (``read_snapshot`` refuses
        a source whose content digest fails).

        Call after ``init_model``. Carry-over is by layer *name* with
        exact shape equality (nnet_impl-inl.hpp:117-150); a layer whose
        saved shape no longer matches and is NOT in ``remap`` raises
        :class:`FinetuneShapeError` naming it (``strict=False``
        restores the reference's silent skip-and-reinit). Returns (and
        emits as the ``finetune`` record) the carry accounting."""
        assert self._initialized, "call init_model first"
        blob, meta = self._read_source_blob(path)
        remap_set = set(remap)
        unknown = remap_set - set(self.params.keys())
        if unknown:
            raise ValueError(
                "finetune_remap names unknown param layer(s) %s; "
                "known: %s" % (sorted(unknown), sorted(self.params)))
        carried = self._carry_from_blob(blob, remap_set, strict)
        fresh = sorted(remap_set)
        frozen = sorted(set(
            lk for lk, tags in self.updaters.items()
            for tag, upd in tags.items() if upd.param.lr_mult == 0.0))
        rec = {
            "source": path,
            "source_digest": str(meta.get("content_digest", "")),
            "carried": len(carried), "remapped": len(fresh),
            "fresh": sorted(set(self.params) - set(carried)
                            - remap_set),
            "carried_layers": carried, "remapped_layers": fresh,
            "frozen_groups": frozen,
        }
        if self.silent == 0:
            print("finetune_from %s: carried %s; remapped %s%s"
                  % (path, ", ".join(carried) or "<none>",
                     ", ".join(fresh) or "<none>",
                     ("; frozen %s" % ", ".join(frozen)) if frozen
                     else ""))
        if self._mon_on():
            self._mon.emit("finetune", **rec)
        return rec

    def _carry_from_blob(self, blob, remap_set, strict: bool):
        """The ONE name+shape carry loop behind ``finetune_from`` and
        ``copy_model_from`` (params + net_state, ``_put_all``,
        residency invalidation) — a fix to the carry semantics cannot
        silently miss one of them. Returns the carried layer keys."""
        carried = []
        for lk, pt in self.params.items():
            if lk in remap_set:
                continue                 # declared remap: fresh init
            hit = {}
            for tag in pt:
                k = "param/%s/%s" % (lk, tag)
                if k not in blob:
                    continue
                if blob[k].shape != tuple(pt[tag].shape):
                    if strict:
                        raise FinetuneShapeError(
                            lk, tag, blob[k].shape, pt[tag].shape)
                    continue             # legacy: skip, keep fresh init
                hit[tag] = jnp.asarray(blob[k])
            if hit:
                newp = dict(self.params[lk])
                newp.update(hit)
                self.params[lk] = newp
                carried.append(lk)
        for lk, st in self.net_state.items():
            if lk in remap_set:
                continue                 # remapped layers keep fresh state
            for kk in st:
                k = "state/%s/%s" % (lk, kk)
                if k in blob and blob[k].shape == tuple(st[kk].shape):
                    st[kk] = jnp.asarray(blob[k])
        self._put_all()
        self.programs.residency = None   # frozen serve tree is stale
        return carried

    def load_weights_inplace(self, path: str) -> None:
        """Refresh params/net_state/update_counter from a verified
        snapshot (or bundle) WITHOUT rebuilding the graph or the
        dispatch programs — every array must match an existing leaf's
        shape exactly. The continual exporter's per-generation reload:
        the bucket-ladder executables (weight-agnostic; weights are
        arguments) stay valid, so generation exports after the first
        compile zero new programs (doc/continual.md)."""
        assert self._initialized, "call init_model/load_model first"
        blob, meta = self._read_source_blob(path)
        for lk, pt in self.params.items():
            newp = dict(pt)
            for tag in pt:
                k = "param/%s/%s" % (lk, tag)
                if k not in blob:
                    continue
                if blob[k].shape != tuple(pt[tag].shape):
                    raise ValueError(
                        "load_weights_inplace: %s:%s shape %s does not "
                        "match the live net's %s — in-place reload "
                        "requires an identical structure (use "
                        "load_model for a structural change)"
                        % (lk, tag, blob[k].shape,
                           tuple(pt[tag].shape)))
                newp[tag] = jnp.asarray(blob[k])
            self.params[lk] = newp
        for lk, st in self.net_state.items():
            for kk in st:
                k = "state/%s/%s" % (lk, kk)
                if k in blob and blob[k].shape == tuple(st[kk].shape):
                    st[kk] = jnp.asarray(blob[k])
        self.update_counter = int(meta.get("update_counter",
                                           self.update_counter))
        self._put_all()
        self.programs.residency = None   # frozen serve tree is stale

    def copy_model_from(self, path: str) -> None:
        """Finetune: copy weights for layers whose *names* match with
        identical shapes, silently skipping the rest
        (nnet_impl-inl.hpp:117-150). Call after init_model. The
        remap-aware, typed-error front end over the same carry loop
        is :meth:`finetune_from` (the ``task = finetune`` path)."""
        from .checkpoint import read_snapshot
        assert self._initialized
        blob, _ = read_snapshot(path)
        copied = self._carry_from_blob(blob, set(), strict=False)
        if self.silent == 0 and copied:
            print("copy_model_from: copied layers %s" % ", ".join(copied))

    @property
    def last_loss(self) -> float:
        return float(self._last_loss)

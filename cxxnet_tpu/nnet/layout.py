"""Channel-alignment planning: the ``channel_pad`` graph pass.

TPU tensor tiles put the channel (NHWC minor) dimension on the 128-wide
lane axis. Inception-class nets are full of narrow channel counts
(1x1 reduces, pool projections) that leave most lanes dead AND invite
the compiler to put the *batch* on the minor dimension instead — the
documented batch-160 layout cliff (doc/perf_profile.md: 5,082 -> 3,088
img/s from one tiling flip). This pass pads channel dims toward lane
multiples where the padding provably "fuses away":

- padding ORIGINATES at conv outputs: zero weight columns produce
  exactly-zero extra channels (no separate pad op — the conv writes
  the aligned tensor directly);
- it PROPAGATES through layers that preserve the zero-channel
  invariant (batch norm with zero-padded slope/bias, relu, spatial
  pooling, dropout, split) and through ``ch_concat``, which becomes
  alignment-aware: it concatenates the physical (padded) branches and
  records the segment map so downstream consumers stay exact;
- it TERMINATES at consumers that can absorb it for free (a conv
  scatters zero weight rows into the pad gaps) or at explicit
  barriers (flatten/LRN/losses/anything not whitelisted), where the
  valid channels are sliced back out.

Training math is bit-identical: every padded channel is exactly zero
in the forward, receives an exactly-zero cotangent in the backward
(BN pads slope with 0, so the padded epilogue is 0*x+0), and padded
weight rows/columns are materialized zeros, never parameters.

A node's *layout* is a tuple of ``(valid, pad)`` segments along the
channel axis; a plain node is ``((C, 0),)``. Layouts are planned once
at net-build time (layers get their annotations via attributes) — the
jitted program sees only static shapes.

Knobs (net-level, via the global layer config):

- ``channel_pad = Q``: pad channel counts up to multiples of Q
  (0 = off; 128 = full lane alignment, 8/32 for sublane multiples).
- ``channel_pad_max_overhead = R`` (default 0.5): never pad a dim by
  more than R*logical channels — alignment must not blow up the HBM
  activation footprint this model class is roofline-bound on.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp

# (valid, pad) segments along the channel axis
Layout = Tuple[Tuple[int, int], ...]


def plain(c: int) -> Layout:
    return ((c, 0),)


def logical_channels(layout: Layout) -> int:
    return sum(v for v, _ in layout)


def physical_channels(layout: Layout) -> int:
    return sum(v + p for v, p in layout)


def is_padded(layout: Optional[Layout]) -> bool:
    return layout is not None and any(p for _, p in layout)


def pad_channel_vec(v: jnp.ndarray, layout: Layout,
                    fill: float = 0.0) -> jnp.ndarray:
    """Scatter a logical per-channel vector into physical positions,
    filling the pad gaps (slope/bias/scale vectors; last axis)."""
    if not is_padded(layout):
        return v
    parts = []
    off = 0
    for valid, pad in layout:
        parts.append(v[..., off:off + valid])
        if pad:
            parts.append(jnp.full(v.shape[:-1] + (pad,), fill, v.dtype))
        off += valid
    return jnp.concatenate(parts, axis=-1)


def take_valid(x: jnp.ndarray, layout: Layout) -> jnp.ndarray:
    """Slice the valid channels back out of a physical array (last
    axis) — the de-pad at barriers and extraction points."""
    if not is_padded(layout):
        return x
    parts = []
    off = 0
    for valid, pad in layout:
        parts.append(x[..., off:off + valid])
        off += valid + pad
    return jnp.concatenate(parts, axis=-1)


# layer types that preserve the zero-channel invariant and operate
# per-channel, so a padded input passes through untouched
_PROPAGATE = ("relu", "max_pooling", "avg_pooling", "sum_pooling",
              "relu_max_pooling", "pallas_relu_max_pooling", "dropout",
              "split")
_BN = ("batch_norm", "batch_norm_no_ma", "pallas_batch_norm")


def _round_up(c: int, q: int) -> int:
    return (c + q - 1) // q * q


def plan_channel_layouts(net) -> None:
    """Annotate a FuncNet with per-node channel layouts + per-layer
    padding decisions. Runs at build time (after shape inference and
    the fusion passes); with channel_pad = 0 every node is plain and
    no layer behavior changes."""
    g = net.graph
    q = net._net_flag("channel_pad")
    max_overhead = 0.5
    for n, v in g.defcfg:
        if n == "channel_pad_max_overhead":
            max_overhead = float(v)
    layouts: List[Optional[Layout]] = [None] * g.num_nodes
    for ni, s in enumerate(net.node_shapes):
        if s is not None:
            layouts[ni] = plain(s.x if s.is_mat else s.ch)
    net._depad_layers = set()
    layers_padded = 0
    padded_channels = 0

    # layers whose parameters are shared elsewhere must stay unpadded:
    # the shared object would carry one site's annotations to the other
    shared_primaries = set(info.primary_layer_index
                           for info in g.layers if info.type == "share")

    def out_layout(c: int) -> Layout:
        if q <= 0 or c % q == 0:
            return plain(c)
        cp = _round_up(c, q)
        if (cp - c) > max_overhead * c:
            return plain(c)
        return ((c, cp - c),)

    for li, info in enumerate(g.layers):
        layer = net.layer_objs[li]
        ltype = info.type
        in_lays = [layouts[ni] for ni in info.nindex_in]
        spatial_in = [ni for ni in info.nindex_in
                      if net.node_shapes[ni] is not None
                      and not net.node_shapes[ni].is_mat]
        if q <= 0:
            continue
        if (ltype == "conv" and li not in shared_primaries
                and layer.param.num_group == 1):
            # conv absorbs any input padding (zero weight rows) and may
            # originate aligned output (zero weight columns)
            lay_in = in_lays[0]
            ol = out_layout(layer.param.num_channel)
            layer._in_layout = lay_in if is_padded(lay_in) else None
            layer._out_pad = physical_channels(ol) \
                - layer.param.num_channel
            layouts[info.nindex_out[0]] = ol
            if layer._out_pad or layer._in_layout:
                layers_padded += 1
                padded_channels += layer._out_pad
        elif ltype in _BN and li not in shared_primaries:
            lay = in_lays[0]
            if is_padded(lay):
                layer._layout = lay
            for ni in info.nindex_out:
                layouts[ni] = lay
        elif ltype in _PROPAGATE:
            lay = in_lays[0]
            for ni in info.nindex_out:
                layouts[ni] = lay
        elif ltype == "ch_concat" and all(
                l is not None for l in in_lays) and spatial_in:
            # alignment-aware concat: join the physical branches and
            # carry the merged segment map downstream
            merged: List[Tuple[int, int]] = []
            for l in in_lays:
                merged.extend(l)
            out_l = tuple(merged)
            if not is_padded(out_l):      # all-plain branches collapse
                out_l = plain(logical_channels(out_l))
            for ni in info.nindex_out:
                layouts[ni] = out_l
        else:
            # barrier: this layer gets logical inputs (valid channels
            # sliced out) and produces plain outputs — including
            # self-loop connections, whose node becomes logical again
            if any(is_padded(layouts[ni]) for ni in info.nindex_in):
                net._depad_layers.add(li)
            for ni in info.nindex_out:
                s = net.node_shapes[ni]
                if s is not None:
                    layouts[ni] = plain(s.x if s.is_mat else s.ch)

    net.node_layouts = layouts
    net.layout_summary = {
        "channel_pad": q,
        "max_overhead": max_overhead,
        "layers_padded": layers_padded,
        "padded_channels": padded_channels,
        "depad_barriers": len(net._depad_layers),
    }

from . import config, metric

__all__ = ["config", "metric"]

"""Evaluation metrics: rmse / error / logloss / rec@n.

Behavior parity with ``/root/reference/src/utils/metric.h:25-250``:
metrics accumulate (sum, count) over instances; ``MetricSet`` binds each
metric to a named label field (``metric[label] = error`` config) and an
output node; printing format is ``\\t<evname>-<metric>[field]:<value>``.

Distributed: ``get()`` reduces [sum, count] across processes the way the
reference allreduces them over rabit (metric.h:60-68) — here via
``jax.distributed`` process groups when initialized (see
``cxxnet_tpu/parallel``).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


class Metric:
    name = "metric"

    def __init__(self) -> None:
        self.sum_metric = 0.0
        self.cnt_inst = 0

    def clear(self) -> None:
        self.sum_metric = 0.0
        self.cnt_inst = 0

    def _calc(self, pred: np.ndarray, label: np.ndarray) -> np.ndarray:
        """Vectorized per-instance metric: (n,k) preds, (n,w) labels -> (n,)."""
        raise NotImplementedError

    def add_eval(self, pred: np.ndarray, label: np.ndarray) -> None:
        if pred.shape[0] == 0:
            return
        vals = self._calc(np.asarray(pred, np.float32),
                          np.asarray(label, np.float32))
        self.sum_metric += float(np.sum(vals))
        self.cnt_inst += int(pred.shape[0])

    def get(self) -> float:
        s, c = _allreduce_sum_count(self.sum_metric, float(self.cnt_inst))
        return s / c if c > 0 else float("nan")


def _allreduce_sum_count(s: float, c: float) -> Tuple[float, float]:
    """Sum (metric, count) across distributed processes, if any.

    A reduction failure falls back to process-local values (the metric
    line still prints, rabit-style), but VISIBLY: the bare
    ``except Exception: pass`` that silently swallowed collective
    failures is narrowed to the failure modes a degraded DCN/backend
    actually produces, and the fallback emits a once-per-run structured
    warning through the monitor. Anything else (a programming error)
    propagates."""
    try:
        import jax
        if jax.process_count() > 1:
            from ..parallel import allreduce_host_sum
            out = allreduce_host_sum(np.array([s, c], np.float64))
            return float(out[0]), float(out[1])
    except (ImportError, RuntimeError, OSError) as e:
        # JaxRuntimeError (collective timeout, coordination failure)
        # subclasses RuntimeError; ImportError covers a jax-less host
        from ..monitor import warn_once
        warn_once("metric_allreduce_failed",
                  "distributed metric reduction failed (%s: %s); "
                  "reporting process-local metric values"
                  % (type(e).__name__, e))
    return s, c


class MetricRMSE(Metric):
    name = "rmse"

    def _calc(self, pred, label):
        if pred.shape[1] != label.shape[1]:
            raise ValueError("rmse: prediction/label size mismatch")
        return np.sum((pred - label) ** 2, axis=1)


class MetricError(Metric):
    name = "error"

    def _calc(self, pred, label):
        if pred.shape[1] != 1:
            maxidx = np.argmax(pred, axis=1)
        else:
            maxidx = (pred[:, 0] > 0.0).astype(np.int64)
        return (maxidx != label[:, 0].astype(np.int64)).astype(np.float32)


class MetricLogloss(Metric):
    name = "logloss"

    def _calc(self, pred, label):
        eps = 1e-15
        if pred.shape[1] != 1:
            tgt = label[:, 0].astype(np.int64)
            p = np.clip(pred[np.arange(pred.shape[0]), tgt], eps, 1 - eps)
            return -np.log(p)
        p = np.clip(pred[:, 0], eps, 1 - eps)
        y = label[:, 0]
        res = -(y * np.log(p) + (1.0 - y) * np.log(1 - p))
        if np.any(np.isnan(res)):
            raise FloatingPointError("NaN detected in logloss")
        return res


class MetricRecall(Metric):
    """rec@n: fraction of true labels present in the top-n predictions."""

    def __init__(self, name: str):
        super().__init__()
        self.name = name
        if not name.startswith("rec@"):
            raise ValueError("must specify n for rec@n")
        self.topn = int(name[4:])

    def _calc(self, pred, label):
        if pred.shape[1] < self.topn:
            raise ValueError("rec@%d on a list of %d" %
                             (self.topn, pred.shape[1]))
        # ties broken by index (reference shuffles then stable-sorts;
        # equivalent in distribution, deterministic here)
        top = np.argpartition(-pred, self.topn - 1, axis=1)[:, :self.topn]
        hits = (top[:, :, None] == label[:, None, :].astype(np.int64))
        return hits.any(axis=1).sum(axis=1).astype(np.float32) \
            / label.shape[1]


def _topk_by_index(pred: np.ndarray, k: int) -> np.ndarray:
    """Deterministic top-k prediction columns: scores descending,
    ties broken by LOWEST column index — the same order
    ``jax.lax.top_k`` and ``retrieval.oracle_topk`` report, so a
    metric computed over served search results and one computed over
    raw scores agree exactly even with duplicate scores."""
    order = np.argsort(-pred, axis=1, kind="stable")
    return order[:, :k]


class MetricRecallAtK(Metric):
    """recall@k: |relevant ∩ top-k| / |relevant| per row.

    The retrieval-eval recall (doc/retrieval.md), distinct from the
    reference's ``rec@n`` above in three deliberate ways: ``k`` clips
    to the prediction width (k > corpus is a defined query, not an
    error), negative label entries are padding (multi-label rows of
    different lengths share one label matrix), and a row with zero
    valid labels scores 0 while still counting — an all-pad eval
    stream reads as 0 recall, not a crash."""

    def __init__(self, name: str):
        super().__init__()
        self.name = name
        if not name.startswith("recall@"):
            raise ValueError("must specify k for recall@k")
        self.topk = int(name[len("recall@"):])
        if self.topk < 1:
            raise ValueError("recall@k needs k >= 1, got %d"
                             % self.topk)

    def _calc(self, pred, label):
        k = min(self.topk, pred.shape[1])
        top = _topk_by_index(pred, k)
        lab = label.astype(np.int64)
        valid = lab >= 0
        hits = (top[:, :, None] == lab[:, None, :]) & valid[:, None, :]
        nrel = valid.sum(axis=1)
        return np.where(
            nrel > 0,
            hits.any(axis=1).sum(axis=1) / np.maximum(nrel, 1),
            0.0).astype(np.float32)


class MetricPrecisionAtK(Metric):
    """prec@k: |relevant ∩ top-k| / k per row — the multi-label
    serving companion of recall@k. Same conventions: negative labels
    are padding, k clips to the prediction width (the divisor stays
    the requested k: asking for 10 of a 5-wide corpus caps precision
    at 0.5 by construction), empty label rows score 0."""

    def __init__(self, name: str):
        super().__init__()
        self.name = name
        if not name.startswith("prec@"):
            raise ValueError("must specify k for prec@k")
        self.topk = int(name[len("prec@"):])
        if self.topk < 1:
            raise ValueError("prec@k needs k >= 1, got %d" % self.topk)

    def _calc(self, pred, label):
        k = min(self.topk, pred.shape[1])
        top = _topk_by_index(pred, k)
        lab = label.astype(np.int64)
        valid = lab >= 0
        hits = (top[:, :, None] == lab[:, None, :]) & valid[:, None, :]
        return (hits.any(axis=2).sum(axis=1)
                / float(self.topk)).astype(np.float32)


def create_metric(name: str) -> Optional[Metric]:
    if name == "rmse":
        return MetricRMSE()
    if name == "error":
        return MetricError()
    if name == "logloss":
        return MetricLogloss()
    if name.startswith("recall@"):
        return MetricRecallAtK(name)
    if name.startswith("prec@"):
        return MetricPrecisionAtK(name)
    if name.startswith("rec@"):
        return MetricRecall(name)
    return None


class MetricSet:
    """A set of metrics, each bound to (label field, output node name)."""

    def __init__(self) -> None:
        self.evals: List[Metric] = []
        self.label_fields: List[str] = []
        self.node_names: List[str] = []

    def add_metric(self, name: str, field: str = "label",
                   node: str = "") -> None:
        m = create_metric(name)
        if m is None:
            raise ValueError("unknown metric name %r" % name)
        self.evals.append(m)
        self.label_fields.append(field)
        self.node_names.append(node)

    def clear(self) -> None:
        for m in self.evals:
            m.clear()

    def add_eval(self, preds: Sequence[np.ndarray],
                 label_fields: Dict[str, np.ndarray]) -> None:
        """preds: one prediction matrix per metric (non-padded rows only)."""
        assert len(preds) == len(self.evals)
        for m, field, pred in zip(self.evals, self.label_fields, preds):
            if field not in label_fields:
                raise ValueError("Metric: unknown target = %s" % field)
            m.add_eval(pred, label_fields[field])

    def results(self) -> List[Tuple[str, float]]:
        """[(tag, value)] where tag is ``<metric>[field]`` (field tag
        only when non-default) — ONE reduction per metric, shared by
        the parity line and the structured eval record (get() is a
        cross-process collective under distributed runs; calling it
        once per metric keeps ranks' collective counts in lockstep)."""
        out = []
        for m, field in zip(self.evals, self.label_fields):
            tag = m.name if field == "label" \
                else "%s[%s]" % (m.name, field)
            out.append((tag, m.get()))
        return out

    @staticmethod
    def format_line(evname: str,
                    results: List[Tuple[str, float]]) -> str:
        """THE parity eval-line format (reference metric.h printing) —
        defined once; print_str and the trainer's train/eval lines all
        render through here so the byte-exact surface cannot drift."""
        return "".join("\t%s-%s:%g" % (evname, tag, v)
                       for tag, v in results)

    def print_str(self, evname: str) -> str:
        return self.format_line(evname, self.results())

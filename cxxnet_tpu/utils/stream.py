"""Pluggable stream layer: URI-addressed file I/O for every repo open().

TPU-era equivalent of dmlc ``Stream::Create`` / the HDFS-S3 stream
abstraction the reference compiles in behind ``make/config.mk:79-88``
(USE_HDFS / USE_S3) and uses for model and data paths at
``cxxnet_main.cpp:93,189``. One function — ``open_stream(uri, mode)`` —
is the single choke point for model save/load, the mean-image cache,
config files, and every data iterator:

* plain local paths (and ``file://``) use the builtin ``open``;
* URIs with a scheme (``gs://``, ``s3://``, ``hdfs://``, ``http://``,
  ``memory://``, ...) go through ``fsspec`` when it is importable;
* a scheme with no fsspec installed raises a clear error instead of a
  confusing FileNotFoundError;
* tests (and users) can register custom schemes with
  ``register_scheme`` without fsspec — the hook a mock filesystem (and
  the checkpoint fault-injection harness, ``utils/faultfs.py``) uses.

Remote opens can be flaky on preemptible capacity (transient 5xx, DNS
blips).  ``set_stream_retry`` turns on opt-in exponential-backoff
retries for *read* opens of scheme URIs (the ``stream_retry`` config
knob); writers never retry implicitly — the checkpoint layer owns write
failure semantics (doc/checkpointing.md).
"""

import builtins
import os
import random
import re
import time
from typing import Callable, Dict, Optional

# 2+ chars so Windows drive letters ('C://...') stay local, as in
# fsspec/dmlc
_URI_RE = re.compile(r"^([a-zA-Z][a-zA-Z0-9+.-]+)://")


class _SchemeHooks:
    """Registered handlers for one scheme: ``opener(uri, mode)`` is
    required; ``lister(dir_uri) -> [basenames]`` and ``remover(uri)``
    are optional (mock filesystems without them list empty / skip
    deletes)."""

    __slots__ = ("opener", "lister", "remover")

    def __init__(self, opener: Callable,
                 lister: Optional[Callable] = None,
                 remover: Optional[Callable] = None):
        self.opener = opener
        self.lister = lister
        self.remover = remover


# scheme -> _SchemeHooks. Registered openers receive the FULL uri
# (scheme included) so they can interpret it however the backing store
# wants.
_SCHEMES: Dict[str, _SchemeHooks] = {}

# opt-in retry policy for transient remote-read failures (stream_retry)
_RETRY = {"attempts": 0, "base_ms": 50.0, "max_ms": 2000.0}
_RETRY_RECOVERED = 0       # process-lifetime count of retried-then-ok ops


def register_scheme(scheme: str, opener: Callable,
                    lister: Optional[Callable] = None,
                    remover: Optional[Callable] = None) -> None:
    """Register ``opener(uri, mode) -> file-like`` for ``scheme://``
    URIs. Overrides fsspec for that scheme. Pass ``None`` to unregister.

    ``lister(dir_uri) -> [basenames]`` (used by ``list_stream_dir``,
    e.g. the continue=1 resume scan) and ``remover(uri)`` (used by
    snapshot retention GC) are optional.
    """
    if opener is None:
        _SCHEMES.pop(scheme, None)
    else:
        _SCHEMES[scheme] = _SchemeHooks(opener, lister, remover)


def set_stream_retry(attempts: int, base_ms: float = 50.0,
                     max_ms: float = 2000.0) -> None:
    """Enable (attempts > 0) or disable retries for transient remote
    read failures: exponential backoff ``base_ms * 2^k`` capped at
    ``max_ms``, with uniform jitter in [0.5, 1.5)x. Local paths never
    retry — a local IOError is not transient."""
    _RETRY["attempts"] = max(0, int(attempts))
    _RETRY["base_ms"] = float(base_ms)
    _RETRY["max_ms"] = float(max_ms)


def stream_retry_count() -> int:
    """Process-lifetime number of operations that failed transiently
    and then succeeded on retry (the telemetry counter)."""
    return _RETRY_RECOVERED


def _retrying(fn: Callable, uri: str, what: str):
    """Run ``fn()`` under the configured retry policy. On eventual
    success after >=1 failure, warn once and emit a ``stream_retry``
    telemetry record so recovered flakiness stays observable."""
    attempts = _RETRY["attempts"]
    if attempts <= 0:
        return fn()
    tries = 0
    while True:
        try:
            out = fn()
        except (IOError, OSError) as e:
            tries += 1
            if tries > attempts:
                raise
            delay = min(_RETRY["max_ms"],
                        _RETRY["base_ms"] * (2 ** (tries - 1))) / 1e3
            time.sleep(delay * (0.5 + random.random()))
            continue
        if tries:
            global _RETRY_RECOVERED
            _RETRY_RECOVERED += 1
            from ..monitor import get_global, warn_once
            warn_once("stream_retry",
                      "transient %s failure on %r recovered after %d "
                      "retr%s (stream_retry=%d)"
                      % (what, uri, tries, "y" if tries == 1 else "ies",
                         attempts))
            mon = get_global()
            if mon is not None and mon.enabled:
                mon.emit("stream_retry", uri=uri, what=what,
                         attempts=tries)
        return out


def uri_scheme(uri: str) -> str:
    """Return the URI scheme, or '' for a plain local path.

    Windows drive letters ('C://..') and other single-char schemes are
    treated as local paths; 'file://' is normalized to ''.
    """
    m = _URI_RE.match(uri)
    if m is None:
        return ""
    s = m.group(1).lower()
    return "" if s == "file" else s


def local_path(uri: str) -> str:
    """Strip a 'file://' prefix; other URIs/paths pass through."""
    return uri[7:] if uri.lower().startswith("file://") else uri


def _open_raw(uri: str, mode: str):
    scheme = uri_scheme(uri)
    if scheme == "":
        path = local_path(uri)
        if any(c in mode for c in "wa+"):
            d = os.path.dirname(path)
            if d and not os.path.isdir(d):
                os.makedirs(d, exist_ok=True)
        return builtins.open(path, mode)
    if scheme in _SCHEMES:
        return _SCHEMES[scheme].opener(uri, mode)
    try:
        import fsspec
        return fsspec.open(uri, mode).open()
    except (ImportError, ValueError) as e:
        raise IOError(
            "open_stream: no handler for scheme '%s://' (uri=%r): %s. "
            "Install fsspec (plus the %s filesystem package) or "
            "register_scheme('%s', opener)." % (scheme, uri, e, scheme,
                                                scheme))


def open_stream(uri: str, mode: str = "rb"):
    """Open ``uri`` for reading or writing; returns a file-like object.

    The single entry point all framework I/O goes through (reference:
    dmlc ``Stream::Create``, used for model_in/model_dir and iterator
    paths). Local paths open natively; ``scheme://`` URIs dispatch to a
    registered opener or fsspec. Read opens of scheme URIs honor the
    opt-in ``set_stream_retry`` policy (missing objects raise whatever
    the backend raises — FileNotFoundError subclasses OSError, so a
    retry policy will re-probe a missing remote object before giving
    up; that is the desired behavior on eventually-consistent stores).
    """
    if uri_scheme(uri) and not any(c in mode for c in "wa+"):
        return _retrying(lambda: _open_raw(uri, mode), uri, "open")
    return _open_raw(uri, mode)


def read_stream_bytes(uri: str) -> bytes:
    """Read the full contents of ``uri``. For scheme URIs the whole
    open+read is one retryable unit under the ``set_stream_retry``
    policy (a read() that dies mid-stream re-opens from the start —
    the caller gets complete bytes or an exception, never a torn
    prefix). The checkpoint loader reads snapshots through this."""
    def _do():
        with _open_raw(uri, "rb") as f:
            return f.read()
    if uri_scheme(uri):
        return _retrying(_do, uri, "read")
    return _do()


def list_stream_dir(uri: str):
    """List entry basenames of a directory URI; [] if it doesn't exist.

    Local paths use os.listdir; scheme:// URIs use the registered
    lister when one exists, else the fsspec filesystem (registered
    schemes without a lister return []). Used by continue=1 resume to
    find the newest snapshot in a possibly remote model_dir (reference
    cxxnet_main.cpp:180-202).
    """
    scheme = uri_scheme(uri)
    if scheme == "":
        path = local_path(uri)
        if not os.path.isdir(path):
            return []
        return os.listdir(path)
    if scheme in _SCHEMES:
        hooks = _SCHEMES[scheme]
        if hooks.lister is None:
            return []
        return list(hooks.lister(uri))
    try:
        import fsspec
        fs, root = fsspec.core.url_to_fs(uri)
        return [p.rstrip("/").rsplit("/", 1)[-1]
                for p in fs.ls(root, detail=False)]
    except FileNotFoundError:
        return []
    except (ImportError, ValueError):
        # no fsspec / unregistered scheme: treat as an empty directory
        # (registered mock schemes have no listing hook). Transient
        # remote errors (auth, network: other OSErrors) PROPAGATE —
        # mapping them to [] would make continue=1 silently restart
        # from round 0 and overwrite snapshots.
        return []


def remove_stream(uri: str) -> bool:
    """Delete ``uri`` if the backend supports it; True on success,
    False when the object is missing or the scheme has no remover.
    Used by snapshot retention GC — a failed delete must never kill a
    training run, so this swallows per-object errors into False."""
    scheme = uri_scheme(uri)
    if scheme == "":
        try:
            os.remove(local_path(uri))
            return True
        except OSError:
            return False
    if scheme in _SCHEMES:
        hooks = _SCHEMES[scheme]
        if hooks.remover is None:
            return False
        try:
            hooks.remover(uri)
            return True
        except (IOError, OSError, KeyError):
            return False
    try:
        import fsspec
        fs, root = fsspec.core.url_to_fs(uri)
        fs.rm(root)
        return True
    except Exception:
        return False


def stream_exists(uri: str) -> bool:
    """True if ``uri`` names an existing file (local stat or a
    successful remote open)."""
    scheme = uri_scheme(uri)
    if scheme == "":
        return os.path.exists(local_path(uri))
    try:
        with _open_raw(uri, "rb"):
            return True
    except (IOError, OSError):
        return False

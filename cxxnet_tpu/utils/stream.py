"""Pluggable stream layer: URI-addressed file I/O for every repo open().

TPU-era equivalent of dmlc ``Stream::Create`` / the HDFS-S3 stream
abstraction the reference compiles in behind ``make/config.mk:79-88``
(USE_HDFS / USE_S3) and uses for model and data paths at
``cxxnet_main.cpp:93,189``. One function — ``open_stream(uri, mode)`` —
is the single choke point for model save/load, the mean-image cache,
config files, and every data iterator:

* plain local paths (and ``file://``) use the builtin ``open``;
* URIs with a scheme (``gs://``, ``s3://``, ``hdfs://``, ``http://``,
  ``memory://``, ...) go through ``fsspec`` when it is importable;
* a scheme with no fsspec installed raises a clear error instead of a
  confusing FileNotFoundError;
* tests (and users) can register custom schemes with
  ``register_scheme`` without fsspec — the hook a mock filesystem uses.
"""

import builtins
import os
import re
from typing import Callable, Dict

# scheme -> open(path_without_scheme_prefixing_rules, mode) -> file obj.
# Registered openers receive the FULL uri (scheme included) so they can
# interpret it however the backing store wants.
_SCHEMES: Dict[str, Callable] = {}

# 2+ chars so Windows drive letters ('C://...') stay local, as in
# fsspec/dmlc
_URI_RE = re.compile(r"^([a-zA-Z][a-zA-Z0-9+.-]+)://")


def register_scheme(scheme: str, opener: Callable) -> None:
    """Register ``opener(uri, mode) -> file-like`` for ``scheme://``
    URIs. Overrides fsspec for that scheme. Pass ``None`` to unregister.
    """
    if opener is None:
        _SCHEMES.pop(scheme, None)
    else:
        _SCHEMES[scheme] = opener


def uri_scheme(uri: str) -> str:
    """Return the URI scheme, or '' for a plain local path.

    Windows drive letters ('C://..') and other single-char schemes are
    treated as local paths; 'file://' is normalized to ''.
    """
    m = _URI_RE.match(uri)
    if m is None:
        return ""
    s = m.group(1).lower()
    return "" if s == "file" else s


def local_path(uri: str) -> str:
    """Strip a 'file://' prefix; other URIs/paths pass through."""
    return uri[7:] if uri.lower().startswith("file://") else uri


def open_stream(uri: str, mode: str = "rb"):
    """Open ``uri`` for reading or writing; returns a file-like object.

    The single entry point all framework I/O goes through (reference:
    dmlc ``Stream::Create``, used for model_in/model_dir and iterator
    paths). Local paths open natively; ``scheme://`` URIs dispatch to a
    registered opener or fsspec.
    """
    scheme = uri_scheme(uri)
    if scheme == "":
        path = local_path(uri)
        if any(c in mode for c in "wa+"):
            d = os.path.dirname(path)
            if d and not os.path.isdir(d):
                os.makedirs(d, exist_ok=True)
        return builtins.open(path, mode)
    if scheme in _SCHEMES:
        return _SCHEMES[scheme](uri, mode)
    try:
        import fsspec
        return fsspec.open(uri, mode).open()
    except (ImportError, ValueError) as e:
        raise IOError(
            "open_stream: no handler for scheme '%s://' (uri=%r): %s. "
            "Install fsspec (plus the %s filesystem package) or "
            "register_scheme('%s', opener)." % (scheme, uri, e, scheme,
                                                scheme))


def list_stream_dir(uri: str):
    """List entry basenames of a directory URI; [] if it doesn't exist.

    Local paths use os.listdir; scheme:// URIs use the fsspec
    filesystem (registered mock schemes without a lister return []).
    Used by continue=1 resume to find the newest snapshot in a possibly
    remote model_dir (reference cxxnet_main.cpp:180-202).
    """
    scheme = uri_scheme(uri)
    if scheme == "":
        path = local_path(uri)
        if not os.path.isdir(path):
            return []
        return os.listdir(path)
    try:
        import fsspec
        fs, root = fsspec.core.url_to_fs(uri)
        return [p.rstrip("/").rsplit("/", 1)[-1]
                for p in fs.ls(root, detail=False)]
    except FileNotFoundError:
        return []
    except (ImportError, ValueError):
        # no fsspec / unregistered scheme: treat as an empty directory
        # (registered mock schemes have no listing hook). Transient
        # remote errors (auth, network: other OSErrors) PROPAGATE —
        # mapping them to [] would make continue=1 silently restart
        # from round 0 and overwrite snapshots.
        return []


def stream_exists(uri: str) -> bool:
    """True if ``uri`` names an existing file (local stat or a
    successful remote open)."""
    scheme = uri_scheme(uri)
    if scheme == "":
        return os.path.exists(local_path(uri))
    try:
        with open_stream(uri, "rb"):
            return True
    except (IOError, OSError):
        return False

"""Config-file parsing: the ``key = value`` grammar of the reference.

TPU-native rebuild of the cxxnet config surface. Grammar matches the
reference tokenizer (``/root/reference/src/utils/config.h:20-192``):

- tokens are whitespace-separated; ``=`` is its own token
- ``#`` starts a comment that runs to end-of-line
- double-quoted values may contain spaces and newlines
- a config is an *ordered* list of (name, value) pairs; ordering carries
  meaning (iterator blocks, netconfig blocks route parameters positionally,
  see ``/root/reference/src/cxxnet_main.cpp:266-315``).
"""

from __future__ import annotations

from typing import Iterator, List, Tuple
from .stream import open_stream

ConfigPairs = List[Tuple[str, str]]


class ConfigError(ValueError):
    """Raised on malformed configuration input."""


def _tokenize(text: str) -> Iterator[str]:
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == "#":
            while i < n and text[i] != "\n":
                i += 1
        elif c == '"':
            j = i + 1
            while j < n and text[j] != '"':
                j += 1
            if j >= n:
                raise ConfigError("unterminated quoted string in config")
            yield text[i + 1:j]
            i = j + 1
        elif c == "=":
            yield "="
            i += 1
        elif c.isspace():
            i += 1
        else:
            j = i
            while j < n and not text[j].isspace() and text[j] not in '=#"':
                j += 1
            yield text[i:j]
            i = j


def parse_config(text: str) -> ConfigPairs:
    """Parse config text into an ordered list of (name, value) pairs."""
    pairs: ConfigPairs = []
    toks = _tokenize(text)
    for name in toks:
        try:
            eq = next(toks)
            if eq != "=":
                raise ConfigError(
                    "expected '=' after config key %r, got %r" % (name, eq))
            val = next(toks)
            if val == "=":
                raise ConfigError("missing value for config key %r" % name)
        except StopIteration:
            raise ConfigError("incomplete config entry for key %r" % name)
        pairs.append((name, val))
    return pairs


def parse_config_file(path: str) -> ConfigPairs:
    with open_stream(path, "r") as f:
        return parse_config(f.read())


def parse_cli_overrides(args: List[str]) -> ConfigPairs:
    """Parse CLI ``key=value`` override arguments (cxxnet_main.cpp:103-108)."""
    pairs: ConfigPairs = []
    for a in args:
        if "=" not in a:
            raise ConfigError("CLI override must be key=value, got %r" % a)
        k, v = a.split("=", 1)
        pairs.append((k.strip(), v.strip().strip('"')))
    return pairs


def split_sections(pairs: ConfigPairs):
    """Route ordered pairs into (iterator blocks, global pairs).

    Mirrors the positional routing of the reference CLI driver
    (``cxxnet_main.cpp:266-315``): parameters between ``iter = <type>`` and
    ``iter = end`` belong to the data-source block most recently opened by a
    ``data = <name>`` / ``eval = <name>`` / ``pred = <val>`` marker.
    Everything else (including the netconfig block, which the net-graph
    parser routes itself) is global.

    Returns (blocks, global_pairs) where each block is a dict with keys
    ``kind`` ('data'|'eval'|'pred'), ``name``, and ``cfg`` (ordered pairs,
    starting with the chained ``iter`` entries).
    """
    blocks = []
    global_pairs: ConfigPairs = []
    cur = None          # pending data/eval/pred marker
    in_iter = False
    for name, val in pairs:
        if name in ("data", "eval", "pred") and not in_iter:
            cur = {"kind": name, "name": val, "cfg": []}
            continue
        if name == "iter":
            if val == "end":
                in_iter = False
                if cur is not None:
                    blocks.append(cur)
                    cur = None
                continue
            in_iter = True
            if cur is None:
                # iterator block with no marker: treated as anonymous data
                cur = {"kind": "data", "name": "", "cfg": []}
            cur["cfg"].append((name, val))
            continue
        if in_iter and cur is not None:
            cur["cfg"].append((name, val))
        else:
            global_pairs.append((name, val))
    if in_iter:
        raise ConfigError("iterator block not closed with 'iter = end'")
    return blocks, global_pairs

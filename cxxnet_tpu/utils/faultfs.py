"""Fault-injection stream scheme: an in-memory filesystem that fails
on purpose.

The checkpoint subsystem's crash-safety claims (doc/checkpointing.md)
are only as good as the failure modes they were demonstrated against.
This module registers a ``fault://`` (configurable) scheme through
``utils.stream.register_scheme`` and injects, under test control:

* **ENOSPC mid-write** — writes raise ``OSError(ENOSPC)`` once a file
  grows past ``enospc_after`` bytes (full disk / quota mid-serialize);
* **torn writes** — the last ``truncate_tail`` bytes of a written file
  are silently dropped at close (a kill or power loss between the
  write and the durable flush);
* **transient open/read failures** — the next ``fail_opens`` read
  opens (or ``fail_reads`` read() calls) raise IOError, then the
  operation succeeds (the flaky-remote case ``stream_retry`` exists
  for);
* **targeted write failures** — opens-for-write whose URI contains
  ``fail_write_substr`` raise (e.g. set it to ``".ok"`` to kill the
  commit manifest after the payload landed — the remote analogue of
  dying between tmp-write and rename).

It implements the full hook triple (opener, lister, remover), so the
resume scan, retention GC, and ``tools/ckpt_verify.py`` all run
end-to-end against it. Nothing here is test-only plumbing in disguise:
pointing ``model_dir`` at a ``fault://`` URI in a config file is a
supported chaos-drill (doc/checkpointing.md "Proving it").
"""

from __future__ import annotations

import errno
import io
from typing import Dict, Optional

from .stream import register_scheme


class _FaultWriteFile(io.BytesIO):
    """Write buffer that commits to the store on close (minus any
    injected torn tail) and enforces the ENOSPC budget per write()."""

    def __init__(self, fs: "FaultFS", uri: str):
        super().__init__()
        self._fs = fs
        self._uri = uri
        self._aborted = False

    def write(self, data) -> int:
        fs = self._fs
        if (fs.enospc_after is not None
                and self.tell() + len(data) > fs.enospc_after):
            self._aborted = True
            fs.counters["enospc"] += 1
            raise OSError(errno.ENOSPC, "faultfs: no space left on "
                          "device (enospc_after=%d)" % fs.enospc_after)
        return super().write(data)

    def close(self) -> None:
        if not self.closed and not self._aborted:
            data = self.getvalue()
            if self._fs.truncate_tail:
                data = data[:max(0, len(data) - self._fs.truncate_tail)]
                self._fs.counters["truncated"] += 1
            self._fs.store[self._uri] = data
        super().close()


class _FaultReadFile(io.BytesIO):
    def __init__(self, fs: "FaultFS", uri: str, data: bytes):
        super().__init__(data)
        self._fs = fs
        self._uri = uri

    def read(self, *args):
        fs = self._fs
        if fs.fail_reads > 0:
            fs.fail_reads -= 1
            fs.counters["read_fail"] += 1
            raise IOError("faultfs: injected transient read failure "
                          "on %r" % self._uri)
        return super().read(*args)


class FaultFS:
    """One in-memory store plus mutable fault knobs (see module doc).
    Construct, ``install()``, point URIs at ``<scheme>://...``."""

    def __init__(self, scheme: str = "fault"):
        self.scheme = scheme
        self.store: Dict[str, bytes] = {}
        # fault knobs — all off by default; tests flip them mid-run
        self.enospc_after: Optional[int] = None
        self.truncate_tail: int = 0
        self.fail_opens: int = 0
        self.fail_reads: int = 0
        self.fail_write_substr: str = ""
        self.counters = {"enospc": 0, "truncated": 0, "open_fail": 0,
                         "read_fail": 0}

    # -- stream hooks ----------------------------------------------------

    def open(self, uri: str, mode: str = "rb"):
        writing = any(c in mode for c in "wa+")
        if writing:
            if (self.fail_write_substr
                    and self.fail_write_substr in uri):
                self.counters["open_fail"] += 1
                raise IOError("faultfs: injected write failure on %r "
                              "(fail_write_substr=%r)"
                              % (uri, self.fail_write_substr))
            f = _FaultWriteFile(self, uri)
            return f if "b" in mode else io.TextIOWrapper(f)
        if self.fail_opens > 0:
            self.fail_opens -= 1
            self.counters["open_fail"] += 1
            raise IOError("faultfs: injected transient open failure "
                          "on %r" % uri)
        if uri not in self.store:
            raise FileNotFoundError(
                errno.ENOENT, "faultfs: no such object", uri)
        f = _FaultReadFile(self, uri, self.store[uri])
        return f if "b" in mode else io.TextIOWrapper(f)

    def list(self, dir_uri: str):
        prefix = dir_uri.rstrip("/") + "/"
        out = []
        for uri in self.store:
            if uri.startswith(prefix):
                rest = uri[len(prefix):]
                if "/" not in rest:
                    out.append(rest)
        return sorted(out)

    def remove(self, uri: str) -> None:
        del self.store[uri]

    # -- lifecycle -------------------------------------------------------

    def install(self) -> "FaultFS":
        register_scheme(self.scheme, self.open, lister=self.list,
                        remover=self.remove)
        return self

    def uninstall(self) -> None:
        register_scheme(self.scheme, None)

    def clear_faults(self) -> None:
        self.enospc_after = None
        self.truncate_tail = 0
        self.fail_opens = 0
        self.fail_reads = 0
        self.fail_write_substr = ""

"""Model/dataset conversion tools (reference tools/ directory parity)."""

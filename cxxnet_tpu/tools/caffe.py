"""Caffe ``.caffemodel`` importer: a minimal protobuf wire-format reader.

The reference's caffe converter links libcaffe and copies InnerProduct /
Convolution blobs into the net by layer name
(``/root/reference/tools/caffe_converter/convert.cpp:29-187``). Here the
binary NetParameter is decoded directly — no protobuf/caffe dependency —
and the blobs are exposed as a torch-style ``{name.weight, name.bias}``
dict that ``convert.load_source``/``convert.convert`` map onto a net by
layer name, exactly like the torch import path.

Wire format essentials (proto2):
  NetParameter: name=1, layers=2 (repeated V1LayerParameter),
                layer=100 (repeated LayerParameter)
  V1LayerParameter: name=4, type=5(enum), blobs=6
  LayerParameter:   name=1, type=2(string), blobs=7
  BlobProto: num=1 channels=2 height=3 width=4 (legacy 4-D),
             data=5 (repeated float, packed or not),
             shape=7 (BlobShape: dim=1 repeated int64)

Caffe blob layouts match torch's: conv (out, in/group, kh, kw), fc
(out, in) — so the existing name-mapped layout conversion in
``convert.py`` applies unchanged.
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Tuple

import numpy as np

from ..utils.stream import open_stream


# ------------------------------------------------------------ wire level

def _read_varint(buf: memoryview, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("caffe import: truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("caffe import: varint too long")


def _fields(buf: memoryview) -> Iterator[Tuple[int, int, object]]:
    """Yield (field_number, wire_type, value) over a message buffer.

    value: int for wire 0/1/5 (raw bits for the fixed types), memoryview
    for wire 2.
    """
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 1:
            val = buf[pos:pos + 8]
            pos += 8
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError("caffe import: unsupported wire type %d"
                             % wire)
        if pos > n:
            raise ValueError("caffe import: truncated field %d" % field)
        yield field, wire, val


# ------------------------------------------------------------ messages

def _parse_blob(buf: memoryview) -> np.ndarray:
    legacy = {}
    dims: List[int] = []
    floats: List[np.ndarray] = []
    for field, wire, val in _fields(buf):
        if field in (1, 2, 3, 4) and wire == 0:
            legacy[field] = val
        elif field == 5:                      # data
            if wire == 2:                     # packed floats
                floats.append(np.frombuffer(bytes(val), "<f4"))
            elif wire == 5:                   # unpacked single float
                floats.append(np.frombuffer(bytes(val), "<f4"))
        elif field == 7 and wire == 2:        # BlobShape{dim=1 varint}
            for f2, w2, v2 in _fields(val):
                if f2 == 1:
                    if w2 == 0:
                        dims.append(int(v2))
                    elif w2 == 2:             # packed int64 dims
                        p = 0
                        while p < len(v2):
                            d, p = _read_varint(v2, p)
                            dims.append(int(d))
        elif field == 8 and wire == 2:        # double_data
            floats.append(np.frombuffer(bytes(val), "<f8")
                          .astype(np.float32))
    data = (np.concatenate(floats) if floats
            else np.zeros((0,), np.float32))
    if not dims and legacy:
        dims = [legacy.get(i, 1) for i in (1, 2, 3, 4)]
        # drop leading singleton dims of the legacy 4-D shape
        while len(dims) > 1 and dims[0] == 1:
            dims = dims[1:]
    if dims and int(np.prod(dims)) == data.size:
        return data.reshape(dims)
    return data


def _parse_layer(buf: memoryview, v1: bool):
    name = ""
    blobs: List[np.ndarray] = []
    f_name = 4 if v1 else 1
    f_blobs = 6 if v1 else 7
    for field, wire, val in _fields(buf):
        if field == f_name and wire == 2:
            name = bytes(val).decode("utf-8", "replace")
        elif field == f_blobs and wire == 2:
            blobs.append(_parse_blob(val))
    return name, blobs


def load_caffe(path: str) -> Dict[str, np.ndarray]:
    """Read a .caffemodel into ``{layer.weight, layer.bias}`` arrays.

    Layers with no blobs (relu, pooling, data...) are skipped, like the
    reference's dynamic_cast chain only matching InnerProduct/
    Convolution (convert.cpp:75-129).
    """
    with open_stream(path, "rb") as f:
        raw = f.read()
    out: Dict[str, np.ndarray] = {}
    for field, wire, val in _fields(memoryview(raw)):
        if field in (2, 100) and wire == 2:
            name, blobs = _parse_layer(val, v1=(field == 2))
            if not name or not blobs:
                continue
            out[name + ".weight"] = blobs[0]
            if len(blobs) > 1:
                out[name + ".bias"] = blobs[1]
    if not out:
        raise ValueError(
            "caffe import: no parameterized layers found in %r" % path)
    return out


def convert_mean(caffe_mean_path: str, out_npy_path: str) -> np.ndarray:
    """Convert a caffe mean file (a serialized BlobProto) into the
    augmenter's ``image_mean`` .npy cache.

    Counterpart of ``tools/caffe_converter/convert_mean.cpp``: the
    caffe blob is (1, C, H, W) channel-major BGR; the augmenter wants
    HWC RGB (iter_augment.py mean layout), so channels are transposed
    and reversed like the reference's BGR re-ordering.
    """
    with open_stream(caffe_mean_path, "rb") as f:
        blob = _parse_blob(memoryview(f.read()))
    arr = np.asarray(blob, np.float32)
    if arr.ndim == 4:
        arr = arr[0]
    if arr.ndim == 2:                 # grayscale mean: (H, W) -> 1 ch
        arr = arr[None]
    if arr.ndim != 3:
        raise ValueError(
            "caffe import: mean blob in %r must be (C, H, W); got "
            "shape %s" % (caffe_mean_path, arr.shape))
    hwc = arr.transpose(1, 2, 0)[:, :, ::-1]      # CHW BGR -> HWC RGB
    out = np.ascontiguousarray(hwc, np.float32)
    with open_stream(out_npy_path, "wb") as f:
        np.save(f, out)
    return out


def main(argv=None) -> int:
    """CLI: python -m cxxnet_tpu.tools.caffe <mean.binaryproto> <out.npy>

    (model conversion goes through ``cxxnet_tpu.tools.convert`` with a
    .caffemodel source; this entry point is the convert_mean binary.)
    """
    import sys
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) < 2:
        print(main.__doc__)
        return 1
    out = convert_mean(argv[0], argv[1])
    print("convert_mean: %s -> %s %s" % (argv[0], argv[1], out.shape))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())

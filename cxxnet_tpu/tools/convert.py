"""External-model weight importer — the caffe-converter equivalent.

Reference: ``/root/reference/tools/caffe_converter/convert.cpp:29-187``,
which instantiates the target cxxnet net from its config, walks the
source framework's layers, and injects conv/fc blobs into same-named
layers via SetWeight visitors. Same flow here with torch (CPU) or .npz
as the source:

    python -m cxxnet_tpu.tools.convert <src.pth|src.npz> <net.conf> \
        <out.model.npz> [name_map.txt]

Source keys follow the torch convention ``<module>.weight`` /
``<module>.bias`` (npz files use the same key shape). Layers are matched
to target layer names automatically; ``name_map.txt`` rows
``<src_module> <target_layer>`` override. Layouts converted:

- Linear ``(out, in)``      -> fullc wmat (reference layout, set as-is)
- Conv2d ``(O, I, kh, kw)`` -> conv wmat ``(out, in*kh*kw)`` (the
  reference visitor layout; internally re-laid-out to HWIO for the MXU)
- 1-D bias                  -> bias
"""

from __future__ import annotations

import sys
from typing import Dict, Optional

import numpy as np

from ..nnet.trainer import NetTrainer
from ..utils.config import parse_config_file
from ..utils.stream import open_stream


def load_source(path: str) -> Dict[str, np.ndarray]:
    """Load a torch state dict (.pth/.pt), .npz, or .caffemodel into
    flat ``{name.weight, name.bias}`` arrays."""
    if path.endswith(".npz"):
        with open_stream(path, "rb") as f:
            return dict(np.load(f))
    if path.endswith(".caffemodel"):
        from .caffe import load_caffe
        return load_caffe(path)
    import torch
    sd = torch.load(path, map_location="cpu", weights_only=True)
    if hasattr(sd, "state_dict"):
        sd = sd.state_dict()
    return {k: v.detach().cpu().numpy() for k, v in sd.items()
            if hasattr(v, "detach")}


def to_ref_layout(w: np.ndarray) -> Optional[np.ndarray]:
    """Source array -> reference SetWeight layout; None if unsupported."""
    if w.ndim == 1 or w.ndim == 2:
        return w                                  # bias / Linear (out,in)
    if w.ndim == 4:                               # Conv OIHW
        o, i, kh, kw = w.shape
        return w.reshape(o, i * kh * kw)
    return None


def convert(src_path: str, conf_path: str, out_path: str,
            map_path: Optional[str] = None, silent: bool = False) -> int:
    src = load_source(src_path)
    name_map: Dict[str, str] = {}
    if map_path:
        with open_stream(map_path, "r") as f:
            for line in f:
                toks = line.split()
                if len(toks) >= 2:
                    name_map[toks[0]] = toks[1]

    trainer = NetTrainer(parse_config_file(conf_path))
    trainer.init_model()

    # group source keys by module prefix
    modules: Dict[str, Dict[str, np.ndarray]] = {}
    for k, v in src.items():
        if "." not in k:
            continue
        prefix, leaf = k.rsplit(".", 1)
        modules.setdefault(prefix, {})[leaf] = v

    n_copied = 0
    for prefix, blobs in modules.items():
        target = name_map.get(prefix, prefix)
        if target not in trainer.params:
            continue
        for leaf, tag in (("weight", "wmat"), ("bias", "bias")):
            if leaf not in blobs or tag not in trainer.params[target]:
                continue
            w = to_ref_layout(np.asarray(blobs[leaf], np.float32))
            if w is None:
                print("skip %s.%s: unsupported rank %d"
                      % (prefix, leaf, blobs[leaf].ndim))
                continue
            want = trainer.get_weight(target, tag).shape
            if tuple(w.shape) != tuple(want):
                print("skip %s.%s: shape %s does not match %s of %s:%s"
                      % (prefix, leaf, w.shape, want, target, tag))
                continue
            trainer.set_weight(target, tag, w)
            n_copied += 1
            if not silent:
                print("copied %s.%s -> %s:%s %s"
                      % (prefix, leaf, target, tag, w.shape))
    if n_copied == 0:
        print("convert: no weights matched any target layer name")
        return 1
    trainer.save_model(out_path)
    if not silent:
        print("convert: %d tensors -> %s" % (n_copied, out_path))
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) < 3:
        print("Usage: python -m cxxnet_tpu.tools.convert "
              "<src.pth|src.npz|src.caffemodel> <net.conf> "
              "<out.model.npz> [name_map.txt]")
        return 1
    return convert(argv[0], argv[1], argv[2],
                   argv[3] if len(argv) > 3 else None)


if __name__ == "__main__":
    sys.exit(main())

"""Pure-Python im2bin: pack an image list into a BinaryPage archive.

Fallback for the native ``bin/im2bin`` (``tools/im2bin.cc``; reference
``/root/reference/tools/im2bin.cpp``): reads ``index label... path``
rows and appends each image file's raw bytes to a page archive readable
by the imgbin iterator.

Usage: python -m cxxnet_tpu.tools.im2bin <list> <image_root> <out.bin>
"""

import sys

from ..io.binpage import PageWriter
from ..utils.stream import open_stream


def im2bin(list_file: str, image_root: str, out_bin: str,
           label_width: int = 1) -> int:
    n = 0
    w = PageWriter(out_bin)
    with open_stream(list_file, "r") as f:
        for line in f:
            toks = line.split()
            if not toks:
                continue
            path = image_root + toks[1 + label_width]
            with open_stream(path, "rb") as img:
                w.write(img.read())
            n += 1
    w.close()
    print("im2bin: packed %d images -> %s" % (n, out_bin))
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) < 3:
        print(__doc__)
        return 1
    return im2bin(argv[0], argv[1], argv[2],
                  int(argv[3]) if len(argv) > 3 else 1)


if __name__ == "__main__":
    sys.exit(main())

"""CLI: ``python -m cxxnet_tpu.lint [paths...]``.

Exit codes follow the bench.py convention: 0 clean, 1 findings,
2 usage error (argparse owns 2)."""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from .core import (LintError, all_checks, render_human, render_json,
                   run_lint, write_baseline)

_DEFAULT_BASELINE = os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "baseline.json")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m cxxnet_tpu.lint",
        description="cxxlint: framework-aware static analysis "
                    "(doc/static_analysis.md)")
    p.add_argument("paths", nargs="*", default=None,
                   help="files/directories to scan (default: "
                        "cxxnet_tpu/ and tools/ under the cwd)")
    p.add_argument("--format", choices=("human", "json"),
                   default="human")
    p.add_argument("--select", default=None, metavar="CODES",
                   help="comma list of check codes to run "
                        "(e.g. CXL002,CXL006)")
    p.add_argument("--doc-dir", default="doc",
                   help="markdown reference pages for the config-drift "
                        "check (default: ./doc; skipped if absent)")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="baseline file of grandfathered findings "
                        "(default: the committed "
                        "cxxnet_tpu/lint/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--write-baseline", action="store_true",
                   help="write the current findings to the baseline "
                        "file and exit 0")
    p.add_argument("--list-checks", action="store_true",
                   help="describe the registered checks and exit")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_checks:
        for c in all_checks():
            print("%s  %-18s %s" % (c.code, c.name,
                                    c.doc.splitlines()[0] if c.doc
                                    else ""))
        return 0
    paths = args.paths or [p for p in ("cxxnet_tpu", "tools")
                           if os.path.isdir(p)]
    if not paths:
        print("cxxlint: no paths given and no default targets found "
              "in the cwd", file=sys.stderr)
        return 2
    baseline = None
    if not args.no_baseline and not args.write_baseline:
        baseline = args.baseline or (
            _DEFAULT_BASELINE if os.path.isfile(_DEFAULT_BASELINE)
            else None)
    select = None
    if args.select:
        select = [c.strip() for c in args.select.split(",")
                  if c.strip()]
    doc_dir = args.doc_dir if os.path.isdir(args.doc_dir) else None
    try:
        result = run_lint(paths, doc_dir=doc_dir,
                          baseline_path=baseline, select=select)
    except LintError as e:
        print("cxxlint: %s" % e, file=sys.stderr)
        return 2
    if args.write_baseline:
        path = args.baseline or _DEFAULT_BASELINE
        write_baseline(path, result.findings)
        print("cxxlint: wrote %d finding(s) to %s"
              % (len(result.findings), path))
        return 0
    out = render_json(result) if args.format == "json" \
        else render_human(result)
    print(out)
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())

"""Shared AST machinery: qualified names, per-class call graphs,
thread-entry detection, and lock-context-aware body walks.

Scope model: functions get dotted qualnames (``Class.method``,
``Class.method.inner``); statements directly in a class body belong to
the enclosing module scope. Decorators and default-argument
expressions are evaluated in the *enclosing* scope, not inside the
function they decorate — ``@partial(jax.jit, ...)`` on a module-level
function is a module-scope jit reference, which is exactly the
distinction the recompile check needs.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

LOCK_FACTORIES = ("Lock", "RLock", "Condition")


class FuncInfo:
    """One function/method (including nested defs)."""

    __slots__ = ("qualname", "name", "node", "cls", "lineno", "parent")

    def __init__(self, qualname: str, node, cls: Optional[str],
                 parent: Optional[str]):
        self.qualname = qualname
        self.name = node.name
        self.node = node
        self.cls = cls          # innermost enclosing class, if any
        self.parent = parent    # enclosing function qualname, if any
        self.lineno = node.lineno

    @property
    def is_public(self) -> bool:
        return not self.name.startswith("_")


class ModuleIndex:
    """Functions of one module plus the scope of every expression."""

    def __init__(self, tree: ast.AST):
        self.functions: Dict[str, FuncInfo] = {}
        # scope of non-def nodes: maps id(node) -> (qualname, cls);
        # "<module>" for module scope
        self.scope_of: Dict[int, Tuple[str, Optional[str]]] = {}
        self._index(tree, "", None, None)

    def _index(self, node, prefix: str, cls: Optional[str],
               parent: Optional[str]) -> None:
        scope = parent if parent is not None else "<module>"
        self.scope_of[id(node)] = (scope, cls)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qn = prefix + node.name
            self.functions[qn] = FuncInfo(qn, node, cls, parent)
            # decorators/defaults evaluate in the enclosing scope
            for d in node.decorator_list:
                self._walk_into(d, prefix, cls, parent)
            for d in list(node.args.defaults) + \
                    [x for x in node.args.kw_defaults if x is not None]:
                self._walk_into(d, prefix, cls, parent)
            for stmt in node.body:
                self._index(stmt, qn + ".", cls, qn)
            return
        if isinstance(node, ast.ClassDef):
            for d in node.decorator_list + node.bases:
                self._walk_into(d, prefix, cls, parent)
            for stmt in node.body:
                self._index(stmt, node.name + ".", node.name, parent)
            return
        for child in ast.iter_child_nodes(node):
            self._index(child, prefix, cls, parent)

    def _walk_into(self, node, prefix, cls, parent) -> None:
        for n in ast.walk(node):
            self.scope_of[id(n)] = (
                parent if parent is not None else "<module>", cls)

    # -- queries ----------------------------------------------------------

    def scope(self, node) -> str:
        return self.scope_of.get(id(node), ("<module>", None))[0]

    def class_of(self, node) -> Optional[str]:
        return self.scope_of.get(id(node), ("<module>", None))[1]

    def methods_of(self, cls: str) -> List[FuncInfo]:
        """All functions belonging to class ``cls`` (methods AND
        functions nested inside them — a closure submitted to a worker
        still runs with the instance's ``self`` in scope)."""
        return [f for f in self.functions.values() if f.cls == cls]

    def resolve_bare(self, name: str,
                     from_qualname: str) -> Optional[str]:
        """Resolve a bare-name call/reference from inside
        ``from_qualname``: innermost nested def first, then enclosing
        scopes, then module level."""
        scope = from_qualname
        while scope:
            cand = scope + "." + name
            if cand in self.functions:
                return cand
            scope = scope.rpartition(".")[0]
        return name if name in self.functions else None


def body_walk(funcnode) -> Iterator[ast.AST]:
    """Walk a function's own body, NOT descending into nested
    function/class definitions (their bodies are separate scopes) —
    but still yielding the def nodes themselves."""
    stack = list(funcnode.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def call_edges(idx: ModuleIndex, fi: FuncInfo) -> Set[str]:
    """Qualnames this function may call: ``self.m(...)`` resolved
    within its class, bare names resolved lexically."""
    out: Set[str] = set()
    for node in body_walk(fi.node):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and fn.value.id == "self" \
                and fi.cls is not None:
            cand = fi.cls + "." + fn.attr
            if cand in idx.functions:
                out.add(cand)
        elif isinstance(fn, ast.Name):
            cand = idx.resolve_bare(fn.id, fi.qualname)
            if cand is not None:
                out.add(cand)
    return out


def reachable(idx: ModuleIndex, roots: Set[str]) -> Set[str]:
    """Transitive closure of :func:`call_edges` from ``roots``."""
    seen: Set[str] = set()
    stack = [r for r in roots if r in idx.functions]
    while stack:
        qn = stack.pop()
        if qn in seen:
            continue
        seen.add(qn)
        for nxt in call_edges(idx, idx.functions[qn]):
            if nxt not in seen:
                stack.append(nxt)
    return seen


def thread_roots(idx: ModuleIndex, tree: ast.AST) -> Set[str]:
    """Functions that run on a spawned thread:

    - ``threading.Thread(target=self.m)`` / ``Thread(target=f)`` —
      the target method/local function;
    - ``<anything>.submit(f)`` where ``f`` is a local def — worker
      submission (the async checkpoint writer's pattern). The callee
      name is not resolved (any executor-like object counts); this is
      deliberately conservative in the "more findings" direction.
    """
    roots: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        callee = fn.attr if isinstance(fn, ast.Attribute) else \
            fn.id if isinstance(fn, ast.Name) else ""
        if callee == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    roots.update(_resolve_ref(idx, node, kw.value))
        elif callee == "submit" and node.args:
            ref = _resolve_ref(idx, node, node.args[0])
            # only local defs: executor.submit(some_import) is opaque
            roots.update(r for r in ref if "." in r or
                         r in idx.functions)
    return roots


def _resolve_ref(idx: ModuleIndex, at_node, expr) -> Set[str]:
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and expr.value.id == "self":
        cls = idx.class_of(at_node)
        if cls is not None and cls + "." + expr.attr in idx.functions:
            return {cls + "." + expr.attr}
        return set()
    if isinstance(expr, ast.Name):
        cand = idx.resolve_bare(expr.id, idx.scope(at_node))
        return {cand} if cand else set()
    return set()


def declared_locks(idx: ModuleIndex, cls: str) -> Set[str]:
    """Instance attributes assigned a ``threading.Lock/RLock/
    Condition`` anywhere in the class."""
    locks: Set[str] = set()
    for fi in idx.methods_of(cls):
        for node in body_walk(fi.node):
            if not isinstance(node, ast.Assign):
                continue
            v = node.value
            if not (isinstance(v, ast.Call) and _is_lock_factory(v.func)):
                continue
            for t in node.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    locks.add(t.attr)
    return locks


def _is_lock_factory(fn) -> bool:
    name = fn.attr if isinstance(fn, ast.Attribute) else \
        fn.id if isinstance(fn, ast.Name) else ""
    return name in LOCK_FACTORIES


def locked_walk(funcnode, lock_attrs: Set[str]
                ) -> Iterator[Tuple[ast.AST, bool]]:
    """Yield (node, holding_lock) over a function body, where
    ``holding_lock`` is True inside ``with self.<lock>:`` for any
    declared lock attribute. Does not descend into nested defs."""

    def rec(node, locked):
        yield node, locked
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = locked
            for item in node.items:
                for n, lk in rec(item.context_expr, locked):
                    yield n, lk
                if item.optional_vars is not None:
                    for n, lk in rec(item.optional_vars, locked):
                        yield n, lk
                ce = item.context_expr
                if isinstance(ce, ast.Attribute) and \
                        isinstance(ce.value, ast.Name) and \
                        ce.value.id == "self" and ce.attr in lock_attrs:
                    inner = True
            for stmt in node.body:
                for n, lk in rec(stmt, inner):
                    yield n, lk
            return
        for child in ast.iter_child_nodes(node):
            for n, lk in rec(child, locked):
                yield n, lk

    for stmt in funcnode.body:
        for n, lk in rec(stmt, False):
            yield n, lk


def self_attr_writes(funcnode, lock_attrs: Set[str]
                     ) -> List[Tuple[str, int, bool]]:
    """(attr, line, locked) for every ``self.attr = / += ...`` in the
    function body."""
    out: List[Tuple[str, int, bool]] = []
    for node, locked in locked_walk(funcnode, lock_attrs):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            for tt in _flatten_targets(t):
                if isinstance(tt, ast.Attribute) and \
                        isinstance(tt.value, ast.Name) and \
                        tt.value.id == "self":
                    out.append((tt.attr, node.lineno, locked))
    return out


def _flatten_targets(t):
    if isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            yield from _flatten_targets(e)
    else:
        yield t


def self_attr_uses(funcnode) -> Set[str]:
    """Attributes of ``self`` referenced (any context) in the body."""
    out: Set[str] = set()
    for node in body_walk(funcnode):
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self":
            out.add(node.attr)
    return out


def dotted_name(node) -> str:
    """Best-effort dotted rendering of a Name/Attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        parts.append(dotted_name(node.func) + "()")
    else:
        parts.append("<expr>")
    return ".".join(reversed(parts))

"""Check registry, suppression/baseline machinery, and the runner.

A check is a function ``check(project) -> iterable[Finding]`` decorated
with :func:`register`. The runner parses every target file once into a
:class:`Project`, runs each registered check over it, then applies the
two escape hatches in order:

1. inline suppressions — ``# cxxlint: disable=<code> -- <reason>`` on
   the finding's line (or a standalone comment on the line above). The
   reason is mandatory; a reasonless or unused suppression is itself a
   finding (CXL000), so the suppression inventory can never rot.
   Markdown targets use the same directive in an HTML comment;
   directives inside fenced code blocks are ignored (doc examples).
2. the committed baseline — grandfathered findings keyed by
   ``(code, path, key)`` where ``key`` is a stable fingerprint (an
   attribute name, a config key, an emit kind — never a line number),
   so baselined findings survive unrelated edits but a *new* instance
   of an old problem still fails the gate.
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

# finding codes are CXL0NN; CXL000 is reserved for lint-directive
# hygiene (bad/unused suppressions, unparseable files)
CODE_RE = re.compile(r"^CXL\d{3}$")

_SUPPRESS_RE = re.compile(
    r"cxxlint:\s*disable=([A-Za-z0-9_,\s]+?)"
    r"(?:\s*--\s*(.*?))?\s*$")
# the HTML-comment close is stripped BEFORE matching: otherwise the
# '--' of '-->' reads as the reason separator and a reasonless
# markdown directive would sneak through with reason '>'
_MD_CLOSE_RE = re.compile(r"\s*-->\s*$")


class LintError(Exception):
    """Usage-level failure (bad path, unreadable baseline): exit 2."""


class Finding:
    """One finding. ``key`` is the stable identity used for baseline
    matching; ``line`` is for humans and suppression matching only."""

    __slots__ = ("code", "check", "path", "line", "key", "message")

    def __init__(self, code: str, check: str, path: str, line: int,
                 key: str, message: str):
        assert CODE_RE.match(code), code
        self.code = code
        self.check = check
        self.path = path
        self.line = int(line)
        self.key = key
        self.message = message

    def fingerprint(self) -> Tuple[str, str, str]:
        return (self.code, self.path, self.key)

    def as_dict(self) -> Dict[str, Any]:
        return {"code": self.code, "check": self.check,
                "path": self.path, "line": self.line,
                "key": self.key, "message": self.message}

    def render(self) -> str:
        return "%s:%d: %s [%s] %s" % (self.path, self.line, self.code,
                                      self.check, self.message)


class Suppression:
    __slots__ = ("line", "codes", "reason", "used")

    def __init__(self, line: int, codes: List[str], reason: str):
        self.line = line
        self.codes = codes
        self.reason = reason
        self.used = False


class SourceFile:
    """One parsed target: Python (``tree`` set) or markdown/other
    (``tree`` None). ``rel`` is the path as given, posix-separated —
    the stable path used in findings and the baseline."""

    def __init__(self, rel: str, source: str,
                 tree: Optional[ast.AST] = None):
        self.rel = rel
        self.source = source
        self.tree = tree
        self.lines = source.splitlines()
        self.suppressions: Dict[int, Suppression] = {}
        self._scan_suppressions()

    def _scan_suppressions(self) -> None:
        is_md = self.rel.endswith(".md")
        in_fence = False
        for i, line in enumerate(self.lines, start=1):
            if is_md and line.lstrip().startswith("```"):
                in_fence = not in_fence
                continue
            if in_fence or "cxxlint:" not in line:
                continue
            work = _MD_CLOSE_RE.sub("", line) if "<!--" in line \
                else line
            m = _SUPPRESS_RE.search(work)
            if m is None:
                continue
            codes = [c.strip() for c in m.group(1).split(",")
                     if c.strip()]
            reason = (m.group(2) or "").strip()
            # a standalone comment line suppresses the NEXT line;
            # a trailing comment suppresses its own line
            stripped = line.strip()
            target = i + 1 if stripped.startswith(("#", "<!--")) else i
            self.suppressions[target] = Suppression(i, codes, reason)


class Project:
    """Everything the checks see: parsed Python files plus raw doc
    pages, with the config constants resolved once."""

    def __init__(self, pyfiles: List[SourceFile],
                 docfiles: List[SourceFile], config):
        self.pyfiles = pyfiles
        self.docfiles = docfiles
        self.config = config

    def find_py(self, suffix: str) -> Optional[SourceFile]:
        for f in self.pyfiles:
            if f.rel.endswith(suffix):
                return f
        return None


class Check:
    __slots__ = ("code", "name", "doc", "fn")

    def __init__(self, code: str, name: str, doc: str, fn: Callable):
        self.code = code
        self.name = name
        self.doc = doc
        self.fn = fn


_REGISTRY: Dict[str, Check] = {}


def register(code: str, name: str):
    """Class-registry decorator: ``@register("CXL00N", "check-name")``
    over a function ``check(project) -> iterable[Finding]``. The
    function docstring becomes the ``--list-checks`` description."""
    assert CODE_RE.match(code), code

    def deco(fn):
        assert code not in _REGISTRY, "duplicate check code %s" % code
        _REGISTRY[code] = Check(code, name, (fn.__doc__ or "").strip(),
                                fn)
        return fn
    return deco


def all_checks() -> List[Check]:
    _load_builtin_checks()
    return [_REGISTRY[c] for c in sorted(_REGISTRY)]


def _load_builtin_checks() -> None:
    from . import checks as _checks  # noqa: F401  (import populates)


class LintResult:
    def __init__(self):
        self.findings: List[Finding] = []      # live (reported)
        self.suppressed: List[Tuple[Finding, str]] = []
        self.baselined: List[Finding] = []
        self.files_scanned = 0
        self.checks_run: List[str] = []

    def as_dict(self) -> Dict[str, Any]:
        return {
            "findings": [f.as_dict() for f in self.findings],
            "counts": {"findings": len(self.findings),
                       "suppressed": len(self.suppressed),
                       "baselined": len(self.baselined),
                       "files": self.files_scanned},
            "checks": self.checks_run,
        }


# -- target collection ----------------------------------------------------


def _norm(path: str) -> str:
    return path.replace(os.sep, "/")


def collect_py_paths(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, files in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__")
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
        elif os.path.isfile(p):
            out.append(p)
        else:
            raise LintError("no such file or directory: %r" % p)
    return out


def load_project(paths: Iterable[str], doc_dir: Optional[str],
                 config) -> Tuple[Project, List[Finding]]:
    """Parse every target; unparseable Python is a CXL000 finding, not
    a crash (the gate must report the file, not die on it)."""
    parse_errors: List[Finding] = []
    pyfiles: List[SourceFile] = []
    for path in collect_py_paths(paths):
        rel = _norm(path)
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except OSError as e:
            raise LintError("cannot read %s: %s" % (path, e))
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            parse_errors.append(Finding(
                "CXL000", "lint-hygiene", rel, e.lineno or 1,
                "parse-error",
                "file does not parse: %s" % e.msg))
            continue
        pyfiles.append(SourceFile(rel, src, tree))
    docfiles: List[SourceFile] = []
    if doc_dir and os.path.isdir(doc_dir):
        for fn in sorted(os.listdir(doc_dir)):
            if fn.endswith(".md"):
                path = os.path.join(doc_dir, fn)
                with open(path, encoding="utf-8") as f:
                    docfiles.append(SourceFile(_norm(path), f.read()))
    return Project(pyfiles, docfiles, config), parse_errors


# -- baseline -------------------------------------------------------------


def load_baseline(path: str) -> set:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except OSError as e:
        raise LintError("cannot read baseline %s: %s" % (path, e))
    except ValueError as e:
        raise LintError("baseline %s is not valid JSON: %s" % (path, e))
    if not isinstance(data, dict) or "findings" not in data:
        raise LintError("baseline %s: expected {\"findings\": [...]}"
                        % path)
    out = set()
    for ent in data["findings"]:
        try:
            out.add((ent["code"], ent["path"], ent["key"]))
        except (KeyError, TypeError) as e:
            # a malformed entry is a usage error (exit 2), not a
            # traceback that reads as "findings present" (exit 1)
            raise LintError(
                "baseline %s: entry %r is missing code/path/key (%s)"
                % (path, ent, e))
    return out


def write_baseline(path: str, findings: List[Finding]) -> None:
    ents = sorted({f.fingerprint() for f in findings})
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"comment": "grandfathered cxxlint findings; "
                              "regenerate with --write-baseline",
                   "findings": [{"code": c, "path": p, "key": k}
                                for c, p, k in ents]},
                  f, indent=1, sort_keys=True)
        f.write("\n")


# -- runner ---------------------------------------------------------------


def run_lint(paths: Iterable[str], doc_dir: Optional[str] = None,
             baseline_path: Optional[str] = None,
             select: Optional[Iterable[str]] = None,
             config=None) -> LintResult:
    """Run the registered checks; returns a :class:`LintResult` whose
    ``findings`` are the live (unsuppressed, unbaselined) ones."""
    if config is None:
        from . import config as config  # repo defaults
    checks = all_checks()
    known = {c.code for c in checks} | {"CXL000"}
    if select is not None:
        sel = set(select)
        bad = sel - known
        if bad:
            raise LintError("unknown check code(s): %s"
                            % ", ".join(sorted(bad)))
        checks = [c for c in checks if c.code in sel]
    project, raw = load_project(paths, doc_dir, config)
    result = LintResult()
    result.files_scanned = len(project.pyfiles) + len(project.docfiles)
    result.checks_run = [c.code for c in checks]
    for check in checks:
        for f in check.fn(project):
            raw.append(f)

    # -- suppressions ----------------------------------------------------
    by_rel = {f.rel: f for f in project.pyfiles}
    by_rel.update({f.rel: f for f in project.docfiles})
    live: List[Finding] = []
    for f in raw:
        sf = by_rel.get(f.path)
        sup = sf.suppressions.get(f.line) if sf is not None else None
        if sup is not None and f.code in sup.codes and sup.reason:
            sup.used = True
            result.suppressed.append((f, sup.reason))
        else:
            live.append(f)
    # directive hygiene: reasons are mandatory, dead suppressions and
    # unknown codes are findings — the escape hatch cannot rot silently
    for sf in list(project.pyfiles) + list(project.docfiles):
        for sup in sf.suppressions.values():
            if not sup.reason:
                live.append(Finding(
                    "CXL000", "lint-hygiene", sf.rel, sup.line,
                    "missing-reason:%d" % sup.line,
                    "suppression without a reason: use "
                    "'cxxlint: disable=%s -- <why>'"
                    % ",".join(sup.codes)))
            for c in sup.codes:
                if c not in known:
                    live.append(Finding(
                        "CXL000", "lint-hygiene", sf.rel, sup.line,
                        "unknown-code:%s:%d" % (c, sup.line),
                        "suppression names unknown check %r" % c))
            if sup.reason and not sup.used \
                    and all(c in known for c in sup.codes):
                # only meaningful when the suppressed checks actually
                # ran — a --select run must not flag the rest as dead
                ran = set(result.checks_run) | {"CXL000"}
                if any(c in ran for c in sup.codes):
                    live.append(Finding(
                        "CXL000", "lint-hygiene", sf.rel, sup.line,
                        "unused:%d" % sup.line,
                        "unused suppression (nothing fires here "
                        "anymore): remove it"))

    # -- baseline --------------------------------------------------------
    baseline = load_baseline(baseline_path) if baseline_path else set()
    for f in live:
        if f.fingerprint() in baseline:
            result.baselined.append(f)
        else:
            result.findings.append(f)
    result.findings.sort(key=lambda f: (f.path, f.line, f.code, f.key))
    return result


# -- output ---------------------------------------------------------------


def render_human(result: LintResult) -> str:
    out = [f.render() for f in result.findings]
    out.append("cxxlint: %d finding(s), %d suppressed, %d baselined, "
               "%d file(s) scanned, checks: %s"
               % (len(result.findings), len(result.suppressed),
                  len(result.baselined), result.files_scanned,
                  " ".join(result.checks_run)))
    return "\n".join(out)


def render_json(result: LintResult) -> str:
    return json.dumps(result.as_dict(), indent=1, sort_keys=True)

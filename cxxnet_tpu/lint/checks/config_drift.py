"""CXL005: config-key drift between the code and doc/*.md.

The config surface is the user contract (PAPER.md: the reference is
driven entirely by ``key = value`` files), but keys are consumed in
a dozen ``set_param(name, val)`` / ``for name, val in cfg`` sites
across the tree, and documented by hand in doc/*.md. The two drift:
a new knob ships undocumented, or a doc table advertises a key no
code reads. Both directions are findings:

- **consumed-but-undocumented** — a key literal compared against the
  config name (``name == "k"``, ``name in ("a", "b")``,
  ``name.startswith("k")``) in a consumer context that never appears
  as a word anywhere in doc/*.md. Finding at the consumption site.
- **documented-but-unconsumed** — a key row of an authoritative
  ``| key | ... |``-headed markdown table whose key no consumer
  matches. Finding at the doc line; mark the row "deprecated" (or
  remove it) if the key is intentionally dead. Keys consumed through
  regex/computed patterns are declared in
  ``lint.config.CONFIG_KEYS_PATTERN_CONSUMED`` with their real
  consumer named.

Consumer contexts are (a) functions whose first non-self parameters
are literally ``(name, val)`` — the tree's set_param convention — and
(b) ``for name, val in ...`` two-tuple loops (the config-pairs
convention). A doc-side finding is suppressed with the usual directive
in an HTML comment on the table row. The stale direction only runs
when the scan includes ``lint.config.CONFIG_CONSUMER_ROOT`` (the main
CLI's config consumer) — a partial scan must not call every
documented key stale.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Set, Tuple

from ..core import Finding, register

_KEY_NORM = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*")
_TABLE_HEAD = re.compile(r"^\|\s*key\s*\|", re.IGNORECASE)
_CELL_KEYS = re.compile(r"`([^`]+)`")


def _norm_key(text: str):
    m = _KEY_NORM.match(text.strip())
    return m.group(0) if m else None


def _name_param_funcs(tree) -> List[ast.AST]:
    out = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            params = [a.arg for a in node.args.args
                      if a.arg not in ("self", "cls")]
            if params[:2] == ["name", "val"]:
                out.append(node)
    return out


def _tuple_loop_bodies(tree) -> List[ast.AST]:
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.For) and \
                isinstance(node.target, ast.Tuple) and \
                len(node.target.elts) == 2 and \
                isinstance(node.target.elts[0], ast.Name) and \
                node.target.elts[0].id == "name":
            out.append(node)
    return out


def _keys_in(scope_node, var: str = "name"
             ) -> List[Tuple[str, int, bool]]:
    """(key, line, is_prefix) literals matched against ``var``."""
    found: List[Tuple[str, int, bool]] = []
    for node in ast.walk(scope_node):
        if isinstance(node, ast.Compare) and \
                isinstance(node.left, ast.Name) and \
                node.left.id == var and len(node.ops) == 1 and \
                isinstance(node.ops[0], (ast.Eq, ast.In, ast.NotEq)):
            cmp = node.comparators[0]
            consts = []
            if isinstance(cmp, ast.Constant):
                consts = [cmp]
            elif isinstance(cmp, (ast.Tuple, ast.List, ast.Set)):
                consts = [e for e in cmp.elts
                          if isinstance(e, ast.Constant)]
            for c in consts:
                if isinstance(c.value, str):
                    found.append((c.value, node.lineno, False))
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "startswith" and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == var and node.args:
            a = node.args[0]
            elts = [a] if isinstance(a, ast.Constant) else \
                list(a.elts) if isinstance(a, (ast.Tuple, ast.List)) \
                else []
            for c in elts:
                if isinstance(c, ast.Constant) and \
                        isinstance(c.value, str):
                    found.append((c.value, node.lineno, True))
    return found


def _consumed_keys(project) -> Dict[str, Tuple[str, int]]:
    """normalized key -> first (path, line) consumption site."""
    out: Dict[str, Tuple[str, int]] = {}
    for sf in project.pyfiles:
        scopes = _name_param_funcs(sf.tree) + _tuple_loop_bodies(sf.tree)
        for scope in scopes:
            for key, line, _pref in _keys_in(scope):
                k = _norm_key(key)
                if k and k not in out:
                    out[k] = (sf.rel, line)
    return out


def _doc_table_keys(project) -> List[Tuple[str, str, int, bool]]:
    """(key, docpath, line, deprecated) from | key |-headed tables."""
    rows: List[Tuple[str, str, int, bool]] = []
    for df in project.docfiles:
        in_table = False
        for i, line in enumerate(df.lines, start=1):
            if _TABLE_HEAD.match(line):
                in_table = True
                continue
            if in_table:
                if not line.lstrip().startswith("|"):
                    in_table = False
                    continue
                cells = line.split("|")
                if len(cells) < 3:
                    continue
                first = cells[1]
                if set(first.strip()) <= {"-", ":", " "}:
                    continue          # the |---|---| separator row
                dep = "deprecated" in line.lower()
                for m in _CELL_KEYS.finditer(first):
                    k = _norm_key(m.group(1))
                    if k:
                        rows.append((k, df.rel, i, dep))
    return rows


def _word_in_docs(project, key: str) -> bool:
    pat = re.compile(r"(?<![A-Za-z0-9_])%s(?![A-Za-z0-9_])"
                     % re.escape(key))
    for df in project.docfiles:
        if pat.search(df.source):
            return True
    return False


@register("CXL005", "config-drift")
def check(project) -> Iterator[Finding]:
    """Config keys consumed in code must appear in doc/*.md; keys in
    authoritative doc tables must still have a consumer."""
    if not project.docfiles:
        return []
    consumed = _consumed_keys(project)
    out: List[Finding] = []
    for key in sorted(consumed):
        rel, line = consumed[key]
        if not _word_in_docs(project, key):
            out.append(Finding(
                "CXL005", "config-drift", rel, line,
                "undocumented:%s" % key,
                "config key %r is consumed here but never mentioned "
                "in doc/*.md — add it to the matching reference page"
                % key))
    if project.find_py(project.config.CONFIG_CONSUMER_ROOT) is None:
        # partial scan: without the primary consumer in the scan set,
        # "no consumer found" means "you didn't scan the consumers",
        # not "the doc row is stale" — skip the stale direction (the
        # undocumented direction above is per-file and already ran)
        return out
    pattern_ok = set(project.config.CONFIG_KEYS_PATTERN_CONSUMED)
    seen_doc: Set[str] = set()
    for key, rel, line, dep in _doc_table_keys(project):
        if dep or key in consumed or key in pattern_ok or \
                key in seen_doc:
            continue
        seen_doc.add(key)
        out.append(Finding(
            "CXL005", "config-drift", rel, line,
            "stale-doc:%s" % key,
            "documented config key %r has no consumer in the scanned "
            "tree — remove the row, mark it deprecated, or (if it is "
            "consumed via a pattern) declare it in "
            "lint.config.CONFIG_KEYS_PATTERN_CONSUMED" % key))
    return out

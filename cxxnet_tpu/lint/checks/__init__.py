"""Built-in check modules; importing this package populates the
registry (core.all_checks). Add a new check by dropping a module here
with a ``@register("CXL0NN", "name")`` function and importing it below
— doc/static_analysis.md walks through a full example."""

from . import (config_drift, hotpath, locks, recompile,  # noqa: F401
               schema_drift, swallow)

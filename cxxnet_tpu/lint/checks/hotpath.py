"""CXL003: host sync on the hot path.

The steady-state contract of this codebase is "the host is off the hot
path" (PR 2) and "zero compiles, zero surprise syncs after warmup"
(PR 4). A single stray ``np.asarray`` on a device value inside the
dispatch loop serializes H2D/compute overlap; one ``.item()`` turns a
pipelined step into a round trip. The hot-path roots are declared in
``lint.config.HOT_PATH_ROOTS``; everything reachable from them in the
same module is audited for the host-sync operators:

- ``jax.device_get`` / ``jax.block_until_ready`` /
  ``<x>.block_until_ready()``
- ``<x>.item()`` / ``<x>.tolist()``
- ``np.asarray`` / ``np.array`` (the tree's idiomatic D2H copy)

Two finding flavors:

- a plain hot-path sync — legitimate ones (metric copies, the
  monitor-gated step timing sync, host-side input staging) carry an
  inline suppression naming the justification, so every sync on the
  path is accounted for;
- a sync while HOLDING a declared lock — the convoy variant: every
  other thread queues behind a device round trip. These should be
  restructured (sync outside the critical section), not suppressed.

Known limitation, by design: ``float(device_scalar)`` also syncs but
``float()`` over host scalars is everywhere; flagging it would bury
the signal. The operators above are the ones this tree uses for D2H.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from ..astutil import (ModuleIndex, declared_locks, locked_walk,
                       reachable)
from ..core import Finding, register

_SYNC_METHOD = ("block_until_ready", "item", "tolist")
_NP_FUNCS = ("asarray", "array")


def _sync_desc(node) -> Optional[str]:
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    if isinstance(fn, ast.Attribute):
        if fn.attr in ("device_get", "block_until_ready") and \
                isinstance(fn.value, ast.Name) and fn.value.id == "jax":
            return "jax." + fn.attr
        if fn.attr in _SYNC_METHOD and not isinstance(fn.value,
                                                      ast.Name):
            return "." + fn.attr + "()"
        if isinstance(fn.value, ast.Name) and fn.attr in _SYNC_METHOD \
                and fn.value.id not in ("np", "numpy", "math", "json"):
            return "." + fn.attr + "()"
        if fn.attr in _NP_FUNCS and isinstance(fn.value, ast.Name) \
                and fn.value.id in ("np", "numpy"):
            return "np." + fn.attr
    return None


@register("CXL003", "hotpath-host-sync")
def check(project) -> Iterator[Finding]:
    """Host-sync operators reachable from the declared hot-path roots
    (lint.config.HOT_PATH_ROOTS); lock-held syncs flagged separately."""
    out: List[Finding] = []
    for sf in project.pyfiles:
        roots: Set[str] = set()
        for suffix, quals in project.config.HOT_PATH_ROOTS.items():
            if sf.rel.endswith(suffix):
                roots.update(quals)
        if not roots:
            continue
        idx = ModuleIndex(sf.tree)
        reach = reachable(idx, roots)
        lock_cache = {}
        for qn in sorted(reach):
            fi = idx.functions[qn]
            locks = set()
            if fi.cls is not None:
                if fi.cls not in lock_cache:
                    lock_cache[fi.cls] = declared_locks(idx, fi.cls)
                locks = lock_cache[fi.cls]
            n_at_line: dict = {}
            for node, locked in locked_walk(fi.node, locks):
                desc = _sync_desc(node)
                if desc is None:
                    continue
                i = n_at_line.setdefault(node.lineno, 0)
                n_at_line[node.lineno] = i + 1
                if locked:
                    out.append(Finding(
                        "CXL003", "hotpath-host-sync", sf.rel,
                        node.lineno,
                        "locked:%s:%s:%d" % (qn, desc, i),
                        "%s inside a 'with self.<lock>:' block in %s "
                        "(hot path): the device round trip convoys "
                        "every thread waiting on the lock — move the "
                        "sync outside the critical section"
                        % (desc, qn)))
                else:
                    out.append(Finding(
                        "CXL003", "hotpath-host-sync", sf.rel,
                        node.lineno,
                        "%s:%s:%d" % (qn, desc, i),
                        "%s in %s is reachable from a hot-path root — "
                        "if this host sync is intentional (host-side "
                        "staging, monitor-gated timing, metric copy) "
                        "suppress it with the reason; otherwise keep "
                        "the value on device" % (desc, qn)))
    return out

"""CXL004: telemetry schema drift.

Every record kind the tree emits must have a REQUIRED validator in
``monitor/schema.py``, and every validator must still have an emitter
— both directions, with file:line findings. This is the promotion of
the old grep-driven guard in tests/test_serve.py to a real AST pass:
the grep pattern (``\\bemit\\(``) could not see the serve layer's
``self._emit("serve_request", ...)`` wrapper emitters because ``_`` is
a word character, so five serving record kinds were invisible to the
guard that existed to protect them.

Emit sites are calls to a function/method named ``emit`` or ``_emit``
whose first positional argument (or ``event=``/``kind=`` keyword) is a
string literal; forwarding shims (``self._mon.emit(kind, ...)``) pass
a variable and are naturally skipped. The REQUIRED map is read
statically from the AST of the schema module found among the scanned
files (``lint.config.SCHEMA_MODULE`` suffix). A scan that sees emit
sites but no schema module is itself a finding (the old grep guard's
"pattern rotted" assert, kept): run the linter over the package root,
as the tier-1 gate does, and the check can never become a silent
no-op because the schema moved.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Tuple

from ..core import Finding, register

_EMIT_NAMES = ("emit", "_emit")


def _emit_kind(node: ast.Call):
    fn = node.func
    name = fn.attr if isinstance(fn, ast.Attribute) else \
        fn.id if isinstance(fn, ast.Name) else ""
    if name not in _EMIT_NAMES:
        return None
    if node.args and isinstance(node.args[0], ast.Constant) and \
            isinstance(node.args[0].value, str):
        return node.args[0].value
    for kw in node.keywords:
        if kw.arg in ("event", "kind") and \
                isinstance(kw.value, ast.Constant) and \
                isinstance(kw.value.value, str):
            return kw.value.value
    return None


def _required_map(schema_sf) -> Dict[str, int]:
    """kind -> line of its key in the REQUIRED dict literal."""
    out: Dict[str, int] = {}
    for node in ast.walk(schema_sf.tree):
        target = None
        if isinstance(node, ast.Assign) and node.targets:
            target = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            target = node.target
        if not (isinstance(target, ast.Name) and
                target.id == "REQUIRED"):
            continue
        if isinstance(node.value, ast.Dict):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and \
                        isinstance(k.value, str):
                    out[k.value] = k.lineno
    return out


@register("CXL004", "schema-drift")
def check(project) -> Iterator[Finding]:
    """Every literal emit() kind has a REQUIRED validator and every
    validator still has an emitter (monitor/schema.py)."""
    schema_sf = project.find_py(project.config.SCHEMA_MODULE)
    emitted: Dict[str, List[Tuple[str, int]]] = {}
    for sf in project.pyfiles:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call):
                kind = _emit_kind(node)
                if kind is not None:
                    emitted.setdefault(kind, []).append(
                        (sf.rel, node.lineno))
    if schema_sf is None:
        if not emitted:
            return []                 # nothing to validate, no schema
        # anti-rot (the old grep guard's "pattern rotted" assert): a
        # scan that SEES emit sites but cannot find the schema module
        # must fail loudly, not silently stop validating — otherwise a
        # moved/renamed schema.py (or a stale SCHEMA_MODULE constant)
        # turns the whole check into a no-op while the gate stays green
        first_kind = sorted(emitted)[0]
        rel, line = emitted[first_kind][0]
        return [Finding(
            "CXL004", "schema-drift", rel, line,
            "no-schema-module",
            "%d emit site(s) found but no %r in the scan set — scan "
            "the package root (the schema module must be included for "
            "kinds to be validated), or update lint.config."
            "SCHEMA_MODULE if the schema moved"
            % (sum(len(v) for v in emitted.values()),
               project.config.SCHEMA_MODULE))]
    required = _required_map(schema_sf)
    out: List[Finding] = []
    for kind in sorted(emitted):
        if kind in required:
            continue
        rel, line = emitted[kind][0]
        out.append(Finding(
            "CXL004", "schema-drift", rel, line,
            "unvalidated:%s" % kind,
            "record kind %r is emitted here but has no REQUIRED "
            "validator in %s — a consumer cannot trust the stream; "
            "add the entry (and its required fields) to the schema"
            % (kind, schema_sf.rel)))
    for kind in sorted(required):
        if kind in emitted:
            continue
        out.append(Finding(
            "CXL004", "schema-drift", schema_sf.rel, required[kind],
            "orphan-validator:%s" % kind,
            "REQUIRED entry %r has no emit site anywhere in the "
            "scanned tree — dead schema vocabulary; delete the entry "
            "or restore the emitter" % kind))
    return out

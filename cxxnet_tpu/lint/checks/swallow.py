"""CXL006: silent exception swallows.

``except: pass`` hides real failures until they surface as corrupt
output — PR 1's metric-allreduce fallback failed silently for a whole
round before it was converted to a warn-once. Any exception handler
whose body is nothing but ``pass`` is a finding; survivors must either
become a ``monitor.warn_once`` (the tree's warn-exactly-once
convention) or carry a suppression whose reason says why silence is
correct (e.g. a racing ``Future`` already resolved, best-effort
cleanup on an exit path).
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from ..astutil import ModuleIndex, dotted_name
from ..core import Finding, register


@register("CXL006", "silent-swallow")
def check(project) -> Iterator[Finding]:
    """except-handlers whose body is only ``pass``."""
    out: List[Finding] = []
    for sf in project.pyfiles:
        idx = ModuleIndex(sf.tree)
        seen = {}
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not all(isinstance(s, ast.Pass) for s in node.body):
                continue
            exc = dotted_name(node.type) if node.type is not None \
                else "<bare>"
            qn = idx.scope(node)
            i = seen.setdefault((qn, exc), 0)
            seen[(qn, exc)] = i + 1
            # anchored at the pass statement: that is where the
            # suppression comment naturally lives
            out.append(Finding(
                "CXL006", "silent-swallow", sf.rel,
                node.body[0].lineno,
                "%s:%s:%d" % (qn, exc, i),
                "except %s: pass in %s swallows the failure silently "
                "— warn once (monitor.warn_once) or suppress with the "
                "reason silence is correct" % (exc, qn)))
    return out

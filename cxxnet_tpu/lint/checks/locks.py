"""CXL002: lock discipline — cross-thread instance state written
unlocked.

Six subsystems share threads (serve dispatcher, frontend, hot-swap
watchers, async checkpoint writer, cv-queue prefetch); the class of
bug this encodes is the one that forced JsonlSink's retrofitted write
lock in PR 4: instance state mutated on a spawned thread while the
main thread reads or writes it, with no lock between them.

Model (per class, per module):

- *declared locks* — attributes assigned ``threading.Lock/RLock/
  Condition`` anywhere in the class;
- *thread-reachable* — the same-module call-graph closure from every
  ``threading.Thread(target=...)`` method/closure and every local
  function handed to a worker via ``.submit(fn)`` (the async
  checkpoint writer's pattern);
- *main-reachable* — the closure from the class's public methods
  (anything external callers invoke on the constructing thread).

A write ``self.attr = ...`` in thread-reachable code, outside a
``with self.<declared lock>:`` block, is a finding when the attribute
is visible to the other side: it is public (external readers), or the
writing function is also main-reachable (the watcher's ``check_once``
pattern — same method runs on both threads), or the attribute is
touched by a main-only method. ``__init__`` writes are construction,
not sharing, and are exempt.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Set

from ..astutil import (ModuleIndex, declared_locks, reachable,
                       self_attr_uses, self_attr_writes, thread_roots)
from ..core import Finding, register


@register("CXL002", "lock-discipline")
def check(project) -> Iterator[Finding]:
    """Instance attributes written on a spawned thread without the
    class's declared lock while the main thread can see them."""
    out: List[Finding] = []
    for sf in project.pyfiles:
        idx = ModuleIndex(sf.tree)
        roots = thread_roots(idx, sf.tree)
        if not roots:
            continue
        # group the roots by owning class; module-level thread targets
        # have no instance state for this check to reason about
        by_cls: Dict[str, Set[str]] = {}
        for r in roots:
            cls = idx.functions[r].cls if r in idx.functions else None
            if cls is not None:
                by_cls.setdefault(cls, set()).add(r)
        for cls, cls_roots in sorted(by_cls.items()):
            locks = declared_locks(idx, cls)
            thread_reach = reachable(idx, cls_roots)
            public = {f.qualname for f in idx.methods_of(cls)
                      if f.is_public and f.parent is None
                      and f.name != "__init__"}
            main_reach = reachable(idx, public)
            # attributes a main-only method touches (read or write)
            main_only_touch: Set[str] = set()
            for fi in idx.methods_of(cls):
                if fi.qualname in thread_reach or \
                        fi.name == "__init__":
                    continue
                main_only_touch |= self_attr_uses(fi.node)
            seen: Set[str] = set()
            for qn in sorted(thread_reach):
                fi = idx.functions.get(qn)
                if fi is None or fi.cls != cls or fi.name == "__init__":
                    continue
                for attr, line, locked in \
                        self_attr_writes(fi.node, locks):
                    if locked or attr in locks:
                        continue
                    shared = (not attr.startswith("_")) \
                        or qn in main_reach \
                        or attr in main_only_touch
                    if not shared:
                        continue
                    key = "%s.%s" % (cls, attr)
                    if key in seen:
                        continue
                    seen.add(key)
                    out.append(Finding(
                        "CXL002", "lock-discipline", sf.rel, line,
                        key,
                        "%s.%s is written in %s (runs on a spawned "
                        "thread) without holding a declared lock%s — "
                        "the main thread can observe a torn/stale "
                        "value; guard the write (and its readers) "
                        "with a lock" % (
                            cls, attr, qn,
                            " (class declares: %s)"
                            % ", ".join(sorted(locks)) if locks
                            else " (class declares no lock)")))
    return out

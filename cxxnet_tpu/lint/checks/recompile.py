"""CXL001: recompile hazard — program construction outside the
registry.

AOT program construction was once duplicated across four call sites
(trainer precompile, serve engine, bench, pred) before PR 4 collapsed
them onto the single-sourced ``pred_sig`` key scheme. A fifth copy
would reintroduce the silent-recompile class of bug: a signature built
slightly differently compiles its own executable in the hot path and
the zero-compile-after-warmup contract dies by a thousand cache
misses. This check makes the registry mechanical: any reference to
``jax.jit`` / ``pjit`` or any ``.lower(<args>)`` call outside
``lint.config.PROGRAM_BUILDERS`` is a finding.

``.lower()`` with NO arguments is ignored — that is ``str.lower``;
jax's AOT entry takes the example arguments being lowered for.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from ..astutil import ModuleIndex, dotted_name
from ..core import Finding, register

_JIT_ATTRS = ("jit", "pjit")


def _is_jit_ref(node) -> bool:
    if isinstance(node, ast.Name) and node.id == "pjit":
        return True
    if isinstance(node, ast.Attribute) and node.attr in _JIT_ATTRS:
        v = node.value
        return isinstance(v, ast.Name) and v.id in ("jax", "pjit")
    return False


def _allowed(rel: str, qualname: str, config) -> bool:
    for suffix, quals in config.PROGRAM_BUILDERS.items():
        if rel.endswith(suffix):
            for q in quals:
                if qualname == q or qualname.startswith(q + "."):
                    return True
    return False


@register("CXL001", "recompile-hazard")
def check(project) -> Iterator[Finding]:
    """jax.jit / pjit / .lower(args) outside the program-build
    registry (lint.config.PROGRAM_BUILDERS)."""
    out: List[Finding] = []
    for sf in project.pyfiles:
        idx = ModuleIndex(sf.tree)
        for node in ast.walk(sf.tree):
            what = None
            if _is_jit_ref(node):
                what = dotted_name(node)
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "lower" and \
                    (node.args or node.keywords):
                what = dotted_name(node.func) + "(...)"
            if what is None:
                continue
            qn = idx.scope(node)
            if _allowed(sf.rel, qn, project.config):
                continue
            out.append(Finding(
                "CXL001", "recompile-hazard", sf.rel, node.lineno,
                "%s:%s" % (qn, what),
                "%s in %s builds an XLA program outside the program "
                "registry — route it through NetTrainer.precompile/"
                "precompile_pred (pred_sig key scheme) or add the "
                "function to lint.config.PROGRAM_BUILDERS in a "
                "reviewed diff" % (what, qn)))
    return out

"""Repo-specific knowledge the checks key off.

These maps are the single place where "which functions may build XLA
programs" and "which functions are the serving/training hot path" are
declared. A new AOT call site or hot-path root is a one-line diff here
— reviewed as such — instead of an invisible new compile hazard.
Paths are matched as ``/``-separated suffixes of the scanned file
path, so the maps work from any checkout root.
"""

# -- CXL001: the program-construction registry ----------------------------
# The ONLY code allowed to call jax.jit / pjit / .lower(...): the
# trainer's single-sourced program builders (PR 4 collapsed four
# duplicated AOT sites into these) and the Pallas kernel module's
# module-level decorators. Everything else must route through
# NetTrainer.precompile / precompile_pred / the engine, which share the
# pred_sig key scheme — a fifth duplicate program-build site fails the
# gate instead of shipping a silent recompile hazard.
PROGRAM_BUILDERS = {
    "cxxnet_tpu/nnet/trainer.py": (
        "NetTrainer._build_steps",
        "NetTrainer.precompile",
        "NetTrainer.precompile_pred",
        "NetTrainer._compile_programs",
        # the one-time serve weight-residency upload: folds/quantizes/
        # casts the eval weight tree on device at freeze
        # (doc/serving.md "Device memory accounting") — never
        # dispatched per request
        "NetTrainer._build_resident_prep",
    ),
    # the program registry (doc/artifacts.md): the one compile loop
    # every (key, lower-thunk) pair goes through, and the sealed-
    # artifact deserializer that installs bundle executables in place
    # of compilation
    "cxxnet_tpu/artifact/registry.py": (
        "ProgramRegistry.compile",
        "ProgramRegistry.install_serialized",
    ),
    "cxxnet_tpu/layers/pallas_kernels.py": ("<module>",),
    # the calibration amax program (one jitted forward computing every
    # quantizable layer's activation range per batch) — offline
    # task=quantize path, never dispatched while serving
    "cxxnet_tpu/nnet/quantize.py": (
        "Calibrator._build_amax_program",
    ),
    # the step_breakdown measurement programs (doc/distributed.md
    # "Overlapped gradient sync"): a grad-only program and a group-
    # granular reduce-only program, built once per measurement call by
    # bench --hosts / the scaling sweep — never on the training path
    "cxxnet_tpu/parallel/gradsync.py": (
        "measure_step_breakdown",
    ),
    # the retrieval top-k program family (doc/retrieval.md): one lower
    # site per query bucket, keyed by search_sig in the SAME registry
    # as the predict programs — sealed into bundles and installed at
    # boot, so a served /v1/search never reaches this builder
    "cxxnet_tpu/retrieval/engine.py": (
        "RetrievalEngine._lower_search",
    ),
}

# -- CXL003: hot-path roots -----------------------------------------------
# Functions on the steady-state throughput path: the per-dispatch train
# loop and the serve stage/dispatch pair. Anything reachable from these
# (same-module call graph) that forces a host sync — np.asarray /
# device_get / block_until_ready / .item() / .tolist() — is either a
# measured, justified sync (inline suppression with the reason) or a
# regression.
HOT_PATH_ROOTS = {
    "cxxnet_tpu/nnet/trainer.py": (
        "NetTrainer.update",
        "NetTrainer.update_many",
        "NetTrainer.run_steps",
    ),
    "cxxnet_tpu/serve/engine.py": (
        "InferenceEngine.stage",
        "InferenceEngine.dispatch",
    ),
    "cxxnet_tpu/serve/batcher.py": (
        "DynamicBatcher._collect_loop",
        "DynamicBatcher._dispatch_loop",
    ),
    # the fleet balancer's per-request path (doc/serving.md
    # "Horizontal fleet"): every fleet request funnels through
    # handle -> _route -> _forward, so a host sync added there taxes
    # the whole fleet's latency, not one engine's. The multiplexed
    # data path (doc/serving.md "Fleet data path") adds the channel
    # writer/reader loops (every forward's frames and replies cross
    # them) and the coalescer flush + merged-forward chain — all
    # steady-state per-request code. The same registrations anchor
    # the CXL002 side: the loops are threading.Thread targets, so the
    # lock-discipline closure already covers the state they share
    # with submitting threads.
    "cxxnet_tpu/fleet/balancer.py": (
        "FleetBalancer.handle",
        "FleetBalancer._route",
        "FleetBalancer._forward",
        "FleetBalancer._forward_merged",
        "ReplicaChannel._writer_loop",
        "ReplicaChannel._reader_loop",
        "_Coalescer._flush_loop",
    ),
    # the replica-side v2 frame loop: request decode (zero-copy
    # frombuffer view), async admission, and the out-of-order reply
    # writer — the per-request path of every pipelined fleet forward
    "cxxnet_tpu/serve/frontend.py": (
        "_BinaryHandler.handle",
        "_V2ConnState.complete",
        "FleetServer.handle_async",
    ),
}

# -- CXL004: telemetry schema ---------------------------------------------
# The module holding the REQUIRED validator map, matched by suffix.
SCHEMA_MODULE = "monitor/schema.py"

# -- CXL005: config-key drift ---------------------------------------------
# The stale-doc direction (documented key with no consumer) only runs
# when the scan set includes the primary config consumer below — a
# partial scan (one file + the real doc/ tree) must not call every
# documented key stale. The undocumented direction runs per-file
# regardless.
CONFIG_CONSUMER_ROOT = "cxxnet_tpu/main.py"

# Keys consumed through a pattern the literal scanner cannot see (regex
# or computed-prefix matching). Each entry names its real consumer so
# the allowlist is auditable.
CONFIG_KEYS_PATTERN_CONSUMED = {
    "metric": "nnet/trainer.py _RE_METRIC (metric / metric[field,node])",
    "label_vec": "io/data.py label_vec[a,b) range binding",
    "extra_data_shape": "io/data.py extra_data_shape[i] indexed keys",
    "layer": "graph.py netconfig layer[from->to] section grammar",
}

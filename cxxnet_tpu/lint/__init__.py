"""cxxlint: framework-aware static analysis for the cxxnet_tpu tree.

The worst bugs in a threaded JAX stack are invisible at runtime:
an unlocked cross-thread mutation loses one counter a week, a fifth
duplicated AOT call site recompiles silently in the serve hot path,
a new telemetry kind ships without a schema validator. cxxlint is the
mechanical memory of those past bugs — each check encodes an invariant
a previous PR had to retrofit by hand (doc/static_analysis.md has the
full catalogue and the history behind every code).

Usage (CLI)::

    python -m cxxnet_tpu.lint cxxnet_tpu/ tools/
    python -m cxxnet_tpu.lint --format json --select CXL002,CXL006

Exit codes follow the bench.py convention: 0 clean, 1 findings,
2 usage error.

Suppressions are inline and must carry a reason::

    x = np.asarray(loss)  # cxxlint: disable=<code> -- <why>

(with the real ``CXL00N`` code; doc/static_analysis.md shows worked
examples.)

Grandfathered findings live in a committed baseline file
(``cxxnet_tpu/lint/baseline.json``); the tier-1 gate keeps the merged
tree at zero unsuppressed, unbaselined findings.
"""

from .core import (Finding, LintError, LintResult, all_checks, register,
                   run_lint)

__all__ = ["Finding", "LintError", "LintResult", "all_checks",
           "register", "run_lint"]

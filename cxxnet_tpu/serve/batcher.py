"""Dynamic micro-batching dispatcher with backpressure.

Concurrent clients submit one example or a small row array; a
coalescing queue closes a micro-batch when ``max_batch`` rows are
pending or the oldest request has waited ``max_delay_ms``; the batch
pads to its bucket and dispatches; per-request futures resolve with the
request's own rows of the result.

Production semantics, deliberately:

- **bounded queue / reject-with-busy** — ``submit`` raises
  :class:`ServeBusyError` the moment pending rows would exceed
  ``max_queue_rows``; an overloaded server answers *busy now* instead
  of building an unbounded latency queue.
- **per-request deadlines** — a request that is still queued when its
  deadline passes fails with :class:`ServeTimeoutError` at batch-form
  time (it never wastes device work).
- **exception propagation** — an engine failure resolves exactly the
  futures of the batch that hit it; the loop keeps serving.
- **graceful shutdown** — ``close(drain=True)`` stops intake, runs
  every queued request through the engine, then joins the workers;
  ``drain=False`` fails the queue fast with :class:`ServeClosedError`.
- **pipelined hand-off** — a collector thread stages batch N+1's H2D
  transfer while the dispatch thread computes batch N (the PR 2
  prefetch-chain overlap applied to serving), through a depth-bounded
  queue between them.

Telemetry (all schema-validated, ``monitor/schema.py``): per-request
``serve_request`` (status, queue wait, latency), per-micro-batch
``serve_batch`` (fill rate, pad fraction, device time), and one
``serve_summary`` at close (latency p50/p99 from an O(1) histogram,
aggregate fill/pad, rejection and timeout counts).
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from typing import Any, Callable, Dict, Optional

import numpy as np

from ..monitor import LatencyHistogram, SafeEmitter


class ServeBusyError(RuntimeError):
    """Queue full: the server sheds this request instead of queueing."""


class ServeTimeoutError(TimeoutError):
    """The request's deadline passed while it waited in the queue."""


class ServeClosedError(RuntimeError):
    """The server is shut down (or shutting down without drain)."""


def _set_exception(future: Future, exc: BaseException) -> None:
    """Fail a future that might have been cancelled by its client
    meanwhile — a cancelled future refuses set_exception, and that
    refusal must never kill a serve worker thread."""
    try:
        future.set_exception(exc)
    except InvalidStateError:
        pass  # cxxlint: disable=CXL006 -- client cancelled first; the failure has no recipient and the docstring is the contract


class _Request:
    __slots__ = ("rows", "n", "future", "t_submit", "deadline")

    def __init__(self, rows: np.ndarray, deadline: Optional[float]):
        self.rows = rows
        self.n = rows.shape[0]
        self.future: Future = Future()
        self.t_submit = time.monotonic()
        self.deadline = deadline


class DynamicBatcher:
    """Coalesce request rows into bucketed micro-batches.

    ``stage_fn(rows) -> staged`` issues the H2D transfer (cheap,
    async); it receives ONE row array for a single-request batch and a
    LIST of per-request row arrays for a coalesced one (so an engine
    with a preallocated staging ring assembles client rows in a single
    copy). ``dispatch_fn(staged) -> np.ndarray`` runs the executable
    and returns one output row per input row. The split exists so the
    two halves can overlap across consecutive batches.
    """

    def __init__(self, stage_fn: Callable[[np.ndarray], Any],
                 dispatch_fn: Callable[[Any], np.ndarray],
                 max_batch: int, max_delay_ms: float = 2.0,
                 max_queue_rows: int = 0, timeout_ms: float = 0.0,
                 monitor=None, stage_depth: int = 2,
                 extra_summary: Optional[Callable[[], Dict[str, Any]]]
                 = None, row_shape: Optional[tuple] = None):
        self._stage_fn = stage_fn
        self._dispatch_fn = dispatch_fn
        self.max_batch = int(max_batch)
        self.max_delay_s = max(0.0, float(max_delay_ms)) / 1e3
        self.max_queue_rows = int(max_queue_rows) or 8 * self.max_batch
        if self.max_queue_rows < self.max_batch:
            # a bound below max_batch would shed every full-size
            # request forever with a "queue full" that blames load that
            # does not exist — surface the misconfiguration at startup
            raise ValueError(
                "max_queue_rows (%d) must be >= max_batch (%d)"
                % (self.max_queue_rows, self.max_batch))
        self.default_timeout_s = max(0.0, float(timeout_ms)) / 1e3
        self._extra_summary = extra_summary
        # per-row shape every request must match (so one client cannot
        # poison a coalesced batch for the others); None = adopt the
        # first request's shape
        self._row_shape = None if row_shape is None else tuple(row_shape)
        self._pending: deque = deque()
        self._pending_rows = 0
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._staged_q: "queue.Queue" = queue.Queue(max(1, stage_depth))
        self._closed = False
        self._t0 = time.monotonic()
        # leaf lock for the cross-thread stats (collector, dispatcher
        # and submit all mutate them; += on a dict slot is not atomic)
        self._stats = threading.Lock()
        self._safe_emit = SafeEmitter(monitor, "cxxnet_tpu serve")
        self._lat = LatencyHistogram()   # request latencies, always on
        self.counters: Dict[str, int] = {
            "requests": 0, "rows": 0, "batches": 0, "batch_rows": 0,
            "bucket_rows": 0, "pad_rows": 0, "rejected": 0,
            "timeouts": 0, "cancelled": 0, "errors": 0}
        self._collector = threading.Thread(
            target=self._collect_loop, name="serve-collect", daemon=True)
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="serve-dispatch",
            daemon=True)
        self._collector.start()
        self._dispatcher.start()

    # -- client surface --------------------------------------------------

    def submit(self, rows: np.ndarray,
               timeout_ms: Optional[float] = None) -> Future:
        """Queue ``rows`` (leading axis = batch, 1..max_batch rows) and
        return the Future of their result rows. Raises ServeBusyError
        on a full queue, ServeClosedError after shutdown."""
        rows = np.asarray(rows)
        if rows.shape[0] < 1 or rows.shape[0] > self.max_batch:
            raise ValueError(
                "request must carry 1..%d rows, got %d"
                % (self.max_batch, rows.shape[0]))
        t = self.default_timeout_s if timeout_ms is None \
            else max(0.0, float(timeout_ms)) / 1e3
        req = _Request(rows, time.monotonic() + t if t > 0 else None)
        shed = None
        with self._lock:
            if self._closed:
                raise ServeClosedError("serve batcher is closed")
            # rows coalesce into one array with other clients' rows —
            # a mismatched shape must bounce to THIS caller, not blow
            # up the shared batch
            if self._row_shape is None:
                self._row_shape = rows.shape[1:]
            elif rows.shape[1:] != self._row_shape:
                raise ValueError(
                    "request row shape %r does not match the served "
                    "shape %r" % (rows.shape[1:], self._row_shape))
            if self._pending_rows + req.n > self.max_queue_rows:
                shed = self._pending_rows
            else:
                self._pending.append(req)
                self._pending_rows += req.n
                self._wake.notify_all()
        if shed is not None:
            # telemetry outside the queue lock: overload shedding must
            # stay cheap, not serialize every submitter behind sink I/O
            with self._stats:
                self.counters["rejected"] += 1
            self._emit_request("busy", req, 0.0)
            raise ServeBusyError(
                "queue full (%d rows pending, limit %d)"
                % (shed, self.max_queue_rows))
        return req.future

    def __call__(self, rows: np.ndarray,
                 timeout_ms: Optional[float] = None) -> np.ndarray:
        """Blocking convenience: submit and wait for the result."""
        return self.submit(rows, timeout_ms).result()

    # -- collector: coalesce + stage -------------------------------------

    def _collect_loop(self) -> None:
        while True:
            with self._lock:
                while not self._pending and not self._closed:
                    self._wake.wait()
                if not self._pending:     # closed and drained
                    break
                window_end = self._pending[0].t_submit + self.max_delay_s
                # wait for the micro-batch to fill or the delay window
                # to pass (closing flushes immediately: drain must not
                # sit out the delay per batch)
                while (self._pending_rows < self.max_batch
                       and not self._closed):
                    remaining = window_end - time.monotonic()
                    if remaining <= 0:
                        break
                    self._wake.wait(remaining)
                batch, dropped, cancelled = [], [], 0
                total = 0
                now = time.monotonic()
                while self._pending:
                    req = self._pending[0]
                    if req.deadline is not None and now > req.deadline:
                        self._pending.popleft()
                        self._pending_rows -= req.n
                        dropped.append(req)
                        continue
                    if total + req.n > self.max_batch:
                        break
                    self._pending.popleft()
                    self._pending_rows -= req.n
                    # batch-form is the commit point: a future the
                    # client already cancelled leaves the batch here
                    # (after this call the future can no longer be
                    # cancelled, so set_result below cannot throw)
                    if not req.future.set_running_or_notify_cancel():
                        cancelled += 1
                        continue
                    batch.append(req)
                    total += req.n
            if cancelled:
                with self._stats:
                    self.counters["cancelled"] += cancelled
            for req in dropped:
                wait_ms = (now - req.t_submit) * 1e3
                with self._stats:
                    self.counters["timeouts"] += 1
                    self._lat.observe(now - req.t_submit)
                self._emit_request("timeout", req, wait_ms,
                                   latency_ms=wait_ms)
                _set_exception(req.future, ServeTimeoutError(
                    "request expired after %.1f ms in queue" % wait_ms))
            if not batch:
                continue
            try:
                # a multi-request batch hands the per-request row
                # arrays straight to stage: the engine assembles them
                # into its preallocated staging buffer in ONE copy
                # (client array -> H2D source) instead of paying a
                # concatenate copy first
                staged = self._stage_fn(
                    batch[0].rows if len(batch) == 1
                    else [r.rows for r in batch])
            except Exception as e:
                self._fail_batch(batch, e, t_form=now)
                continue
            # blocks when stage_depth batches are already in flight —
            # H2D stays at most one batch ahead of compute, and the
            # backpressure propagates into the bounded pending queue
            self._staged_q.put((staged, batch, now))
        self._staged_q.put(None)

    # -- dispatcher: compute + resolve -----------------------------------

    def _dispatch_loop(self) -> None:
        while True:
            item = self._staged_q.get()
            if item is None:
                break
            staged, batch, t_form = item
            t0 = time.monotonic()
            try:
                out = self._dispatch_fn(staged)
            except Exception as e:
                self._fail_batch(batch, e, staged=staged,
                                 device_ms=(time.monotonic() - t0) * 1e3,
                                 t_form=t_form)
                continue
            device_ms = (time.monotonic() - t0) * 1e3
            t_done = time.monotonic()
            offset = 0
            # resolve every future before any telemetry: sink I/O
            # (json + locked file write) must not sit on the client
            # latency path
            for req in batch:
                res = out[offset:offset + req.n]
                offset += req.n
                req.future.set_result(res)
            for req in batch:
                with self._stats:
                    self.counters["requests"] += 1
                    self.counters["rows"] += req.n
                    self._lat.observe(t_done - req.t_submit)
                self._emit_request("ok", req,
                                   (t_form - req.t_submit) * 1e3,
                                   latency_ms=(t_done - req.t_submit)
                                   * 1e3)
            self._note_batch(batch, staged, t_form, device_ms, "ok")

    def _fail_batch(self, batch, exc, staged=None,
                    device_ms: float = 0.0,
                    t_form: Optional[float] = None) -> None:
        t_done = time.monotonic()
        for req in batch:
            with self._stats:
                self.counters["errors"] += 1
                self._lat.observe(t_done - req.t_submit)
            self._emit_request("error", req,
                               ((t_form or t_done) - req.t_submit) * 1e3,
                               latency_ms=(t_done - req.t_submit) * 1e3)
            _set_exception(req.future, exc)
        if t_form is not None:
            self._note_batch(batch, staged, t_form, device_ms, "error")

    # -- telemetry -------------------------------------------------------

    def _emit(self, kind: str, **fields) -> None:
        """Emit a serve record, never letting a sink failure (full
        disk, closed file) escape — a telemetry error must not kill a
        worker thread and hang every waiting client. SafeEmitter owns
        the warn-once latch (shared with the fleet frontend)."""
        self._safe_emit(kind, **fields)

    def _emit_request(self, status: str, req: _Request,
                      queue_ms: float, latency_ms: float = 0.0) -> None:
        self._emit("serve_request", status=status, rows=req.n,
                   queue_ms=queue_ms, latency_ms=latency_ms)

    def _note_batch(self, batch, staged, t_form: float,
                    device_ms: float, status: str) -> None:
        rows = sum(r.n for r in batch)
        bucket = getattr(staged, "bucket", rows)
        with self._stats:
            self.counters["batches"] += 1
            self.counters["batch_rows"] += rows
            self.counters["bucket_rows"] += bucket
            self.counters["pad_rows"] += bucket - rows
            nbatch = self.counters["batches"]
        oldest = min(r.t_submit for r in batch)
        self._emit(
            "serve_batch", batch=nbatch, status=status,
            rows=rows, requests=len(batch), bucket=bucket,
            pad_rows=bucket - rows,
            fill_rate=rows / float(self.max_batch),
            pad_fraction=(bucket - rows) / float(bucket),
            queue_ms=(t_form - oldest) * 1e3, device_ms=device_ms)

    # -- load introspection ----------------------------------------------

    def queue_rows(self) -> int:
        """Rows currently waiting in the coalescing queue — the load
        signal the fleet tier's ``/healthz`` exports for balancer
        routing and autoscale decisions (doc/serving.md "Horizontal
        fleet")."""
        with self._lock:
            return self._pending_rows

    def latency_percentile(self, q: float) -> float:
        """Request-latency percentile (ms) over the batcher's lifetime
        histogram — the ``p99_ms`` health signal."""
        with self._stats:
            return self._lat.percentile(q)

    def fill_stats(self) -> Dict[str, Any]:
        """Cumulative micro-batch economics (batches, rows, bucket
        rows, pad rows + derived fill/pad ratios) — exported through
        the fleet ``/healthz`` so the multi-replica bench can report
        pad fraction fleet-wide (doc/serving.md "Fleet data path")."""
        with self._stats:
            c = dict(self.counters)
        return {
            "batches": c["batches"],
            "batch_rows": c["batch_rows"],
            "bucket_rows": c["bucket_rows"],
            "pad_rows": c["pad_rows"],
            "fill_rate": c["batch_rows"]
            / float(max(1, c["batches"] * self.max_batch)),
            "pad_fraction": c["pad_rows"]
            / float(max(1, c["bucket_rows"])),
        }

    # -- shutdown --------------------------------------------------------

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> Dict[str, Any]:
        """Stop intake; with ``drain`` run every queued request first,
        otherwise fail them with ServeClosedError. Joins both workers
        and returns the summary (also emitted as ``serve_summary``)."""
        failed = []
        with self._lock:
            self._closed = True
            if not drain:
                while self._pending:
                    req = self._pending.popleft()
                    self._pending_rows -= req.n
                    failed.append(req)
            self._wake.notify_all()
        for req in failed:
            with self._stats:
                self.counters["errors"] += 1
            self._emit_request("closed", req, 0.0)
            _set_exception(req.future,
                           ServeClosedError("server shut down"))
        self._collector.join(timeout)
        self._dispatcher.join(timeout)
        return self.summary(emit=True)

    def summary(self, emit: bool = False) -> Dict[str, Any]:
        with self._stats:
            c = dict(self.counters)
            p50 = self._lat.percentile(0.50)
            p99 = self._lat.percentile(0.99)
        bucket_rows = max(1, c["bucket_rows"])
        batch_cap = max(1, c["batches"] * self.max_batch)
        out = {
            "requests": c["requests"], "rows": c["rows"],
            "batches": c["batches"], "rejected": c["rejected"],
            "timeouts": c["timeouts"], "errors": c["errors"],
            "latency_p50_ms": round(p50, 3),
            "latency_p99_ms": round(p99, 3),
            "fill_rate": c["batch_rows"] / float(batch_cap),
            "pad_fraction": c["pad_rows"] / float(bucket_rows),
            "wall_s": time.monotonic() - self._t0,
        }
        if self._extra_summary is not None:
            # engine-side counters (compile events, AOT hit counts)
            # ride in the same summary record
            out.update(self._extra_summary())
        if emit:
            self._emit("serve_summary", **out)
        return out

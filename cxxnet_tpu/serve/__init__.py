"""Dynamic-batching inference: snapshot -> frozen engine -> dispatcher.

The serving subsystem (doc/serving.md). Pieces:

- :mod:`~cxxnet_tpu.serve.bucketing` — the batch-size bucket ladder
  every padded dispatch shape comes from
- :mod:`~cxxnet_tpu.serve.engine` — frozen eval-mode engine with AOT
  executables per bucket (zero compile events after warmup)
- :mod:`~cxxnet_tpu.serve.batcher` — coalescing micro-batch dispatcher:
  bounded queue, reject-with-busy backpressure, per-request deadlines,
  exception propagation, graceful drain, pipelined H2D hand-off
- :mod:`~cxxnet_tpu.serve.server` — config-driven ``ServeSession`` and
  the closed-loop client drive behind ``task = serve`` and
  ``tools/serve_bench.py``

The fleet layer (``task = serve_fleet``, doc/serving.md):

- :mod:`~cxxnet_tpu.serve.router` — multi-model routing: N engines
  behind one front end, atomic hot-swap flip
- :mod:`~cxxnet_tpu.serve.quota` — per-tenant token-bucket quotas and
  typed over-quota shedding
- :mod:`~cxxnet_tpu.serve.swap` — checkpoint-driven zero-downtime
  hot-swap (verified-snapshot watcher, shadow warmup, flip + drain)
- :mod:`~cxxnet_tpu.serve.frontend` — the network front end: HTTP/JSON
  + length-prefixed binary protocols over one shared request core
"""

from .batcher import (DynamicBatcher, ServeBusyError, ServeClosedError,
                      ServeTimeoutError)
from .bucketing import (bucket_ladder, mesh_align, pad_to_bucket,
                        parse_buckets, pick_bucket)
from .engine import InferenceEngine, StagedBatch, build_engine
from .frontend import (BinaryClient, FailoverBinaryClient,
                       FailoverHttpClient, FleetConfig, FleetServer,
                       registry_endpoints)
from .quota import QuotaManager, TenantQuotaError, TokenBucket
from .router import ModelRouter, UnknownModelError
from .server import ServeConfig, ServeSession, run_closed_loop
from .swap import SnapshotWatcher, latest_verified

__all__ = [
    "DynamicBatcher", "ServeBusyError", "ServeClosedError",
    "ServeTimeoutError", "bucket_ladder", "mesh_align", "pad_to_bucket",
    "parse_buckets", "pick_bucket", "InferenceEngine", "StagedBatch",
    "build_engine", "ServeConfig", "ServeSession", "run_closed_loop",
    "BinaryClient", "FailoverBinaryClient", "FailoverHttpClient",
    "registry_endpoints", "FleetConfig", "FleetServer", "QuotaManager",
    "TenantQuotaError", "TokenBucket", "ModelRouter",
    "UnknownModelError", "SnapshotWatcher", "latest_verified",
]

"""Dynamic-batching inference: snapshot -> frozen engine -> dispatcher.

The serving subsystem (doc/serving.md). Pieces:

- :mod:`~cxxnet_tpu.serve.bucketing` — the batch-size bucket ladder
  every padded dispatch shape comes from
- :mod:`~cxxnet_tpu.serve.engine` — frozen eval-mode engine with AOT
  executables per bucket (zero compile events after warmup)
- :mod:`~cxxnet_tpu.serve.batcher` — coalescing micro-batch dispatcher:
  bounded queue, reject-with-busy backpressure, per-request deadlines,
  exception propagation, graceful drain, pipelined H2D hand-off
- :mod:`~cxxnet_tpu.serve.server` — config-driven ``ServeSession`` and
  the closed-loop client drive behind ``task = serve`` and
  ``tools/serve_bench.py``
"""

from .batcher import (DynamicBatcher, ServeBusyError, ServeClosedError,
                      ServeTimeoutError)
from .bucketing import (bucket_ladder, mesh_align, pad_to_bucket,
                        parse_buckets, pick_bucket)
from .engine import InferenceEngine, StagedBatch, build_engine
from .server import ServeConfig, ServeSession, run_closed_loop

__all__ = [
    "DynamicBatcher", "ServeBusyError", "ServeClosedError",
    "ServeTimeoutError", "bucket_ladder", "mesh_align", "pad_to_bucket",
    "parse_buckets", "pick_bucket", "InferenceEngine", "StagedBatch",
    "build_engine", "ServeConfig", "ServeSession", "run_closed_loop",
]

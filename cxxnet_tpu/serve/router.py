"""Multi-model routing: N serve engines behind one front end.

The :class:`ModelRouter` maps a model id to its live
:class:`~cxxnet_tpu.serve.server.ServeSession`. Every entry owns its
own engine (bucket ladder, AOT executables, dispatcher threads) and
its own drain lifecycle; the router is only the atomic name -> session
indirection the protocol layer resolves through, which is what makes
zero-downtime hot-swap possible: :meth:`swap` flips the entry under
the lock and hands the *old* session back to the caller, who drains it
(``close(drain=True)``) after the flip — requests already queued on
the old engine complete, new requests land on the new one.

The one race a flip cannot close — a request that resolved the old
session but had not yet entered its queue when the drain began — is
handled one layer up: the front end retries a
:class:`~cxxnet_tpu.serve.batcher.ServeClosedError` through a fresh
``resolve`` (see ``frontend.py``), so a swap is never observable as a
failed request.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ..artifact.registry import ResidencyBudgetError


class UnknownModelError(KeyError):
    """Request named a model id the router does not serve."""


def session_resident_bytes(session) -> int:
    """A session's device-resident bytes: the model's frozen serve
    weight tree + retained masters (buffer-deduplicated) plus — when
    the bundle sealed an embedding index — the device corpus matrix.
    0 when the engine runs without weight residency accounting (the
    index still counts: it is resident regardless)."""
    idx_bytes = int(getattr(session, "index_bytes", 0) or 0)
    try:
        res = session.engine.trainer.programs.residency
    except AttributeError:
        return idx_bytes
    weight = int(res.total_bytes) if res is not None else 0
    return weight + idx_bytes


class ModelEntry:
    """One routed model: the live session plus the provenance the
    hot-swap watcher compares against (snapshot counter + path) and
    its device-memory accounting."""

    __slots__ = ("model_id", "session", "counter", "path", "generation",
                 "resident_bytes")

    def __init__(self, model_id: str, session, counter: int, path: str,
                 generation: int = 0):
        self.model_id = model_id
        self.session = session
        self.counter = counter
        self.path = path
        self.generation = generation
        self.resident_bytes = session_resident_bytes(session)


class ModelRouter:
    """Thread-safe model-id -> session table with atomic swap.

    The first registered model is the default (requests that name no
    model id route there). ``close_all`` drains every entry — the
    front-end shutdown path.

    ``mem_budget_bytes`` (0 = unlimited) makes multi-model co-location
    memory-honest: a ``register`` or ``swap`` whose per-model resident
    weight bytes would push the fleet total over the budget raises the
    typed :class:`~cxxnet_tpu.artifact.registry.ResidencyBudgetError`
    — the table is untouched, so whatever was serving keeps serving
    (the hot-swap watcher treats it like any failed flip and discards
    the shadow session)."""

    def __init__(self, mem_budget_bytes: int = 0):
        self._lock = threading.Lock()
        self._models: Dict[str, ModelEntry] = {}
        self._order: List[str] = []
        self._closed = False
        self.mem_budget_bytes = int(mem_budget_bytes)

    def _check_budget(self, entry: ModelEntry,
                      replacing: Optional[str] = None) -> None:
        """Called under the lock: would installing ``entry`` (in place
        of ``replacing``) blow the budget?"""
        if not self.mem_budget_bytes:
            return
        total = entry.resident_bytes + sum(
            e.resident_bytes for m, e in self._models.items()
            if m != replacing)
        if total > self.mem_budget_bytes:
            raise ResidencyBudgetError(
                "loading model %r (%d resident bytes) would put the "
                "fleet at %d bytes, over serve_device_mem_budget (%d)"
                % (entry.model_id, entry.resident_bytes, total,
                   self.mem_budget_bytes))

    def resident_bytes_total(self) -> int:
        with self._lock:
            return sum(e.resident_bytes for e in self._models.values())

    # -- registration -----------------------------------------------------

    def register(self, model_id: str, session, counter: int = 0,
                 path: str = "") -> ModelEntry:
        with self._lock:
            if model_id in self._models:
                raise ValueError("model %r already registered"
                                 % model_id)
            entry = ModelEntry(model_id, session, counter, path)
            self._check_budget(entry)
            self._models[model_id] = entry
            self._order.append(model_id)
            return entry

    @property
    def default_id(self) -> Optional[str]:
        with self._lock:
            return self._order[0] if self._order else None

    def ids(self) -> List[str]:
        with self._lock:
            return list(self._order)

    # -- lookup -----------------------------------------------------------

    def resolve(self, model_id: str = "") -> ModelEntry:
        """The live entry for ``model_id`` ("" = the default model).
        Raises :class:`UnknownModelError` for names never registered
        (a *swapped* model keeps its name — the entry just points at
        the new session)."""
        with self._lock:
            if not model_id:
                if not self._order:
                    raise UnknownModelError("no models registered")
                model_id = self._order[0]
            entry = self._models.get(model_id)
            if entry is None:
                raise UnknownModelError(
                    "unknown model %r (serving: %s)"
                    % (model_id, ", ".join(self._order) or "none"))
            return entry

    def describe(self) -> List[Dict[str, Any]]:
        """Model table for the HTTP ``/v1/models`` endpoint."""
        with self._lock:
            return [{"model": e.model_id, "counter": e.counter,
                     "path": e.path, "generation": e.generation,
                     "device_mem_bytes": e.resident_bytes}
                    for e in (self._models[m] for m in self._order)]

    # -- hot swap ---------------------------------------------------------

    def swap(self, model_id: str, session, counter: int,
             path: str) -> ModelEntry:
        """Atomically point ``model_id`` at ``session`` and return the
        retired entry. The caller owns draining the old session AFTER
        this returns — flip first, drain second, so there is no window
        with no live engine."""
        with self._lock:
            if self._closed:
                # a watcher finishing a shadow build after close_all
                # must not install an engine nothing will ever drain
                raise RuntimeError(
                    "router is closed; refusing to swap model %r"
                    % model_id)
            old = self._models.get(model_id)
            if old is None:
                raise UnknownModelError(
                    "cannot swap unregistered model %r" % model_id)
            entry = ModelEntry(model_id, session, counter, path,
                               generation=old.generation + 1)
            # steady-state accounting: the retired entry's bytes free
            # once it drains, so the budget compares against the
            # post-swap set (the shadow-build window transiently holds
            # both — documented in doc/serving.md)
            self._check_budget(entry, replacing=model_id)
            self._models[model_id] = entry
            return old

    # -- shutdown ---------------------------------------------------------

    def close_all(self, drain: bool = True) -> Dict[str, Dict]:
        """Close every session (idempotent); returns per-model close
        summaries keyed by model id."""
        with self._lock:
            if self._closed:
                entries = []
            else:
                self._closed = True
                entries = [self._models[m] for m in self._order]
        out = {}
        for e in entries:
            out[e.model_id] = e.session.close(drain=drain)
        return out

"""Fleet front end: network protocols over the serve subsystem.

``FleetServer`` turns N :class:`~cxxnet_tpu.serve.server.ServeSession`
engines into one deployable service (``task = serve_fleet``,
doc/serving.md):

- **two protocols, one core** — an HTTP/JSON endpoint for
  debuggability (curl-able, self-describing errors) and a
  length-prefixed binary protocol for raw float rows (no JSON
  float-printing cost on the hot path). Both funnel into
  :meth:`FleetServer.handle`, so routing, quotas, shedding and
  telemetry behave identically.
- **multi-model routing** — requests name a model id; the
  :class:`~cxxnet_tpu.serve.router.ModelRouter` resolves it to the
  live engine (each with its own bucket ladder and drain lifecycle).
- **tenant quotas** — every request passes the
  :class:`~cxxnet_tpu.serve.quota.QuotaManager` *before* touching the
  shared dispatcher queue; an over-quota tenant is shed with a typed
  429-style reply (``over_quota``, Retry-After) instead of queueing
  into everyone's p99. Dispatcher backpressure
  (:class:`~cxxnet_tpu.serve.batcher.ServeBusyError`) and deadlines
  (``ServeTimeoutError``) map to ``busy`` (429) and ``timeout`` (504)
  the same way.
- **zero-downtime hot-swap** — a
  :class:`~cxxnet_tpu.serve.swap.SnapshotWatcher` per model polls its
  ``model_dir`` for newer *verified* snapshots, warms a shadow engine,
  flips the router entry, drains the old engine. The front end retries
  the one unclosable race (``ServeClosedError`` from a session that
  was flipped away mid-request) through a fresh resolve, so a swap
  never fails a request.

Every request emits a schema-validated ``serve_http`` record; quota
sheds additionally emit ``tenant_shed``; swaps emit ``hot_swap``.
"""

from __future__ import annotations

import http.client
import json
import os
import socket
import socketserver
import struct
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..monitor import SafeEmitter
from .batcher import (ServeBusyError, ServeClosedError,
                      ServeTimeoutError)
from .quota import QuotaManager, TenantQuotaError
from .router import ModelRouter, UnknownModelError
from .server import ServeSession
from .swap import SnapshotWatcher, counter_of, latest_verified

# -- binary protocol ------------------------------------------------------
#
# v1 (untagged, one round trip per in-flight request):
# Request:  MAGIC | u8 model_len | u8 tenant_len | u32 nrows |
#           u32 elems_per_row | f32 timeout_ms | model utf8 |
#           tenant utf8 | nrows*elems float32 LE rows
# Reply:    MAGIC | u8 status | u32 nrows | u32 elems_per_row |
#           payload: float32 LE rows (status 0) or
#           u32 msg_len + utf8 message (any other status)
#
# v2 (correlated, multiplexed): the same grammar under the CXN2 magic
# with a u32 correlation id after the magic on both frames. Replies
# carry the request's id and MAY arrive out of order, so one
# persistent connection pipelines many in-flight requests (the fleet
# balancer's ReplicaChannel, doc/serving.md "Fleet data path").
# Negotiation is per-frame and stateless: a v2 frame gets a v2 reply,
# an untagged v1 frame gets a v1 reply — old clients keep working
# unchanged. A v2 request with nrows == elems == model_len ==
# tenant_len == 0 is a PING: answered ok (0 rows) without touching
# the request core — the connect-time probe a v2 client uses to
# detect a v1-only server (which answers the unknown magic with a v1
# bad_request frame and drops the connection).

BIN_MAGIC = b"CXN1"
BIN_MAGIC_V2 = b"CXN2"
_REQ_HEADER = struct.Struct("<4sBBIIf")
_REP_HEADER = struct.Struct("<4sBII")
_REQ_HEADER_V2 = struct.Struct("<4sIBBIIf")
_REP_HEADER_V2 = struct.Struct("<4sIBII")
_MSG_LEN = struct.Struct("<I")

# hard sanity caps on a single binary frame: a corrupt length prefix
# must fail the frame, not allocate gigabytes
MAX_FRAME_ROWS = 1 << 20
MAX_FRAME_BYTES = 256 << 20

STATUS_OK = 0
STATUS_BUSY = 1
STATUS_OVER_QUOTA = 2
STATUS_TIMEOUT = 3
STATUS_UNKNOWN_MODEL = 4
STATUS_BAD_REQUEST = 5
STATUS_CLOSED = 6
STATUS_ERROR = 7

STATUS_NAMES = {
    STATUS_OK: "ok", STATUS_BUSY: "busy",
    STATUS_OVER_QUOTA: "over_quota", STATUS_TIMEOUT: "timeout",
    STATUS_UNKNOWN_MODEL: "unknown_model",
    STATUS_BAD_REQUEST: "bad_request", STATUS_CLOSED: "closed",
    STATUS_ERROR: "error",
}
STATUS_CODES = {v: k for k, v in STATUS_NAMES.items()}

# HTTP status per outcome: both shedding outcomes are 429 (the typed
# JSON body and Retry-After distinguish quota from backpressure),
# deadline expiry is the gateway-timeout class
HTTP_STATUS = {
    "ok": 200, "busy": 429, "over_quota": 429, "timeout": 504,
    "unknown_model": 404, "bad_request": 400, "closed": 503,
    "error": 500,
}

# served operations beyond plain prediction (doc/retrieval.md): a
# request names ``model#op[:k]`` — ``embed`` (the served node's
# vectors; identical dispatch to predict, named for intent), ``search``
# (rows are query VECTORS, top-k over the model's sealed index) and
# ``fsearch`` (rows are model INPUTS; embed -> search composed in one
# request on ONE resolved model entry — the fan_out=1 form of
# /v1/search). The suffix rides the existing model-id field on both
# protocols, so the binary wire needs no new frame grammar.
SERVE_OPS = ("embed", "search", "fsearch")


def parse_model_op(model_id: str) -> Tuple[str, str, Optional[int]]:
    """Split ``model#op[:k]`` -> (model, op, k). Plain ids pass
    through as (id, "", None); an unknown op or malformed k raises
    ValueError (-> bad_request)."""
    base, sep, op = model_id.partition("#")
    if not sep:
        return model_id, "", None
    op, ksep, kstr = op.partition(":")
    if op not in SERVE_OPS:
        raise ValueError("unknown serve op %r (one of %s)"
                         % (op, "/".join(SERVE_OPS)))
    k = None
    if ksep:
        k = int(kstr)                    # ValueError -> bad_request
        if k < 1:
            raise ValueError("search k must be >= 1, got %d" % k)
    return base, op, k


def pack_search_result(ids: np.ndarray, scores: np.ndarray
                       ) -> Tuple[np.ndarray, Dict[str, Any]]:
    """One wire form of a top-k answer for both protocols: the result
    rows are the (n, 2k) float32 block ``[ids | scores]`` (the binary
    reply ships it verbatim; doc ids are exact in float32 up to 2**24
    corpus rows — doc/retrieval.md) and the extra dict carries the
    JSON lists the HTTP handler answers with."""
    payload = np.concatenate(
        [ids.astype(np.float32), scores.astype(np.float32)], axis=1)
    extra = {"k": int(ids.shape[1]),
             "ids": ids.tolist(),  # cxxlint: disable=CXL003 -- host arrays already (post-D2H); JSON reply staging
             "scores": scores.tolist()}  # cxxlint: disable=CXL003 -- host arrays already (post-D2H); JSON reply staging
    return payload, extra


def pack_request(model: str, tenant: str, rows: np.ndarray,
                 timeout_ms: float = 0.0) -> bytes:
    """Encode one binary-protocol request frame."""
    rows = np.ascontiguousarray(rows, dtype="<f4")
    if rows.ndim == 1:
        rows = rows[None, :]
    flat = rows.reshape(rows.shape[0], -1)
    m, t = model.encode(), tenant.encode()
    if len(m) > 255 or len(t) > 255:
        raise ValueError("model/tenant ids are limited to 255 bytes")
    return (_REQ_HEADER.pack(BIN_MAGIC, len(m), len(t), flat.shape[0],
                             flat.shape[1], float(timeout_ms))
            + m + t + flat.tobytes())


def pack_reply(status: int, payload: np.ndarray = None,
               message: str = "") -> bytes:
    """Encode one binary-protocol reply frame."""
    if status == STATUS_OK:
        flat = np.ascontiguousarray(payload, dtype="<f4")
        flat = flat.reshape(flat.shape[0], -1)
        return (_REP_HEADER.pack(BIN_MAGIC, status, flat.shape[0],
                                 flat.shape[1]) + flat.tobytes())
    msg = message.encode()
    return (_REP_HEADER.pack(BIN_MAGIC, status, 0, 0)
            + _MSG_LEN.pack(len(msg)) + msg)


def pack_request_v2(corr: int, model: str, tenant: str,
                    rows: np.ndarray,
                    timeout_ms: float = 0.0) -> bytes:
    """Encode one protocol-v2 request frame (correlation-tagged)."""
    rows = np.ascontiguousarray(rows, dtype="<f4")
    if rows.ndim == 1:
        rows = rows[None, :]
    flat = rows.reshape(rows.shape[0], -1)
    m, t = model.encode(), tenant.encode()
    if len(m) > 255 or len(t) > 255:
        raise ValueError("model/tenant ids are limited to 255 bytes")
    return (_REQ_HEADER_V2.pack(BIN_MAGIC_V2, corr, len(m), len(t),
                                flat.shape[0], flat.shape[1],
                                float(timeout_ms))
            + m + t + flat.tobytes())


def pack_ping_v2(corr: int = 0) -> bytes:
    """The v2 PING frame (zero rows, zero ids): answered ok without
    touching the request core — the negotiation probe."""
    return _REQ_HEADER_V2.pack(BIN_MAGIC_V2, corr, 0, 0, 0, 0, 0.0)


def pack_reply_v2(corr: int, status: int, payload: np.ndarray = None,
                  message: str = "") -> bytes:
    """Encode one protocol-v2 reply frame. ``payload is None`` with
    an ok status encodes the zero-row pong."""
    if status == STATUS_OK:
        if payload is None:
            return _REP_HEADER_V2.pack(BIN_MAGIC_V2, corr, status,
                                       0, 0)
        flat = np.ascontiguousarray(payload, dtype="<f4")
        flat = flat.reshape(flat.shape[0], -1)
        return (_REP_HEADER_V2.pack(BIN_MAGIC_V2, corr, status,
                                    flat.shape[0], flat.shape[1])
                + flat.tobytes())
    msg = message.encode()
    return (_REP_HEADER_V2.pack(BIN_MAGIC_V2, corr, status, 0, 0)
            + _MSG_LEN.pack(len(msg)) + msg)


def _read_exact(rfile, n: int) -> Optional[bytes]:
    """Read exactly n bytes; None on clean EOF at a frame boundary."""
    buf = b""
    while len(buf) < n:
        chunk = rfile.read(n - len(buf))
        if not chunk:
            return None if not buf else buf  # torn frame signals below
        buf += chunk
    return buf


def _read_reply_payload(rfile, status: int, nrows: int,
                        elems: int) -> Tuple[str, Any]:
    """Read a reply frame's payload (shared by both protocol
    versions) -> (status_name, rows | message)."""
    name = STATUS_NAMES.get(status, "error")
    if status == STATUS_OK:
        payload = _read_exact(rfile, nrows * elems * 4)
        if payload is None or len(payload) < nrows * elems * 4:
            raise IOError("connection closed mid-payload")
        return name, np.frombuffer(payload, "<f4").reshape(nrows,
                                                           elems)
    raw = _read_exact(rfile, _MSG_LEN.size)
    if raw is None or len(raw) < _MSG_LEN.size:
        raise IOError("connection closed mid-reply")
    mlen = _MSG_LEN.unpack(raw)[0]
    msg = _read_exact(rfile, mlen) if mlen else b""
    return name, (msg or b"").decode(errors="replace")


def read_reply(rfile) -> Tuple[str, Any]:
    """Read one v1 reply frame -> (status_name, rows | message)."""
    hdr = _read_exact(rfile, _REP_HEADER.size)
    if hdr is None or len(hdr) < _REP_HEADER.size:
        raise IOError("connection closed mid-reply")
    magic, status, nrows, elems = _REP_HEADER.unpack(hdr)
    if magic != BIN_MAGIC:
        raise IOError("bad reply magic %r" % magic)
    return _read_reply_payload(rfile, status, nrows, elems)


def read_reply_tagged(rfile) -> Tuple[Optional[int], str, Any]:
    """Read one reply frame of EITHER protocol version ->
    (corr_id, status_name, rows | message); a v1 frame reports
    ``corr_id = None`` — how a v2 client's negotiation probe detects
    a v1-only server."""
    magic = _read_exact(rfile, 4)
    if magic is None or len(magic) < 4:
        raise IOError("connection closed mid-reply")
    if magic == BIN_MAGIC:
        rest = _read_exact(rfile, _REP_HEADER.size - 4)
        if rest is None or len(rest) < _REP_HEADER.size - 4:
            raise IOError("connection closed mid-reply")
        _, status, nrows, elems = _REP_HEADER.unpack(magic + rest)
        name, payload = _read_reply_payload(rfile, status, nrows,
                                            elems)
        return None, name, payload
    if magic != BIN_MAGIC_V2:
        raise IOError("bad reply magic %r" % magic)
    rest = _read_exact(rfile, _REP_HEADER_V2.size - 4)
    if rest is None or len(rest) < _REP_HEADER_V2.size - 4:
        raise IOError("connection closed mid-reply")
    _, corr, status, nrows, elems = _REP_HEADER_V2.unpack(magic + rest)
    name, payload = _read_reply_payload(rfile, status, nrows, elems)
    return corr, name, payload


class BinaryClient:
    """Minimal persistent-connection client for the binary protocol
    (the closed-loop drive in tests and ``tools/serve_bench.py``)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.sock = socket.create_connection((host, port),
                                             timeout=timeout)
        # request/reply framing over small segments: Nagle + delayed
        # ACK turns every exchange into a ~40ms stall
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._rfile = self.sock.makefile("rb")

    def predict(self, rows: np.ndarray, model: str = "",
                tenant: str = "",
                timeout_ms: float = 0.0) -> Tuple[str, Any]:
        self.sock.sendall(pack_request(model, tenant, rows,
                                       timeout_ms))
        return read_reply(self._rfile)

    def close(self) -> None:
        try:
            self._rfile.close()
        finally:
            self.sock.close()


def registry_endpoints(path: str, role: str = "balancer",
                       proto: str = "binary"
                       ) -> List[Tuple[str, int]]:
    """``(host, port)`` endpoints of one role from the fleet's
    endpoint-registry file (fleet/placement.py grammar) — how a
    failover client discovers the front doors without knowing the
    controller. Draining/disabled entries are skipped."""
    with open(path) as f:
        doc = json.load(f)
    key = "%s_port" % ("binary" if proto == "binary" else "http")
    out = []
    for e in sorted(dict(doc.get("endpoints", {})).values(),
                    key=lambda e: str(e.get("id", ""))):
        if e.get("role") != role or e.get("draining"):
            continue
        port = int(e.get(key, 0))
        if port > 0:
            out.append((str(e.get("host", "127.0.0.1")), port))
    return out


class FailoverBinaryClient:
    """A :class:`BinaryClient` over MULTIPLE endpoints — the client
    half of the sharded front tier's zero-drop contract.

    Connects to one door (rotating over the list until a connect
    succeeds); any transport failure mid-exchange (refused/reset
    connection, torn frame: the signature of a door dying) — or a
    graceful ``closed`` reply from a draining door — closes the
    connection, advances to the next door, and retries the SAME rows —
    ``predict`` is idempotent, so a SIGKILLed balancer costs a
    reconnect, never a failed request. Raises IOError only when every
    endpoint refused ``attempts`` times over."""

    def __init__(self, endpoints: Sequence[Tuple[str, int]],
                 timeout: float = 30.0, attempts: int = 0):
        if not endpoints:
            raise ValueError("failover client needs >= 1 endpoint")
        self.endpoints = [(h, int(p)) for h, p in endpoints]
        self.timeout = timeout
        # default: two passes over the doors — one transient failure
        # per door plus the reconnect that lands on a live one
        self.attempts = attempts or 2 * len(self.endpoints)
        self._i = 0
        self.sock: Optional[socket.socket] = None
        self._rfile = None
        self.failovers = 0

    @classmethod
    def from_registry(cls, path: str,
                      timeout: float = 30.0) -> "FailoverBinaryClient":
        return cls(registry_endpoints(path, "balancer", "binary"),
                   timeout=timeout)

    def _connect(self) -> None:
        last: Optional[BaseException] = None
        for _ in range(len(self.endpoints)):
            host, port = self.endpoints[self._i % len(self.endpoints)]
            try:
                self.sock = socket.create_connection(
                    (host, port), timeout=self.timeout)
                self.sock.setsockopt(socket.IPPROTO_TCP,
                                     socket.TCP_NODELAY, 1)
                self._rfile = self.sock.makefile("rb")
                return
            except OSError as e:
                last = e
                self.sock = None
                self._i += 1
        raise IOError("no balancer endpoint reachable "
                      "(last: %s)" % last)

    def _drop(self) -> None:
        if self._rfile is not None:
            try:
                self._rfile.close()
            except OSError:
                pass  # cxxlint: disable=CXL006 -- teardown of a dead socket on the failover path; nothing to do with a close error
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass  # cxxlint: disable=CXL006 -- teardown of a dead socket on the failover path; nothing to do with a close error
        self.sock, self._rfile = None, None
        self._i += 1           # next attempt tries the NEXT door
        self.failovers += 1

    def predict(self, rows: np.ndarray, model: str = "",
                tenant: str = "",
                timeout_ms: float = 0.0) -> Tuple[str, Any]:
        last: Optional[BaseException] = None
        for _ in range(self.attempts):
            try:
                if self.sock is None:
                    self._connect()
                self.sock.sendall(pack_request(model, tenant, rows,
                                               timeout_ms))
                status, result = read_reply(self._rfile)
                if status == "closed":
                    # a graceful goodbye: the door is draining away
                    # and did NOT process the rows — same retry
                    # contract as a dead socket
                    last = IOError("door draining: %s" % (result,))
                    self._drop()
                    continue
                return status, result
            except (OSError, ValueError) as e:
                # OSError: connect/send/recv died; ValueError: torn or
                # garbled frame — either way the exchange is void and
                # the idempotent rows retry on another door
                last = e
                self._drop()
        raise IOError("predict failed through every balancer "
                      "endpoint (last: %s)" % last)

    def close(self) -> None:
        if self.sock is not None:
            try:
                self._rfile.close()
            finally:
                self.sock.close()
        self.sock, self._rfile = None, None


class FailoverHttpClient:
    """HTTP/JSON twin of :class:`FailoverBinaryClient`: POST
    ``/v1/predict`` against a list of doors, retrying the idempotent
    body on the next door after any transport-level failure.
    ``predict`` returns ``(http_code, decoded_json_body)``."""

    def __init__(self, endpoints: Sequence[Tuple[str, int]],
                 timeout: float = 30.0, attempts: int = 0):
        if not endpoints:
            raise ValueError("failover client needs >= 1 endpoint")
        self.endpoints = [(h, int(p)) for h, p in endpoints]
        self.timeout = timeout
        self.attempts = attempts or 2 * len(self.endpoints)
        self._i = 0
        self._conn: Optional[http.client.HTTPConnection] = None
        self.failovers = 0

    @classmethod
    def from_registry(cls, path: str,
                      timeout: float = 30.0) -> "FailoverHttpClient":
        return cls(registry_endpoints(path, "balancer", "http"),
                   timeout=timeout)

    def _drop(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass  # cxxlint: disable=CXL006 -- teardown of a dead connection on the failover path; nothing to do with a close error
        self._conn = None
        self._i += 1
        self.failovers += 1

    def predict(self, model: str, tenant: str, rows,
                timeout_ms: float = 0.0) -> Tuple[int, Dict[str, Any]]:
        body = json.dumps({
            "model": model, "tenant": tenant,
            "rows": np.asarray(rows, dtype=np.float32).tolist(),
            **({"timeout_ms": timeout_ms} if timeout_ms else {})})
        last: Optional[BaseException] = None
        for _ in range(self.attempts):
            host, port = self.endpoints[self._i % len(self.endpoints)]
            try:
                if self._conn is None:
                    self._conn = http.client.HTTPConnection(
                        host, port, timeout=self.timeout)
                self._conn.request(
                    "POST", "/v1/predict", body,
                    {"Content-Type": "application/json"})
                resp = self._conn.getresponse()
                payload = json.loads(resp.read() or b"{}")
                if payload.get("error") == "closed":
                    # graceful drain reply: rows were NOT processed
                    last = IOError("door draining")
                    self._drop()
                    continue
                return resp.status, payload
            except (OSError, ValueError,
                    http.client.HTTPException) as e:
                last = e
                self._drop()
        raise IOError("predict failed through every balancer "
                      "endpoint (last: %s)" % last)

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
        self._conn = None


# -- fleet configuration --------------------------------------------------


class FleetConfig:
    """Parsed ``serve_fleet`` keys (doc/serving.md):

    - ``serve_models`` — list of ``id=source[|buckets]`` entries; the
      source is a model_dir to watch (newest verified snapshot) or an
      explicit snapshot file. Entries separate on ``,``, or on ``;``
      when any entry carries a ``|buckets`` override (bucket ladders
      are comma lists themselves: ``main=./m1;alt=./m2|1,8``).
      Default: one model ``default`` over ``model_in`` (if set) or
      ``model_dir``.
    - ``serve_http_port`` / ``serve_binary_port`` — listen ports
      (0 = ephemeral, -1 = protocol disabled).
    - ``serve_host`` — bind address (default 127.0.0.1; set 0.0.0.0
      to serve off-host).
    - ``serve_swap_poll_s`` — hot-swap watcher period (0 = no
      watchers).
    - ``serve_fleet_duration_s`` — CLI run time (0 = until
      SIGTERM/SIGINT).
    - ``serve_port_file`` — when set, ``start()`` writes a small JSON
      file (pid + resolved listen ports) there atomically; how a
      parent fleet controller learns the ephemeral ports of a replica
      it spawned (doc/serving.md "Horizontal fleet").
    """

    def __init__(self, cfg: Sequence):
        self.models: List[Tuple[str, str, str]] = []
        self.http_port = 0
        self.binary_port = 0
        self.host = "127.0.0.1"
        self.swap_poll_s = 2.0
        self.duration_s = 0.0
        self.mem_budget_mb = 0.0
        self.port_file = ""
        model_dir, model_in = "./models", ""
        for name, val in cfg:
            if name == "serve_models":
                self.models = self._parse_models(val)
            if name == "serve_http_port":
                self.http_port = int(val)
            if name == "serve_binary_port":
                self.binary_port = int(val)
            if name == "serve_host":
                self.host = val
            if name == "serve_swap_poll_s":
                self.swap_poll_s = float(val)
            if name == "serve_fleet_duration_s":
                self.duration_s = float(val)
            if name == "serve_device_mem_budget":
                self.mem_budget_mb = float(val)
            if name == "serve_port_file":
                self.port_file = val
            if name == "model_dir":
                model_dir = val
            if name == "model_in":
                model_in = val
        if not self.models:
            self.models = [("default", model_in or model_dir, "")]
        if self.http_port < 0 and self.binary_port < 0:
            raise ValueError(
                "serve_fleet with both protocols disabled serves "
                "nothing — enable serve_http_port or "
                "serve_binary_port")

    @staticmethod
    def _parse_models(spec: str) -> List[Tuple[str, str, str]]:
        # entries separate on ';' when any entry carries a bucket
        # override (bucket ladders are comma lists themselves:
        # ``main=./m1;alt=./m2|1,8``); a plain spec may use ','
        sep = ";" if (";" in spec or "|" in spec) else ","
        out = []
        for entry in spec.split(sep):
            entry = entry.strip()
            if not entry:
                continue
            mid, eq, src = entry.partition("=")
            if not eq or not mid or not src:
                raise ValueError(
                    "serve_models entry %r must be id=source[|buckets]"
                    % entry)
            src, _, buckets = src.partition("|")
            out.append((mid.strip(), src.strip(), buckets.strip()))
        ids = [m for m, _, _ in out]
        if len(set(ids)) != len(ids):
            raise ValueError("duplicate model id in serve_models: %r"
                             % spec)
        return out


# -- the fleet server -----------------------------------------------------


class FleetServer:
    """N routed engines + quotas + hot-swap behind two protocol
    listeners. Build from the same ordered config-pair stream as the
    rest of the system; ``start()`` binds the listeners (ephemeral
    ports resolve to ``http_port``/``binary_port`` attributes),
    ``close()`` stops watchers and listeners and drains every
    engine."""

    def __init__(self, cfg: Sequence, monitor=None):
        self.cfg = list(cfg)
        self.fleet_cfg = FleetConfig(self.cfg)
        self.quota = QuotaManager(self.cfg)
        # fleet-wide device-memory accounting: the router rejects a
        # register/swap whose resident weight bytes would blow the
        # budget (typed error, old model set keeps serving)
        self.router = ModelRouter(
            mem_budget_bytes=int(self.fleet_cfg.mem_budget_mb * 1e6))
        self._mon = monitor
        self._closing = False
        self._closed = False
        self._stats = threading.Lock()
        self._safe_emit = SafeEmitter(monitor,
                                      "cxxnet_tpu serve frontend")
        self.counters: Dict[str, int] = {
            name: 0 for name in STATUS_NAMES.values()}
        self.counters["requests"] = 0
        self._watchers: List[SnapshotWatcher] = []
        self._http_server = None
        self._binary_server = None
        self._threads: List[threading.Thread] = []
        self.http_port = -1
        self.binary_port = -1
        for model_id, src, buckets in self.fleet_cfg.models:
            counter, path, watch_dir = self._resolve_source(src)
            session = self.build_session(path, buckets)
            self.router.register(model_id, session, counter, path)
            if watch_dir and self.fleet_cfg.swap_poll_s > 0:
                self._watchers.append(SnapshotWatcher(
                    self.router, model_id, watch_dir,
                    builder=lambda p, b=buckets:
                        self.build_session(p, b),
                    poll_s=self.fleet_cfg.swap_poll_s,
                    monitor=monitor))

    @staticmethod
    def _resolve_source(src: str) -> Tuple[int, str, str]:
        """A model source is a snapshot file or sealed artifact bundle
        (PINNED: served as-is, no watcher — naming an exact artifact
        is a deliberate version pin) or a model_dir (serve the newest
        verified snapshot/bundle and hot-swap as newer ones commit).
        Returns (counter, snapshot_path, dir_to_watch) — watch dir ""
        means pinned."""
        from ..artifact.bundle import is_bundle
        from ..utils.stream import stream_exists
        if src.endswith(".npz") and stream_exists(src):
            return counter_of(src), src, ""
        if is_bundle(src):
            return counter_of(src), src, ""
        counter, path = latest_verified(src)
        if path is None:
            raise FileNotFoundError(
                "model source %r holds no verified snapshot" % src)
        return counter, path, src

    def build_session(self, path: str, buckets: str = "") -> \
            ServeSession:
        """Session factory shared by boot and the hot-swap shadow
        build: full warmup inside, per-model bucket override appended
        last so it wins over a global ``serve_buckets``."""
        cfg = self.cfg
        if buckets:
            cfg = cfg + [("serve_buckets", buckets)]
        return ServeSession(cfg, model_path=path, monitor=self._mon)

    # -- the one request path both protocols share -----------------------

    def handle(self, model_id: str, tenant: str, rows,
               protocol: str = "http",
               timeout_ms: Optional[float] = None
               ) -> Tuple[str, Any, Dict[str, Any]]:
        """Route one request: quota -> router -> dispatcher. Returns
        ``(status_name, result_rows | message, extra)`` — never
        raises, so a protocol handler cannot leak a stack trace to the
        wire."""
        t0 = time.monotonic()
        nrows = 0
        resolved = model_id
        try:
            base, op, k = parse_model_op(model_id)
            resolved = base
            entry = self.router.resolve(base)
            resolved = entry.model_id
            if op in ("search", "fsearch") \
                    and entry.session.retrieval is None:
                raise ValueError("model %r serves no embedding index"
                                 % resolved)
            if op == "search":
                arr = self._shape_queries(entry, rows)
            else:
                arr = self._shape_rows(entry, rows)
            nrows = arr.shape[0]
            try:
                self.quota.admit(tenant, nrows)
            except TenantQuotaError as e:
                self._emit("tenant_shed", tenant=tenant,
                           model=resolved, rows=nrows, rate=e.rate,
                           burst=e.burst,
                           retry_after_s=round(e.retry_after_s, 3))
                raise
            if op == "search":
                out, extra = pack_search_result(
                    *self._search_current(resolved, arr, k))
            elif op == "fsearch":
                out, extra = pack_search_result(
                    *self._fanout_with_retry(resolved, arr, k,
                                             timeout_ms))
            else:
                # "" and "embed" are the same dispatch: the served
                # node's per-row vectors through the batcher
                out = self._predict_with_retry(resolved, arr,
                                               timeout_ms)
                extra = {}
            status, result = "ok", out
        except TenantQuotaError as e:
            status, result = "over_quota", str(e)
            extra = {"retry_after_s": e.retry_after_s}
        except ServeBusyError as e:
            status, result, extra = "busy", str(e), {}
        except ServeTimeoutError as e:
            status, result, extra = "timeout", str(e), {}
        except ServeClosedError as e:
            status, result, extra = "closed", str(e), {}
        except UnknownModelError as e:
            status, result, extra = "unknown_model", str(e.args[0]), {}
        except (ValueError, TypeError) as e:
            status, result, extra = "bad_request", str(e), {}
        except Exception as e:       # an engine bug must answer, not hang
            status, result, extra = "error", str(e), {}
        self._record(protocol, status, resolved, tenant, nrows, t0)
        return status, result, extra

    def _shape_rows(self, entry, rows) -> np.ndarray:
        """Coerce client rows (flat or natural layout) to the served
        instance shape; mismatches bounce as bad_request."""
        arr = np.asarray(rows, dtype=np.float32)  # cxxlint: disable=CXL003 -- protocol admission: client rows arrive as host bytes/JSON; the binary path's <f4 frombuffer view passes through copy-free and there is no device value to keep resident
        inst = entry.session.engine._inst_shape()
        elems = int(np.prod(inst))
        if arr.ndim == 1 and arr.size == elems:
            arr = arr.reshape((1,) + inst)
        elif arr.ndim == 2 and arr.shape[1] == elems \
                and arr.shape[1:] != inst:
            arr = arr.reshape((arr.shape[0],) + inst)
        if arr.ndim != len(inst) + 1 or arr.shape[1:] != inst:
            raise ValueError(
                "rows of shape %r do not match the served instance "
                "shape %r (%d values per row)"
                % (tuple(arr.shape), inst, elems))
        return arr

    def _shape_queries(self, entry, rows) -> np.ndarray:
        """``#search`` rows are query VECTORS in the index's embedding
        space (not model inputs): coerce to (n, dim) against the
        served index; mismatches bounce as bad_request."""
        r = entry.session.retrieval
        arr = np.asarray(rows, dtype=np.float32)  # cxxlint: disable=CXL003 -- protocol admission: query vectors arrive as host bytes/JSON
        dim = r.index.dim
        if arr.ndim == 1 and arr.size == dim:
            arr = arr.reshape(1, dim)
        if arr.ndim != 2 or arr.shape[1] != dim:
            raise ValueError(
                "queries of shape %r do not match the index embedding "
                "dim %d" % (tuple(arr.shape), dim))
        return arr

    def handle_async(self, model_id: str, tenant: str, rows,
                     protocol: str = "binary",
                     timeout_ms: Optional[float] = None,
                     done=None) -> None:
        """Non-blocking twin of :meth:`handle` — the out-of-order
        reply path of the v2 binary protocol (doc/serving.md "Fleet
        data path"). Admission (routing, shape, quota) runs inline on
        the caller's thread; the dispatch rides the batcher's Future.
        ``done(status, result, extra)`` fires exactly once — inline
        for admission failures, from a serve worker thread otherwise
        — and, like ``handle``, this never raises."""
        if "#" in model_id:
            # retrieval ops (``model#op[:k]``) answer through the
            # synchronous core: search dispatches outside the batcher
            # and fsearch must hold ONE resolved entry across both
            # legs (the no-torn-pair guarantee), so neither rides a
            # batcher Future. handle() records the request itself, so
            # ``done`` fires directly — the one v2 tradeoff is that
            # these replies come in handler-thread completion order.
            status, result, extra = self.handle(
                model_id, tenant, rows, protocol=protocol,
                timeout_ms=timeout_ms)
            done(status, result, extra)
            return
        t0 = time.monotonic()
        state = {"nrows": 0, "model": model_id}

        def finish(status, result, extra):
            self._record(protocol, status, state["model"], tenant,
                         state["nrows"], t0)
            done(status, result, extra)

        try:
            entry = self.router.resolve(model_id)
            state["model"] = entry.model_id
            arr = self._shape_rows(entry, rows)
            state["nrows"] = arr.shape[0]
            try:
                self.quota.admit(tenant, state["nrows"])
            except TenantQuotaError as e:
                self._emit("tenant_shed", tenant=tenant,
                           model=state["model"], rows=state["nrows"],
                           rate=e.rate, burst=e.burst,
                           retry_after_s=round(e.retry_after_s, 3))
                raise
        except TenantQuotaError as e:
            finish("over_quota", str(e),
                   {"retry_after_s": e.retry_after_s})
            return
        except UnknownModelError as e:
            finish("unknown_model", str(e.args[0]), {})
            return
        except (ValueError, TypeError) as e:
            finish("bad_request", str(e), {})
            return
        except Exception as e:   # an admission bug must answer, not hang
            finish("error", str(e), {})
            return
        # a super-batch wider than one dispatch (the balancer's
        # coalesced forwards) splits into max_batch chunks and
        # reassembles — the dispatcher re-coalesces chunks onto the
        # bucket ladder, so an oversized request costs ceil(n/mb)
        # submits, not a bad_request bounce
        mb = entry.session.engine.max_batch
        if state["nrows"] > mb:
            self._dispatch_chunked(state["model"], arr, mb,
                                   timeout_ms, finish)
        else:
            self._dispatch_async(state["model"], arr, timeout_ms,
                                 finish, attempts=8)

    def _dispatch_chunked(self, model_id: str, arr: np.ndarray,
                          max_batch: int,
                          timeout_ms: Optional[float],
                          finish) -> None:
        """Fan an oversized row array out as max_batch-sized chunks
        and call ``finish`` once with the reassembled rows (or the
        first non-ok status)."""
        chunks = [arr[i:i + max_batch]
                  for i in range(0, arr.shape[0], max_batch)]
        results: List[Any] = [None] * len(chunks)
        state = {"pending": len(chunks), "failed": None}
        lock = threading.Lock()

        def chunk_finish(idx):
            def _finish(status, result, extra):
                with lock:
                    if status == "ok":
                        results[idx] = result
                    elif state["failed"] is None:
                        state["failed"] = (status, result, extra)
                    state["pending"] -= 1
                    last = state["pending"] == 0
                if not last:
                    return
                if state["failed"] is not None:
                    finish(*state["failed"])
                else:
                    finish("ok", np.concatenate(
                        [np.asarray(r) for r in results]), {})
            return _finish

        for i, chunk in enumerate(chunks):
            self._dispatch_async(model_id, chunk, timeout_ms,
                                 chunk_finish(i), attempts=8)

    def _dispatch_async(self, model_id: str, arr: np.ndarray,
                        timeout_ms: Optional[float], finish,
                        attempts: int) -> None:
        """Submit through the CURRENT session and chain ``finish``
        onto the batcher Future; the hot-swap ``ServeClosedError``
        race retries through a fresh resolve exactly like
        ``_predict_with_retry`` (the 1 ms settle runs on the retiring
        session's worker, off the request path)."""
        try:
            entry = self.router.resolve(model_id)
            fut = entry.session.submit(arr, timeout_ms)
        except ServeClosedError as e:
            if not self._closing and attempts > 1:
                time.sleep(0.001)   # let the flip commit, then re-resolve
                self._dispatch_async(model_id, arr, timeout_ms,
                                     finish, attempts - 1)
            else:
                finish("closed", str(e), {})
            return
        except ServeBusyError as e:
            finish("busy", str(e), {})
            return
        except ServeTimeoutError as e:
            finish("timeout", str(e), {})
            return
        except (ValueError, TypeError) as e:
            finish("bad_request", str(e), {})
            return
        except Exception as e:
            finish("error", str(e), {})
            return

        def _done(f):
            exc = f.exception()
            if exc is None:
                finish("ok", f.result(), {})
            elif isinstance(exc, ServeClosedError) \
                    and not self._closing and attempts > 1:
                time.sleep(0.001)
                self._dispatch_async(model_id, arr, timeout_ms,
                                     finish, attempts - 1)
            elif isinstance(exc, ServeBusyError):
                finish("busy", str(exc), {})
            elif isinstance(exc, ServeTimeoutError):
                finish("timeout", str(exc), {})
            elif isinstance(exc, ServeClosedError):
                finish("closed", str(exc), {})
            else:
                finish("error", str(exc), {})

        fut.add_done_callback(_done)

    def _predict_with_retry(self, model_id: str, arr: np.ndarray,
                            timeout_ms: Optional[float]) -> np.ndarray:
        """Dispatch through the CURRENT session for ``model_id``; a
        ``ServeClosedError`` during a hot-swap window (the request
        resolved the old session right as it began draining) retries
        through a fresh resolve — the new engine is already routed, so
        in-flight requests never fail during a swap."""
        for _ in range(8):
            entry = self.router.resolve(model_id)
            try:
                return entry.session.predict(arr, timeout_ms)
            except ServeClosedError:
                if self._closing:
                    raise
                time.sleep(0.001)   # let the flip commit, then re-resolve
        raise ServeClosedError(
            "model %r kept draining across retries" % model_id)

    def _search_current(self, model_id: str, arr: np.ndarray,
                        k: Optional[int]
                        ) -> Tuple[np.ndarray, np.ndarray]:
        """Top-k over the CURRENT entry's index. The router swaps
        model and index as one entry, so one resolve is the whole
        consistency story; the retrieval engine dispatches outside the
        batcher and never raises ServeClosedError (its programs live
        in the session's own registry, retired with it only after the
        drain)."""
        entry = self.router.resolve(model_id)
        r = entry.session.retrieval
        if r is None:        # raced a swap to an index-less bundle
            raise ValueError("model %r serves no embedding index"
                             % model_id)
        return r.search(arr, k=k)

    def _fanout_with_retry(self, model_id: str, arr: np.ndarray,
                           k: Optional[int],
                           timeout_ms: Optional[float]
                           ) -> Tuple[np.ndarray, np.ndarray]:
        """``fan_out=1``: embed then search composed in ONE request on
        ONE resolved entry — both legs run against the same session,
        so a mid-flight hot-swap can never pair the new model with the
        old index (or vice versa). The embed leg rides the batcher
        (coalesced with plain predict traffic); a hot-swap
        ServeClosedError retries the WHOLE composition through a fresh
        resolve, exactly like :meth:`_predict_with_retry`."""
        for _ in range(8):
            entry = self.router.resolve(model_id)
            r = entry.session.retrieval
            if r is None:
                raise ValueError("model %r serves no embedding index"
                                 % model_id)
            try:
                vecs = entry.session.predict(arr, timeout_ms)
            except ServeClosedError:
                if self._closing:
                    raise
                time.sleep(0.001)
                continue
            vecs = np.asarray(vecs, dtype=np.float32)  # cxxlint: disable=CXL003 -- batcher results are already host rows
            return r.search(vecs.reshape(vecs.shape[0], -1), k=k)
        raise ServeClosedError(
            "model %r kept draining across retries" % model_id)

    # -- telemetry / accounting -------------------------------------------

    def _emit(self, kind: str, **fields) -> None:
        # telemetry failure must not fail requests; SafeEmitter owns
        # the warn-once latch (shared with DynamicBatcher)
        self._safe_emit(kind, **fields)

    def _record(self, protocol: str, status: str, model: str,
                tenant: str, rows: int, t0: float) -> None:
        with self._stats:
            self.counters["requests"] += 1
            self.counters[status] = self.counters.get(status, 0) + 1
        self._emit("serve_http", protocol=protocol, status=status,
                   model=model, tenant=tenant, rows=rows,
                   latency_ms=(time.monotonic() - t0) * 1e3)

    # runtime-fingerprint hashes are constant per (process, mesh
    # shape): memoize so the introspection endpoints operators poll
    # don't re-walk jax.devices() per model per request
    _fp_sha_cache: Dict[tuple, str] = {}

    @classmethod
    def _fingerprint_sha(cls, mesh) -> str:
        from ..artifact.bundle import (fingerprint_sha,
                                       runtime_fingerprint)
        key = tuple(sorted(dict(mesh.shape).items())) \
            if mesh is not None else ()
        sha = cls._fp_sha_cache.get(key)
        if sha is None:
            sha = fingerprint_sha(runtime_fingerprint(mesh))
            cls._fp_sha_cache[key] = sha
        return sha

    def describe(self) -> List[Dict[str, Any]]:
        """Model table with the client-facing dispatch contract."""
        from ..artifact.bundle import is_bundle
        out = []
        for e in (self.router.resolve(m) for m in self.router.ids()):
            inst = e.session.engine._inst_shape()
            out.append({
                "model": e.model_id, "counter": e.counter,
                "path": e.path, "generation": e.generation,
                "max_batch": e.session.engine.max_batch,
                "row_elems": int(np.prod(inst)),
                "instance_shape": list(inst),
                "buckets": list(e.session.engine.buckets),
                # per-model device-memory accounting (doc/serving.md
                # "Device memory accounting")
                "device_mem_bytes": e.resident_bytes,
                # version identity (doc/serving.md "Horizontal
                # fleet"): which bundle/snapshot counter this engine
                # was booted from, whether the source was a sealed
                # bundle, and the runtime-fingerprint hash its
                # executables are valid against — what the canary
                # comparator and operators key per-version telemetry
                # on
                "bundle": bool(is_bundle(e.path)),
                "fingerprint_sha256": self._fingerprint_sha(
                    e.session.engine.trainer.mesh),
            })
            r = e.session.retrieval
            if r is not None:
                # the search contract clients compose against
                # (doc/retrieval.md): what /v1/search accepts and what
                # k it answers by default
                out[-1]["index"] = r.describe()
        return out

    def health_snapshot(self) -> Dict[str, Any]:
        """Load-aware health for ``GET /healthz`` — the signals the
        fleet balancer routes on and the autoscaler differentiates
        between polls (doc/serving.md "Horizontal fleet"): cumulative
        request/shed/error counters, current queued rows, lifetime
        p99, resident device bytes, and per-model version identity +
        compile accounting."""
        with self._stats:
            c = dict(self.counters)
        shed = c.get("busy", 0) + c.get("over_quota", 0)
        models = []
        queue_rows = 0
        p99 = 0.0
        for e in (self.router.resolve(m) for m in self.router.ids()):
            batcher = e.session.batcher
            # read each signal ONCE so the per-model rows always sum/
            # max to the aggregates (and each poll takes the batcher
            # locks once per model, not twice)
            m_queue = batcher.queue_rows()
            m_p99 = batcher.latency_percentile(0.99)
            queue_rows += m_queue
            p99 = max(p99, m_p99)
            snap = e.session.engine.counters_snapshot()
            row = {
                "model": e.model_id, "counter": e.counter,
                "generation": e.generation,
                "max_batch": e.session.engine.max_batch,
                "queue_rows": m_queue,
                "p99_ms": round(m_p99, 3),
                "compile_events": snap["compile_events"],
                "aot_hits": snap["aot_hits"],
            }
            r = e.session.retrieval
            if r is not None:
                # search has its own compile books: the zero-compile
                # guarantee covers predict AND search dispatch
                rsnap = r.counters_snapshot()
                row["search_compile_events"] = rsnap["compile_events"]
                row["search_aot_hits"] = rsnap["aot_hits"]
            # cumulative batch economics (fill/pad): what the fleet
            # bench aggregates across replicas (doc/serving.md "Fleet
            # data path")
            row.update(batcher.fill_stats())
            models.append(row)
        return {
            "ok": True, "pid": os.getpid(),
            "models": self.router.ids(),
            "requests": c["requests"], "shed": shed,
            "errors": c.get("error", 0) + c.get("closed", 0),
            "queue_rows": queue_rows,
            "p99_ms": round(p99, 3),
            "resident_bytes": self.router.resident_bytes_total(),
            "model_health": models,
        }

    # -- listeners --------------------------------------------------------

    def start(self) -> None:
        c = self.fleet_cfg
        if c.http_port >= 0:
            self._http_server = _FleetHTTPServer(
                (c.host, c.http_port), _HttpHandler, self)
            self.http_port = self._http_server.server_address[1]
            t = threading.Thread(
                target=self._http_server.serve_forever,
                name="serve-http", daemon=True)
            t.start()
            self._threads.append(t)
        if c.binary_port >= 0:
            self._binary_server = _FleetBinaryServer(
                (c.host, c.binary_port), _BinaryHandler, self)
            self.binary_port = \
                self._binary_server.server_address[1]
            t = threading.Thread(
                target=self._binary_server.serve_forever,
                name="serve-binary", daemon=True)
            t.start()
            self._threads.append(t)
        for w in self._watchers:
            w.start()
        if c.port_file:
            self._write_port_file(c.port_file)

    def notify_watchers(self) -> None:
        """Kick every hot-swap watcher for an immediate poll — the
        in-process exporter's post-commit hook (the continual loop
        calls this right after sealing a generation bundle so the flip
        does not wait out ``serve_swap_poll_s``; doc/continual.md)."""
        for w in self._watchers:
            w.notify()

    def _write_port_file(self, path: str) -> None:
        """Atomically publish the resolved listen ports (tmp +
        rename): a fleet controller polling for this file must never
        read a torn write."""
        payload = json.dumps({"pid": os.getpid(),
                              "http_port": self.http_port,
                              "binary_port": self.binary_port})
        d = os.path.dirname(os.path.abspath(path))
        if d and not os.path.isdir(d):
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, path)

    def close(self, drain: bool = True) -> Dict[str, Any]:
        """Stop watchers, stop intake (listeners), drain every
        engine. Idempotent; returns the fleet summary."""
        if self._closed:
            return self._summary({})
        self._closed = True
        self._closing = True
        for w in self._watchers:
            w.close()
        for srv in (self._http_server, self._binary_server):
            if srv is not None:
                srv.shutdown()
                srv.server_close()
        for t in self._threads:
            t.join(timeout=30)
        summaries = self.router.close_all(drain=drain)
        return self._summary(summaries)

    def _summary(self, per_model: Dict[str, Dict]) -> Dict[str, Any]:
        with self._stats:
            c = dict(self.counters)
        return {"requests": c, "models": per_model,
                "quota": self.quota.snapshot(),
                "swaps": sum(w.swaps for w in self._watchers)}


# -- HTTP protocol --------------------------------------------------------


class _FleetHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, handler, fleet: FleetServer):
        self.fleet = fleet
        super().__init__(addr, handler)


class _HttpHandler(BaseHTTPRequestHandler):
    server_version = "cxxnet-serve"
    protocol_version = "HTTP/1.1"

    def _send_json(self, code: int, obj: Dict[str, Any],
                   headers: Dict[str, str] = ()) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in dict(headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        fleet = self.server.fleet
        if self.path == "/healthz":
            self._send_json(200, fleet.health_snapshot())
        elif self.path == "/v1/models":
            self._send_json(200, {"models": fleet.describe()})
        else:
            self._send_json(404, {"error": "not_found",
                                  "message": "unknown path %r"
                                  % self.path})

    def do_POST(self):
        fleet = self.server.fleet
        if self.path not in ("/v1/predict", "/v1/embed",
                             "/v1/search"):
            self._send_json(404, {"error": "not_found",
                                  "message": "POST /v1/predict, "
                                  "/v1/embed or /v1/search"})
            return
        t0 = time.monotonic()
        try:
            length = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(length) or b"{}")
            model = str(req.get("model", ""))
            tenant = str(req.get("tenant", ""))
            timeout_ms = req.get("timeout_ms")
            rows = req["rows"]
            # the endpoints are sugar over the op-suffix grammar the
            # shared core (and the binary protocol) speak natively
            op_model = model
            if self.path == "/v1/embed":
                op_model = model + "#embed"
            elif self.path == "/v1/search":
                op = "fsearch" if int(req.get("fan_out", 0) or 0) \
                    else "search"
                k = req.get("k")
                op_model = model + "#" + op + \
                    (":%d" % int(k) if k is not None else "")
        except (ValueError, KeyError, TypeError) as e:
            # malformed body: never reached the shared core, so the
            # request is recorded here for the stream's completeness
            fleet._record("http", "bad_request", "", "", 0, t0)
            self._send_json(400, {"error": "bad_request",
                                  "message": "body must be JSON with "
                                  "'rows': %s" % e})
            return
        status, result, extra = fleet.handle(
            op_model, tenant, rows, protocol="http",
            timeout_ms=timeout_ms)
        code = HTTP_STATUS[status]
        if status == "ok" and "ids" in extra:
            self._send_json(code, {
                "model": model or fleet.router.default_id,
                "rows": len(extra["ids"]), "k": extra["k"],
                "ids": extra["ids"], "scores": extra["scores"]})
            return
        if status == "ok":
            flat = np.asarray(result)
            self._send_json(code, {
                "model": model or fleet.router.default_id,
                "rows": int(flat.shape[0]),
                "result": flat.reshape(flat.shape[0], -1).tolist()})
            return
        headers = {}
        if status in ("busy", "over_quota"):
            headers["Retry-After"] = "%d" % max(
                1, int(extra.get("retry_after_s", 1) + 0.999))
        self._send_json(code, dict(
            {"error": status, "message": result}, **extra),
            headers=headers)

    def log_message(self, fmt, *args):   # stdout parity: no access log
        pass


# -- binary protocol ------------------------------------------------------


class _FleetBinaryServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True

    def process_request(self, request, client_address):
        # the reply side writes header and payload as separate small
        # segments; without TCP_NODELAY, Nagle holds the second one
        # for the peer's delayed ACK (~40ms per exchange)
        request.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        super().process_request(request, client_address)

    def __init__(self, addr, handler, fleet: FleetServer):
        self.fleet = fleet
        super().__init__(addr, handler)


class _V2ConnState:
    """Out-of-order reply half of one v2 binary connection:
    completion callbacks frame (corr, status, result) straight onto
    the socket in COMPLETION order, serialized by a write lock — a
    slow request never blocks the replies behind it (no head-of-line
    blocking), and a completed reply reaches the wire with no thread
    hop (a dedicated reply thread measured as a p99 convoy under GIL
    pressure: every reply of the connection serialized behind one
    thread's scheduling). The write into the kernel socket buffer is
    microseconds for these frames; ``finish()`` holds teardown until
    the in-flight requests have answered."""

    def __init__(self, wfile, wlock):
        self._wfile = wfile
        # the CONNECTION's write lock, shared with the handler's v1
        # reply writes: per-frame negotiation allows v1 and v2 frames
        # interleaved on one connection, and a v1 reply on the handler
        # thread must not interleave bytes with a concurrent v2
        # completion write
        self._wlock = wlock
        self._lock = threading.Lock()
        self._pending = 0
        self._drained = threading.Condition(self._lock)

    def begin(self) -> None:
        with self._lock:
            self._pending += 1

    def reply(self, corr: int, status: str, result) -> None:
        """Immediate reply (pings, inline admission failures answered
        through complete() instead — this one does not pair with a
        begin())."""
        self._write(corr, status, result)

    def complete(self, corr: int, status: str, result) -> None:
        self._write(corr, status, result)
        with self._lock:
            self._pending -= 1
            if self._pending == 0:
                self._drained.notify_all()

    def finish(self) -> None:
        """Read loop done (EOF/torn frame): wait for the in-flight
        requests to answer before the connection tears down."""
        with self._lock:
            deadline = time.monotonic() + 60
            while self._pending > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._drained.wait(remaining)

    def _write(self, corr: int, status: str, result) -> None:
        try:
            if status == "ok":
                frame = pack_reply_v2(corr, STATUS_OK, payload=result)
            else:
                frame = pack_reply_v2(corr, STATUS_CODES[status],
                                      message=str(result))
            with self._wlock:
                self._wfile.write(frame)
        except (OSError, ValueError):
            # client went away mid-stream: there is no one to answer,
            # but the in-flight accounting must still drain
            pass  # cxxlint: disable=CXL006 -- the reply has no recipient; the caller's complete() keeps shutdown bounded


class _BinaryHandler(socketserver.StreamRequestHandler):
    """Persistent connection, both protocol versions per frame: an
    untagged v1 frame gets the classic one-in-one-out round trip; a
    correlation-tagged v2 frame is dispatched asynchronously and its
    reply may overtake slower neighbors (out-of-order, pipelined). A
    malformed frame answers bad_request and drops the connection (a
    desynced length-prefixed stream cannot be re-synchronized)."""

    def handle(self):
        fleet = self.server.fleet
        self._v2 = None
        # one write lock per connection: v1 replies (handler thread)
        # and v2 completion writes (worker threads) share the socket
        self._wlock = threading.Lock()
        try:
            while True:
                magic = _read_exact(self.rfile, 4)
                if magic is None or len(magic) < 4:
                    return                    # EOF (torn magic: drop)
                if magic == BIN_MAGIC:
                    if not self._handle_v1(fleet, magic):
                        return
                elif magic == BIN_MAGIC_V2:
                    if not self._handle_v2(fleet, magic):
                        return
                else:
                    self._write_v1(pack_reply(
                        STATUS_BAD_REQUEST,
                        message="bad frame magic %r" % magic))
                    return
        finally:
            if self._v2 is not None:
                self._v2.finish()

    def _write_v1(self, frame: bytes) -> None:
        with self._wlock:
            self.wfile.write(frame)

    def _read_frame(self, magic: bytes):
        """Read one request frame after its magic; returns
        (corr, model, tenant, rows, timeout_ms) or an error string,
        or None on a torn stream (drop silently)."""
        v2 = magic == BIN_MAGIC_V2
        header = _REQ_HEADER_V2 if v2 else _REQ_HEADER
        rest = _read_exact(self.rfile, header.size - 4)
        if rest is None or len(rest) < header.size - 4:
            return None
        if v2:
            _, corr, mlen, tlen, nrows, elems, timeout_ms = \
                header.unpack(magic + rest)
        else:
            corr = None
            _, mlen, tlen, nrows, elems, timeout_ms = \
                header.unpack(magic + rest)
        if nrows > MAX_FRAME_ROWS \
                or nrows * max(1, elems) * 4 > MAX_FRAME_BYTES:
            return "bad frame header (%d x %d)" % (nrows, elems)
        if v2 and nrows == 0 and elems == 0 and mlen == 0 \
                and tlen == 0:
            return ("ping", corr)
        body = _read_exact(self.rfile,
                           mlen + tlen + nrows * elems * 4)
        if body is None or len(body) < mlen + tlen + nrows * elems * 4:
            return None
        model = body[:mlen].decode(errors="replace")
        tenant = body[mlen:mlen + tlen].decode(errors="replace")
        # zero-copy ingress: the frame's row bytes become a read-only
        # float32 VIEW (frombuffer at an offset — a bytes slice would
        # copy the whole payload) the engine's staging ring copies
        # from exactly once (client bytes -> H2D source)
        rows = np.frombuffer(body, "<f4",
                             offset=mlen + tlen).reshape(nrows,
                                                         elems) \
            if nrows else np.zeros((0, max(1, elems)), np.float32)
        return corr, model, tenant, rows, timeout_ms

    def _handle_v1(self, fleet, magic: bytes) -> bool:
        frame = self._read_frame(magic)
        if frame is None:
            return False
        if isinstance(frame, str):   # pings are v2-only
            self._write_v1(pack_reply(STATUS_BAD_REQUEST,
                                      message=frame))
            return False
        _, model, tenant, rows, timeout_ms = frame
        status, result, _ = fleet.handle(
            model, tenant, rows, protocol="binary",
            timeout_ms=timeout_ms if timeout_ms > 0 else None)
        if status == "ok":
            self._write_v1(pack_reply(STATUS_OK, payload=result))
        else:
            self._write_v1(pack_reply(STATUS_CODES[status],
                                      message=str(result)))
        return True

    def _handle_v2(self, fleet, magic: bytes) -> bool:
        frame = self._read_frame(magic)
        if frame is None:
            return False
        if self._v2 is None:
            self._v2 = _V2ConnState(self.wfile, self._wlock)
        if isinstance(frame, str):
            self._v2.reply(0, "bad_request", frame)
            return False
        if frame[0] == "ping":
            # pong without touching the core (the negotiation probe,
            # and the deterministic out-of-order witness in tests)
            self._v2.reply(frame[1], "ok", None)
            return True
        corr, model, tenant, rows, timeout_ms = frame
        st = self._v2
        st.begin()
        if hasattr(fleet, "handle_async"):
            fleet.handle_async(
                model, tenant, rows, protocol="binary",
                timeout_ms=timeout_ms if timeout_ms > 0 else None,
                done=lambda s, r, e, c=corr: st.complete(c, s, r))
        else:
            # a core without an async surface (the balancer) answers
            # v2 frames in order — correlation ids still correct
            status, result, _ = fleet.handle(
                model, tenant, rows, protocol="binary",
                timeout_ms=timeout_ms if timeout_ms > 0 else None)
            st.complete(corr, status, result)
        return True

"""Config-driven serve session: snapshot -> engine -> batcher.

``ServeSession`` is the surface both the ``task = serve`` CLI entry and
library embedders use: it loads a model into a frozen
:class:`~cxxnet_tpu.serve.engine.InferenceEngine` (bucket-aligned mesh,
AOT warmup), fronts it with a
:class:`~cxxnet_tpu.serve.batcher.DynamicBatcher`, and exposes
``submit`` / ``predict`` / ``close``. All knobs come from the same
``key = value`` config grammar as the rest of the system:

- ``serve_buckets`` — ``auto`` (1/2/4/.../max_batch ladder) or an
  explicit comma list like ``1,8,32``
- ``serve_max_batch`` — micro-batch row cap (default: ``batch_size``)
- ``serve_max_delay_ms`` — batch-close deadline (default 2 ms)
- ``serve_queue_rows`` — backpressure bound (default 8x max_batch)
- ``serve_timeout_ms`` — default per-request deadline (0 = none)
- ``serve_node`` — node to serve (default: the top node)
- ``serve_warm_run`` — dispatch each bucket once at warmup (default 1)
- ``serve_clients`` / ``serve_requests`` / ``serve_request_rows`` —
  the CLI soak drive (``task = serve``): N closed-loop clients each
  issuing M requests of K rows

See doc/serving.md for the full reference and the telemetry records.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .batcher import DynamicBatcher
from .engine import InferenceEngine, build_engine


class ServeConfig:
    """Parsed ``serve_*`` keys (plus the globals serving depends on)."""

    def __init__(self, cfg: Sequence) -> None:
        self.buckets = "auto"
        self.max_batch = 0
        self.max_delay_ms = 2.0
        self.queue_rows = 0
        self.timeout_ms = 0.0
        self.node = ""
        self.warm_run = 1
        self.clients = 8
        self.requests = 32
        self.request_rows = 1
        # retrieval overrides (doc/retrieval.md): 0/"" defer to the
        # bundle's sealed search contract, so a plain boot requests
        # exactly the sealed search keys (zero compiles)
        self.search_k = 0
        self.search_buckets = ""
        batch_size = 0
        for name, val in cfg:
            if name == "batch_size":
                batch_size = int(val)
            if name == "serve_buckets":
                self.buckets = val
            if name == "serve_max_batch":
                self.max_batch = int(val)
            if name == "serve_max_delay_ms":
                self.max_delay_ms = float(val)
            if name == "serve_queue_rows":
                self.queue_rows = int(val)
            if name == "serve_timeout_ms":
                self.timeout_ms = float(val)
            if name == "serve_node":
                self.node = val
            if name == "serve_warm_run":
                self.warm_run = int(val)
            if name == "serve_clients":
                self.clients = int(val)
            if name == "serve_requests":
                self.requests = int(val)
            if name == "serve_request_rows":
                self.request_rows = int(val)
            if name == "search_k":
                self.search_k = int(val)
            if name == "search_buckets":
                self.search_buckets = val
        if not self.max_batch:
            self.max_batch = batch_size
        if not self.max_batch:
            raise ValueError(
                "serving needs serve_max_batch (or batch_size)")


class ServeSession:
    """A long-lived concurrent predictor over one snapshot.

    Build either from config + model path (the CLI path; the engine
    gets its own bucket-aligned mesh) or around an existing engine
    (library/test path). ``close`` drains in-flight work and emits the
    ``serve_summary`` record.
    """

    def __init__(self, cfg: Sequence = (),
                 model_path: Optional[str] = None,
                 engine: Optional[InferenceEngine] = None,
                 monitor=None):
        self.cfg = ServeConfig(cfg)
        c = self.cfg
        if engine is None:
            assert model_path, "ServeSession needs model_path or engine"
            engine = build_engine(cfg, model_path, buckets=c.buckets,
                                  max_batch=c.max_batch, node=c.node,
                                  monitor=monitor)
        self.engine = engine
        self.warmup_programs = engine.warmup(warm_run=bool(c.warm_run))
        # a bundle that seals an embedding index gets a retrieval
        # engine beside the predictor: same program registry (search
        # executables install from the bundle → zero-compile search
        # warmup), same residency budget books (weights + index), one
        # atomic swap unit
        self.retrieval = None
        self.index_bytes = 0
        if model_path:
            self._attach_index(model_path, monitor)
        self.batcher = DynamicBatcher(
            engine.stage, engine.dispatch,
            max_batch=engine.max_batch, max_delay_ms=c.max_delay_ms,
            max_queue_rows=c.queue_rows, timeout_ms=c.timeout_ms,
            monitor=monitor, row_shape=engine._inst_shape(),
            extra_summary=self._engine_summary)
        self._closed = False

    def _attach_index(self, model_path: str, monitor) -> None:
        """Load the bundle's sealed index (digest-verified) into a
        warmed :class:`~cxxnet_tpu.retrieval.engine.RetrievalEngine`.
        No-op for snapshot models and index-less bundles. Explicit
        ``search_k`` / ``search_buckets`` config wins over the sealed
        contract (those keys then re-lower instead of installing)."""
        from ..artifact import bundle as _ab
        if not _ab.is_bundle(model_path):
            return
        man = _ab.bundle_manifest(model_path)
        entry = man.get("index")
        if entry is None:
            return
        from ..retrieval import EmbeddingIndex, RetrievalEngine
        index = EmbeddingIndex.deserialize(
            _ab.read_index_member(model_path, man))
        c = self.cfg
        spec = c.search_buckets
        if spec and spec != "auto":
            buckets = tuple(sorted({int(t) for t in spec.split(",")
                                    if t.strip()}))
        elif spec != "auto" and entry.get("buckets"):
            buckets = tuple(int(b) for b in entry["buckets"])
        else:
            buckets = None               # the engine's default ladder
        self.retrieval = RetrievalEngine(
            index, self.engine.trainer.programs,
            k=c.search_k or int(entry.get("k", 0)) or 10,
            buckets=buckets, monitor=monitor)
        # the same budget the weight tree froze under: index bytes
        # stack on top of the registry's weight residency
        budget = int(self.engine.trainer.serve_device_mem_budget * 1e6)
        self.retrieval.warmup(warm_run=bool(c.warm_run),
                              budget_bytes=budget)
        self.index_bytes = index.nbytes

    def _engine_summary(self) -> Dict[str, int]:
        # one snapshot: compile_events and aot_hits must come from the
        # same instant in the emitted serve_summary record
        snap = self.engine.counters_snapshot()
        res = self.engine.trainer.programs.residency
        return {"compile_events": snap["compile_events"],
                "aot_hits": snap["aot_hits"],
                # zero-copy dispatch accounting: bytes that actually
                # crossed D2H (valid rows only) and the staging-ring
                # reuse split (doc/serving.md)
                "d2h_bytes": snap["d2h_bytes"],
                "staging_reuse": snap["staging_reuse"],
                "staging_alloc": snap["staging_alloc"],
                "resident_bytes": res.total_bytes if res else 0}

    def submit(self, rows: np.ndarray,
               timeout_ms: Optional[float] = None):
        """Queue rows (internal layout); returns their result Future."""
        return self.batcher.submit(rows, timeout_ms)

    def predict(self, rows: np.ndarray,
                timeout_ms: Optional[float] = None) -> np.ndarray:
        """Blocking score: the served node's rows for ``rows``."""
        return self.batcher(rows, timeout_ms)

    def close(self, drain: bool = True) -> Dict[str, Any]:
        if self._closed:
            return self.batcher.summary()
        self._closed = True
        return self.batcher.close(drain=drain)


def run_closed_loop(session: ServeSession, pool: np.ndarray,
                    clients: int, requests: int,
                    request_rows: int = 1) -> Dict[str, Any]:
    """Drive ``clients`` threaded closed-loop clients through the
    session: each sends ``requests`` requests of ``request_rows``
    consecutive pool rows (wrapping), waiting for each result before
    sending the next — the classic serving load model, and the drive
    behind both ``task = serve`` and ``tools/serve_bench.py``.

    Returns aggregate stats (client errors surface in ``errors``; a
    failed request does not kill its client loop)."""
    results: List[Dict[str, int]] = [
        {"ok": 0, "busy": 0, "timeout": 0, "error": 0}
        for _ in range(clients)]
    npool = pool.shape[0]

    def client(ci: int) -> None:
        from .batcher import ServeBusyError, ServeTimeoutError
        for r in range(requests):
            start = ((ci * requests + r) * request_rows) % npool
            rows = np.take(pool,
                           range(start, start + request_rows),
                           axis=0, mode="wrap")
            try:
                session.predict(rows)
                results[ci]["ok"] += 1
            except ServeBusyError:
                results[ci]["busy"] += 1
            except ServeTimeoutError:
                results[ci]["timeout"] += 1
            except Exception:
                results[ci]["error"] += 1

    t0 = time.monotonic()
    threads = [threading.Thread(target=client, args=(i,),
                                name="serve-client-%d" % i)
               for i in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    agg = {k: sum(r[k] for r in results)
           for k in ("ok", "busy", "timeout", "error")}
    agg["wall_s"] = wall
    agg["clients"] = clients
    agg["rows"] = agg["ok"] * request_rows
    agg["rows_per_sec"] = agg["rows"] / wall if wall > 0 else 0.0
    return agg

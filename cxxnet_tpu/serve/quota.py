"""Per-tenant token-bucket quotas: the fleet's load-shedding policy.

One overloaded tenant must not queue into everyone else's p99. The
front end (``serve/frontend.py``) runs every request through a
:class:`QuotaManager` *before* it touches the shared dispatcher queue:
an over-quota request is shed immediately with a typed
:class:`TenantQuotaError` (a :class:`~cxxnet_tpu.serve.batcher.
ServeBusyError` subclass, so library callers that already handle busy
replies keep working) — the 429-with-Retry-After of the protocol
layer. Admitted requests then still face the dispatcher's own bounded
queue, so the two shedding layers compose: quota sheds a tenant that
exceeds its contract, backpressure sheds everyone when the device is
the bottleneck.

Config grammar (doc/serving.md):

- ``serve_quota`` — comma list of ``tenant:rate[:burst]`` entries.
  ``rate`` is rows/second; ``burst`` is the bucket depth in rows
  (default ``max(rate, 1)``). ``rate 0`` exempts that tenant.
- ``serve_quota_default`` — ``rate[:burst]`` applied to tenants with
  no explicit entry (default: unlimited).

A request of more rows than a tenant's ``burst`` can never be
admitted and is shed deterministically — size your bursts at least one
``serve_max_batch``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Sequence, Tuple

from .batcher import ServeBusyError


class TenantQuotaError(ServeBusyError):
    """Typed over-quota shed: the tenant exceeded its token bucket.

    Subclasses :class:`ServeBusyError` so every existing busy-handling
    path (closed-loop clients, the protocol layer's 429 mapping) treats
    it as load shedding; carries the quota parameters so the reply can
    say *whose* quota and when to retry."""

    def __init__(self, tenant: str, rows: int, rate: float,
                 burst: float, retry_after_s: float):
        super().__init__(
            "tenant %r over quota: %d rows requested, %.6g rows/s "
            "rate, %.6g burst (retry in %.2fs)"
            % (tenant, rows, rate, burst, retry_after_s))
        self.tenant = tenant
        self.rows = rows
        self.rate = rate
        self.burst = burst
        self.retry_after_s = retry_after_s


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/second refill up to
    ``burst``; ``try_take(n)`` admits iff n tokens are available now.
    Thread-safe — protocol handler threads admit concurrently."""

    def __init__(self, rate: float, burst: float):
        if rate <= 0:
            raise ValueError("token bucket rate must be > 0")
        if burst <= 0:
            raise ValueError("token bucket burst must be > 0")
        self.rate = float(rate)
        self.burst = float(burst)
        self._tokens = self.burst
        self._t = time.monotonic()
        self._lock = threading.Lock()

    def _refill(self, now: float) -> None:
        # clamp against a backwards clock step: time.monotonic() is
        # contractually monotonic, but a mocked/virtualized clock (or a
        # future caller passing wall time) must never MINT tokens from
        # a negative elapsed interval, and must not drag _t backwards
        # (which would double-mint when the clock recovers)
        elapsed = now - self._t
        if elapsed <= 0:
            return
        self._tokens = min(self.burst,
                           self._tokens + elapsed * self.rate)
        self._t = now

    def try_take(self, n: float) -> Tuple[bool, float]:
        """Admit ``n`` tokens worth of work now. Returns
        ``(admitted, retry_after_s)`` — when shed, ``retry_after_s``
        estimates when ``n`` tokens will next be available (capped at
        the time a full burst takes, for n > burst)."""
        now = time.monotonic()
        with self._lock:
            self._refill(now)
            if self._tokens >= n:
                self._tokens -= n
                return True, 0.0
            missing = min(n, self.burst) - self._tokens
            return False, max(0.0, missing / self.rate)

    def available(self) -> float:
        now = time.monotonic()
        with self._lock:
            self._refill(now)
            return self._tokens

    def reconfigure(self, rate: float, burst: float) -> None:
        """Retune a live bucket in place (the fleet tier's quota-share
        rebalancer). Accrued tokens are refilled at the OLD rate up to
        now, then clamped to the new burst — a share cut cannot mint
        tokens, and a share raise keeps only what was already banked.
        Unchanged parameters return without touching state (the
        single-door fleet stays bit-identical under rebalancing)."""
        if rate <= 0:
            raise ValueError("token bucket rate must be > 0")
        if burst <= 0:
            raise ValueError("token bucket burst must be > 0")
        now = time.monotonic()
        with self._lock:
            if rate == self.rate and burst == self.burst:
                return
            self._refill(now)
            self.rate = float(rate)
            self.burst = float(burst)
            self._tokens = min(self._tokens, self.burst)


def _parse_bucket_spec(spec: str) -> Optional[Tuple[float, float]]:
    """``rate[:burst]`` -> (rate, burst); rate 0 means unlimited
    (returns None)."""
    parts = [p.strip() for p in spec.split(":")]
    rate = float(parts[0])
    if rate == 0:
        return None
    if rate < 0:
        raise ValueError("quota rate must be >= 0, got %r" % spec)
    burst = float(parts[1]) if len(parts) > 1 and parts[1] \
        else max(rate, 1.0)
    if burst <= 0:
        # fail at config parse, not as a per-request 400 blaming the
        # first client this tenant sends
        raise ValueError("quota burst must be > 0, got %r" % spec)
    return rate, burst


class QuotaManager:
    """Per-tenant admission control from the ``serve_quota`` config.

    ``admit(tenant, rows)`` either returns (recording the admit) or
    raises :class:`TenantQuotaError` (recording the shed). Tenants
    without an explicit entry share the default policy — each such
    tenant still gets its *own* bucket (a burst from tenant A must not
    drain tenant B's default allowance)."""

    def __init__(self, cfg: Sequence = ()):
        self._explicit: Dict[str, Optional[Tuple[float, float]]] = {}
        self._default: Optional[Tuple[float, float]] = None
        for name, val in cfg:
            # a blank value UNSETS the policy: the fleet tier moves
            # quota enforcement to the balancer and spawns replicas
            # with serve_quota= / serve_quota_default= overrides so a
            # conf-file policy is not double-enforced per replica
            if name == "serve_quota":
                if not val.strip():
                    self._explicit = {}
                    continue
                for entry in val.split(","):
                    entry = entry.strip()
                    if not entry:
                        continue
                    tenant, _, spec = entry.partition(":")
                    if not tenant or not spec:
                        raise ValueError(
                            "serve_quota entry %r must be "
                            "tenant:rate[:burst]" % entry)
                    self._explicit[tenant] = _parse_bucket_spec(spec)
            if name == "serve_quota_default":
                self._default = _parse_bucket_spec(val) \
                    if val.strip() else None
        self._buckets: Dict[str, TokenBucket] = {}
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {"admitted": 0, "shed": 0}
        self.shed_by_tenant: Dict[str, int] = {}

    def policy_for(self, tenant: str) -> Optional[Tuple[float, float]]:
        if tenant in self._explicit:
            return self._explicit[tenant]
        return self._default

    def _bucket_for(self, tenant: str) -> Optional[TokenBucket]:
        policy = self.policy_for(tenant)
        if policy is None:
            return None
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                b = TokenBucket(*policy)
                self._buckets[tenant] = b
            return b

    def admit(self, tenant: str, rows: int) -> None:
        """Charge ``rows`` against ``tenant``'s bucket; raises
        :class:`TenantQuotaError` when over quota."""
        bucket = self._bucket_for(tenant)
        if bucket is None:
            with self._lock:
                self.counters["admitted"] += 1
            return
        ok, retry_after = bucket.try_take(rows)
        with self._lock:
            if ok:
                self.counters["admitted"] += 1
            else:
                self.counters["shed"] += 1
                self.shed_by_tenant[tenant] = \
                    self.shed_by_tenant.get(tenant, 0) + 1
        if not ok:
            raise TenantQuotaError(tenant, rows, bucket.rate,
                                   bucket.burst, retry_after)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {"admitted": self.counters["admitted"],
                    "shed": self.counters["shed"],
                    "shed_by_tenant": dict(self.shed_by_tenant)}

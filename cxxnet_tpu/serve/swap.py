"""Checkpoint-driven zero-downtime hot-swap.

A :class:`SnapshotWatcher` polls a model's ``model_dir`` for a newer
*verified* snapshot (the crash-safe checkpoint subsystem's contract:
digest + structure checked by ``nnet.checkpoint.verify_snapshot``
before a single byte is trusted), builds and bucket-warms a **shadow**
engine off the request path, then atomically flips the router entry
and drains the retired engine. The swap sequence:

1. **scan** — committed candidates newest-first. Deliberately *not*
   :func:`~cxxnet_tpu.nnet.checkpoint.find_latest_valid`: that scan
   owns resume semantics (it sweeps stale ``.tmp`` files and
   quarantines corrupt candidates) and assumes no live writer — but a
   watched ``model_dir`` usually HAS a live writer (the training run
   producing the snapshots being served). :func:`latest_verified` is
   the read-only equivalent: ``scan_snapshots`` + ``verify_snapshot``,
   skip-don't-touch on anything invalid or in-flight.
2. **shadow build** — a full :class:`~cxxnet_tpu.serve.server.
   ServeSession` (own trainer, own mesh, own bucket ladder) warms
   every (bucket, mask-variant) executable before the flip, so the
   first request on the new engine pays zero compile cost; the
   engine's ``compile_events``/``aot_hits`` counters account for it
   the same way the steady-state contract is counted.
3. **flip** — ``router.swap`` replaces the entry atomically; new
   requests route to the shadow engine from that instant.
4. **drain** — the retired session ``close(drain=True)``s: requests
   already queued on it complete, then its workers join. The front
   end retries the one unclosable race (resolved-old, submitted-after-
   drain-began) through a fresh resolve, so in-flight requests never
   fail during a swap.

Every swap emits a schema-validated ``hot_swap`` record; a failed
shadow build warns and leaves the old engine serving (failing to
*upgrade* must never take down what currently works).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from ..nnet.checkpoint import (MODEL_RE, scan_snapshots, snapshot_uri,
                               verify_snapshot)
from .router import ModelRouter


def latest_verified(model_dir: str, min_counter: int = -1,
                    ) -> Tuple[Optional[int], Optional[str]]:
    """Newest verified model in ``model_dir`` — a snapshot that
    passes ``verify_snapshot`` or a sealed artifact bundle that
    passes ``verify_bundle`` — as (counter, uri); (None, None) when
    none does. At equal counters the bundle wins: flipping to a
    bundle skips the shadow build's compile time entirely
    (doc/artifacts.md). ``min_counter`` prunes candidates the caller
    would discard anyway BEFORE verification — the watcher's idle
    poll must not re-hash a multi-GB artifact every 2 seconds just to
    compare counters afterwards. (A bundle the caller then *boots*
    is read and hashed again by ``load_bundle`` — deliberately: the
    verification of record belongs to the load, since the artifact
    can change between scan and boot.) Read-only — safe against a
    model_dir a live writer (training run or exporter) is committing
    into (see module docstring)."""
    from ..artifact.bundle import scan_bundles, verify_bundle
    try:
        candidates = [(counter, name, False)
                      for counter, name in scan_snapshots(model_dir)]
        candidates += [(counter, name, True)
                       for counter, name in scan_bundles(model_dir)]
    except (IOError, OSError):
        return None, None
    # newest first; bundle before snapshot at the same counter
    candidates.sort(key=lambda c: (c[0], c[2]), reverse=True)
    for counter, name, is_bundle in candidates:
        if counter <= min_counter:
            break                        # sorted: nothing newer left
        uri = snapshot_uri(model_dir, name)
        rep = verify_bundle(uri) if is_bundle else verify_snapshot(uri)
        if rep["ok"]:
            return counter, uri
    return None, None


def counter_of(path: str) -> int:
    """Snapshot/bundle counter from a ``NNNN.model.npz`` /
    ``NNNN.model.bundle`` basename (0 when the name follows neither
    convention — e.g. an explicit model_in file — so any watched
    counter >= 1 upgrades it)."""
    from ..artifact.bundle import BUNDLE_RE
    base = os.path.basename(path.rstrip("/"))
    m = MODEL_RE.match(base) or BUNDLE_RE.match(base)
    return int(m.group(1)) if m else 0


class SnapshotWatcher:
    """Poll one model's directory and hot-swap on a newer verified
    snapshot.

    ``builder(path)`` must return a warmed-up ``ServeSession`` for the
    snapshot at ``path`` (the front end passes its session factory).
    ``check_once()`` is the synchronous core — the poll thread calls
    it on a timer; tests and the CLI can call it directly.
    """

    def __init__(self, router: ModelRouter, model_id: str,
                 model_dir: str,
                 builder: Callable[[str], Any],
                 poll_s: float = 2.0, monitor=None):
        self.router = router
        self.model_id = model_id
        self.model_dir = model_dir
        self.builder = builder
        self.poll_s = max(0.05, float(poll_s))
        self._mon = monitor
        self._stop = threading.Event()
        # the notify() kick: an in-process writer (the continual
        # exporter) wakes the poll thread the instant its artifact
        # commits instead of waiting out poll_s; polling stays for
        # external writers (a training run in another process)
        self._kick = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # serializes the scan->build->flip->drain sequence AND guards
        # the public counters: check_once runs on the poll thread but
        # is also a public entry point (tests, the CLI's synchronous
        # mode), and two overlapping calls that both see the same new
        # snapshot would shadow-build twice and drain the session the
        # first call just installed
        self._lock = threading.Lock()
        self.swaps = 0
        self.failed_builds = 0
        # negative cache for the same-counter bundle-upgrade probe:
        # uri -> the commit-marker bytes that failed verification. A
        # corrupt bundle beside the served snapshot must not be fully
        # re-hashed every poll; a re-export rewrites the marker, which
        # invalidates the entry and retries
        self._bad_upgrade: Dict[str, bytes] = {}

    # -- the swap core ----------------------------------------------------

    def check_once(self) -> Optional[Dict[str, Any]]:
        """One poll: swap if a newer verified snapshot exists. Returns
        the ``hot_swap`` record fields on a swap, None otherwise.
        Never raises — a failed shadow build warns and leaves the
        current engine serving. Serialized: a concurrent call blocks,
        then sees the freshly swapped counter and does nothing."""
        with self._lock:
            try:
                current = self.router.resolve(self.model_id)
            except KeyError:
                return None
            # resolve BEFORE the scan so already-served counters are
            # pruned pre-verification: the idle poll (no newer
            # artifact) costs a directory listing, not a full re-hash
            # of the currently served bundle every poll_s seconds
            counter, path = latest_verified(
                self.model_dir, min_counter=current.counter)
            if counter is None:
                # no strictly-newer artifact — but an export may have
                # just sealed the COUNTER WE ARE SERVING into a
                # bundle (the headline deploy loop): a same-counter
                # snapshot->bundle upgrade swaps too, so subsequent
                # swaps and restarts skip compiles
                counter, path = self._bundle_upgrade(current)
                if counter is None:
                    return None
            if counter < current.counter or path == current.path:
                return None
            t0 = time.monotonic()
            try:
                # shadow build + bucket warmup, off the request path:
                # the router still serves the old engine while this
                # compiles
                session = self.builder(path)
            except Exception as e:
                self.failed_builds += 1
                self._warn("hot_swap_build_failed:%s" % path,
                           "hot-swap of model %r to %s failed to build "
                           "(%s); keeping the current engine"
                           % (self.model_id, path, e))
                return None
            try:
                old = self.router.swap(self.model_id, session, counter,
                                       path)
            except Exception as e:
                # router refused (closed mid-build, entry gone): the
                # shadow engine must not leak its dispatcher threads
                session.close(drain=False)
                self._warn("hot_swap_flip_failed:%s" % path,
                           "hot-swap of model %r to %s could not flip "
                           "(%s); shadow engine discarded"
                           % (self.model_id, path, e))
                return None
            # drain AFTER the flip: new traffic is already landing on
            # the shadow engine, old traffic finishes on the retiring
            # one
            old_summary = old.session.close(drain=True)
            self.swaps += 1
            rec = {
                "model": self.model_id,
                "old_counter": old.counter,
                "new_counter": counter,
                "path": path,
                "warmup_programs": int(
                    getattr(session, "warmup_programs", 0)),
                "old_requests": int(old_summary.get("requests", 0)),
                "old_compile_events": int(
                    old_summary.get("compile_events", 0)),
                "wall_ms": (time.monotonic() - t0) * 1e3,
            }
            if self._mon is not None and self._mon.enabled:
                try:
                    self._mon.emit("hot_swap", **rec)
                except Exception:
                    # telemetry must not kill swaps; the sink-broken
                    # case warns exactly once instead of passing silently
                    self._warn("hot_swap_emit_failed",
                               "hot_swap record for model %r could "
                               "not be emitted" % self.model_id)
            return rec

    def _bundle_upgrade(self, current) -> Tuple[Optional[int],
                                                Optional[str]]:
        """Probe for a committed bundle at the CURRENTLY SERVED
        counter while the entry still serves a snapshot — cheap
        (directory listing + marker existence) until such a bundle
        appears, full verification only then. (None, None) when
        already on a bundle or none exists."""
        from ..artifact.bundle import (BUNDLE_RE, scan_bundles,
                                       verify_bundle)
        if BUNDLE_RE.match(os.path.basename(
                (current.path or "").rstrip("/"))):
            return None, None            # already serving a bundle
        try:
            bundles = scan_bundles(self.model_dir)
        except (IOError, OSError):
            return None, None
        for c, name in bundles:
            if c != current.counter:
                continue
            uri = snapshot_uri(self.model_dir, name)
            if uri == current.path:
                return None, None
            marker = self._read_marker(uri)
            if marker is not None \
                    and self._bad_upgrade.get(uri) == marker:
                return None, None        # same failed bytes: skip
            # the shadow build's load_bundle re-verifies at read time
            # (verification-of-record belongs to the load; the
            # artifact can change between this poll and the flip)
            rep = verify_bundle(uri)
            if rep["ok"]:
                self._bad_upgrade.pop(uri, None)
                return c, uri
            if marker is not None:
                self._bad_upgrade[uri] = marker
            self._warn("bundle_upgrade_invalid:%s" % uri,
                       "bundle %s at the served counter fails "
                       "verification (%s); staying on the snapshot "
                       "(re-export to retry)" % (uri, rep["error"]))
            return None, None
        return None, None

    @staticmethod
    def _read_marker(uri: str):
        """The bundle's tiny commit-marker bytes (the negative-cache
        key), or None when unreadable."""
        from ..artifact.bundle import MANIFEST_NAME, OK_SUFFIX, \
            member_uri
        from ..utils.stream import read_stream_bytes
        try:
            return read_stream_bytes(
                member_uri(uri, MANIFEST_NAME + OK_SUFFIX))
        except (IOError, OSError):
            return None

    def _warn(self, code: str, message: str) -> None:
        if self._mon is not None:
            self._mon.warn_once(code, message)
        else:
            from ..monitor import warn_once
            warn_once(code, message)

    # -- poll thread ------------------------------------------------------

    def start(self) -> None:
        assert self._thread is None, "watcher already started"
        self._thread = threading.Thread(
            target=self._loop, name="serve-watch-%s" % self.model_id,
            daemon=True)
        self._thread.start()

    def notify(self) -> None:
        """Wake the poll thread for an immediate check — the
        in-process writer's post-commit kick (a notify that lands
        while a check is already running simply schedules one more
        pass, so a commit can never fall into the poll gap). Safe
        from any thread; a no-op before ``start()`` beyond making the
        first poll immediate. External writers keep the plain
        ``poll_s`` cadence — they have no handle to call this."""
        self._kick.set()

    def _loop(self) -> None:
        while True:
            self._kick.wait(self.poll_s)
            self._kick.clear()
            if self._stop.is_set():
                return
            try:
                self.check_once()
            except Exception as e:
                # the watcher must outlive any single bad poll (e.g. a
                # transient remote-list error)
                self._warn("hot_swap_poll_failed",
                           "hot-swap poll for model %r failed: %s"
                           % (self.model_id, e))

    def close(self) -> None:
        self._stop.set()
        self._kick.set()                 # wake a sleeping poll NOW
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

"""Checkpoint-driven zero-downtime hot-swap.

A :class:`SnapshotWatcher` polls a model's ``model_dir`` for a newer
*verified* snapshot (the crash-safe checkpoint subsystem's contract:
digest + structure checked by ``nnet.checkpoint.verify_snapshot``
before a single byte is trusted), builds and bucket-warms a **shadow**
engine off the request path, then atomically flips the router entry
and drains the retired engine. The swap sequence:

1. **scan** — committed candidates newest-first. Deliberately *not*
   :func:`~cxxnet_tpu.nnet.checkpoint.find_latest_valid`: that scan
   owns resume semantics (it sweeps stale ``.tmp`` files and
   quarantines corrupt candidates) and assumes no live writer — but a
   watched ``model_dir`` usually HAS a live writer (the training run
   producing the snapshots being served). :func:`latest_verified` is
   the read-only equivalent: ``scan_snapshots`` + ``verify_snapshot``,
   skip-don't-touch on anything invalid or in-flight.
2. **shadow build** — a full :class:`~cxxnet_tpu.serve.server.
   ServeSession` (own trainer, own mesh, own bucket ladder) warms
   every (bucket, mask-variant) executable before the flip, so the
   first request on the new engine pays zero compile cost; the
   engine's ``compile_events``/``aot_hits`` counters account for it
   the same way the steady-state contract is counted.
3. **flip** — ``router.swap`` replaces the entry atomically; new
   requests route to the shadow engine from that instant.
4. **drain** — the retired session ``close(drain=True)``s: requests
   already queued on it complete, then its workers join. The front
   end retries the one unclosable race (resolved-old, submitted-after-
   drain-began) through a fresh resolve, so in-flight requests never
   fail during a swap.

Every swap emits a schema-validated ``hot_swap`` record; a failed
shadow build warns and leaves the old engine serving (failing to
*upgrade* must never take down what currently works).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from ..nnet.checkpoint import (MODEL_RE, scan_snapshots, snapshot_uri,
                               verify_snapshot)
from .router import ModelRouter


def latest_verified(model_dir: str) -> Tuple[Optional[int],
                                             Optional[str]]:
    """Newest snapshot in ``model_dir`` that passes
    ``verify_snapshot``, as (counter, uri); (None, None) when none
    does. Read-only — safe against a model_dir a live training run is
    committing into (see module docstring)."""
    try:
        candidates = scan_snapshots(model_dir)
    except (IOError, OSError):
        return None, None
    for counter, name in candidates:
        uri = snapshot_uri(model_dir, name)
        if verify_snapshot(uri)["ok"]:
            return counter, uri
    return None, None


def counter_of(path: str) -> int:
    """Snapshot counter from a ``NNNN.model.npz`` basename (0 when the
    name does not follow the convention — e.g. an explicit model_in
    file — so any watched counter >= 1 upgrades it)."""
    m = MODEL_RE.match(os.path.basename(path))
    return int(m.group(1)) if m else 0


class SnapshotWatcher:
    """Poll one model's directory and hot-swap on a newer verified
    snapshot.

    ``builder(path)`` must return a warmed-up ``ServeSession`` for the
    snapshot at ``path`` (the front end passes its session factory).
    ``check_once()`` is the synchronous core — the poll thread calls
    it on a timer; tests and the CLI can call it directly.
    """

    def __init__(self, router: ModelRouter, model_id: str,
                 model_dir: str,
                 builder: Callable[[str], Any],
                 poll_s: float = 2.0, monitor=None):
        self.router = router
        self.model_id = model_id
        self.model_dir = model_dir
        self.builder = builder
        self.poll_s = max(0.05, float(poll_s))
        self._mon = monitor
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # serializes the scan->build->flip->drain sequence AND guards
        # the public counters: check_once runs on the poll thread but
        # is also a public entry point (tests, the CLI's synchronous
        # mode), and two overlapping calls that both see the same new
        # snapshot would shadow-build twice and drain the session the
        # first call just installed
        self._lock = threading.Lock()
        self.swaps = 0
        self.failed_builds = 0

    # -- the swap core ----------------------------------------------------

    def check_once(self) -> Optional[Dict[str, Any]]:
        """One poll: swap if a newer verified snapshot exists. Returns
        the ``hot_swap`` record fields on a swap, None otherwise.
        Never raises — a failed shadow build warns and leaves the
        current engine serving. Serialized: a concurrent call blocks,
        then sees the freshly swapped counter and does nothing."""
        with self._lock:
            counter, path = latest_verified(self.model_dir)
            if counter is None:
                return None
            try:
                current = self.router.resolve(self.model_id)
            except KeyError:
                return None
            if counter <= current.counter:
                return None
            t0 = time.monotonic()
            try:
                # shadow build + bucket warmup, off the request path:
                # the router still serves the old engine while this
                # compiles
                session = self.builder(path)
            except Exception as e:
                self.failed_builds += 1
                self._warn("hot_swap_build_failed:%s" % path,
                           "hot-swap of model %r to %s failed to build "
                           "(%s); keeping the current engine"
                           % (self.model_id, path, e))
                return None
            try:
                old = self.router.swap(self.model_id, session, counter,
                                       path)
            except Exception as e:
                # router refused (closed mid-build, entry gone): the
                # shadow engine must not leak its dispatcher threads
                session.close(drain=False)
                self._warn("hot_swap_flip_failed:%s" % path,
                           "hot-swap of model %r to %s could not flip "
                           "(%s); shadow engine discarded"
                           % (self.model_id, path, e))
                return None
            # drain AFTER the flip: new traffic is already landing on
            # the shadow engine, old traffic finishes on the retiring
            # one
            old_summary = old.session.close(drain=True)
            self.swaps += 1
            rec = {
                "model": self.model_id,
                "old_counter": old.counter,
                "new_counter": counter,
                "path": path,
                "warmup_programs": int(
                    getattr(session, "warmup_programs", 0)),
                "old_requests": int(old_summary.get("requests", 0)),
                "old_compile_events": int(
                    old_summary.get("compile_events", 0)),
                "wall_ms": (time.monotonic() - t0) * 1e3,
            }
            if self._mon is not None and self._mon.enabled:
                try:
                    self._mon.emit("hot_swap", **rec)
                except Exception:
                    # telemetry must not kill swaps; the sink-broken
                    # case warns exactly once instead of passing silently
                    self._warn("hot_swap_emit_failed",
                               "hot_swap record for model %r could "
                               "not be emitted" % self.model_id)
            return rec

    def _warn(self, code: str, message: str) -> None:
        if self._mon is not None:
            self._mon.warn_once(code, message)
        else:
            from ..monitor import warn_once
            warn_once(code, message)

    # -- poll thread ------------------------------------------------------

    def start(self) -> None:
        assert self._thread is None, "watcher already started"
        self._thread = threading.Thread(
            target=self._loop, name="serve-watch-%s" % self.model_id,
            daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.check_once()
            except Exception as e:
                # the watcher must outlive any single bad poll (e.g. a
                # transient remote-list error)
                self._warn("hot_swap_poll_failed",
                           "hot-swap poll for model %r failed: %s"
                           % (self.model_id, e))

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

"""The frozen inference engine: a snapshot turned into a predictor.

Wraps an eval-mode :class:`~cxxnet_tpu.nnet.trainer.NetTrainer` whose
weights never change again: the forward runs with ``is_train=False``, so
``bn_fold_eval`` folds running-stats scale/shift into the conv weights
and dropout/augment-time randomness is off. ``warmup()`` AOT-compiles
the pred executables at every batch-size bucket (both mask variants)
via ``NetTrainer.precompile_pred`` — after that, a dispatch at any
bucket goes straight to a compiled executable and the engine's
``compile_events`` counter stays at zero.

The engine exposes a two-phase dispatch for the batcher's pipelined
hand-off (stage the H2D transfer for batch N+1 while batch N computes —
the PR 2 prefetch-chain pattern applied to serving):

- :meth:`stage` — pad rows to their bucket and issue the device_put
- :meth:`dispatch` — run the executable and fetch the depadded rows

plus one-shot helpers (:meth:`run`, :meth:`predict`) for library
callers that do not need the concurrent path.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from .bucketing import (bucket_ladder, pad_to_bucket, pick_bucket,
                        reachable_variants)


def input_dtype_for(serve_dtype: str):
    """The staging dtype a ladder warms for a ``serve_dtype``: bf16
    ladders warm and stage bf16 (half the H2D bytes); int8/fp8 graphs
    quantize on device, so their input stays f32. One definition shared
    by :func:`build_engine` and ``tools/serve_bench.py``."""
    import jax.numpy as jnp
    return jnp.bfloat16 if serve_dtype == "bfloat16" else np.float32


class StagedBatch:
    """A micro-batch whose H2D transfer has been issued: device-resident
    data + mask, the valid-row count, and the node set to fetch."""

    __slots__ = ("data", "mask", "nvalid", "bucket", "nodes")

    def __init__(self, data, mask, nvalid: int, bucket: int,
                 nodes: Tuple[int, ...]):
        self.data = data
        self.mask = mask
        self.nvalid = nvalid
        self.bucket = bucket
        self.nodes = nodes


class InferenceEngine:
    """Bucketed AOT predictor over a loaded trainer.

    ``trainer`` must be initialized (init_model/load_model). Buckets
    must split evenly across the trainer's mesh data axis; engines
    built through :func:`build_engine` / ``ServeSession`` choose the
    mesh from the bucket ladder automatically (a ladder containing 1
    forces a single-device data axis).

    Thread safety: :meth:`dispatch` (and the one-shot helpers) hold an
    internal lock — one dispatch at a time, callers from any thread.
    """

    def __init__(self, trainer, buckets: Optional[Sequence[int]] = None,
                 node: str = "", monitor=None,
                 input_dtype=np.float32):
        assert trainer._initialized, \
            "InferenceEngine needs an initialized trainer"
        self.trainer = trainer
        # the dtype the bucket ladder warms (and therefore the ONLY
        # dtype stage() may ship): a bf16-warmed ladder staging f32
        # would recompile-hazard every dispatch
        self.input_dtype = np.dtype(input_dtype)
        mesh_axes = dict(trainer.mesh.shape)
        align = int(mesh_axes.get("data", 1))
        if buckets is None:
            buckets = bucket_ladder(trainer.batch_size, align=align)
        self.buckets = tuple(sorted(int(b) for b in buckets))
        for b in self.buckets:
            if b % align:
                raise ValueError(
                    "bucket %d does not split across the mesh data "
                    "axis %d" % (b, align))
        self.max_batch = self.buckets[-1]
        top = trainer.graph.num_nodes - 1
        self.nodes = (trainer.net.node_index_by_name(node) if node
                      else top,)
        self._mon = monitor
        self._lock = threading.Lock()
        self._sigs = set()               # jit signatures seen (compile
        #                                  detection on the fallback path)
        self.counters: Dict[str, int] = {
            "dispatches": 0, "rows": 0, "pad_rows": 0, "aot_hits": 0,
            "compile_events": 0}

    # -- warmup ----------------------------------------------------------

    def warmup(self, warm_run: bool = True) -> int:
        """Compile every (bucket, mask-variant) pred executable; with
        ``warm_run`` also push one zero batch through each bucket so
        first-request latency pays no lazy-init cost. Resets the
        compile counter: events counted afterwards are real steady-
        state compiles — the number a healthy server keeps at zero."""
        compiled = self.trainer.precompile_pred(self.buckets, self.nodes,
                                                dtype=self.input_dtype)
        if warm_run:
            inst = self._inst_shape()
            for _, rows in reachable_variants(self.buckets):
                self.dispatch(self.stage(
                    np.zeros((rows,) + inst, self.input_dtype)))
        with self._lock:
            self.counters["compile_events"] = 0
            self.counters["aot_hits"] = 0
            self.counters["dispatches"] = 0
            self.counters["rows"] = 0
            self.counters["pad_rows"] = 0
        return compiled

    def _inst_shape(self) -> Tuple[int, ...]:
        from ..io.data import inst_array_shape
        return inst_array_shape(tuple(self.trainer.graph.input_shape))

    # -- two-phase dispatch (the batcher path) ---------------------------

    def stage(self, rows: np.ndarray) -> StagedBatch:
        """Pad ``rows`` (internal layout: NHWC / (n, features), any
        dtype) to their bucket and issue the H2D transfer. Cheap host
        work + an async device_put — safe to run for batch N+1 while
        batch N computes. Rows are cast to the engine's warmed
        ``input_dtype`` (f32 by default, bf16 under a bf16-warmed
        ladder) — so no caller dtype can trigger a steady-state
        compile, and a low-precision ladder never silently up-casts on
        the H2D path."""
        rows = np.asarray(rows)  # cxxlint: disable=CXL003 -- host staging: request rows arrive as host numpy/json, never device values
        if rows.dtype != self.input_dtype:
            rows = rows.astype(self.input_dtype)
        n = rows.shape[0]
        bucket = pick_bucket(n, self.buckets)
        if bucket is None:
            raise ValueError(
                "batch of %d rows exceeds the largest bucket %d"
                % (n, self.max_batch))
        padded, npad = pad_to_bucket(rows, bucket)
        t = self.trainer
        mask = None
        if npad:
            m = np.ones((bucket,), np.float32)
            m[n:] = 0.0
            mask = t._put_batch_array(m)
        # only self.nodes is servable: warmup compiled exactly that
        # node set, so any other request would jit-compile in the hot
        # path and break the zero-compile-after-warmup contract
        return StagedBatch(t._put_batch_array(padded), mask, n, bucket,
                           self.nodes)

    def dispatch(self, staged: StagedBatch) -> np.ndarray:
        """Run the staged batch and return the valid rows of the first
        requested node as float32 numpy (natural node shape, depadded
        both in channels and batch rows)."""
        t = self.trainer
        with self._lock:
            sig = ("pred",) + t.pred_sig(
                staged.data.shape, staged.data.dtype,
                staged.mask is None, 0, staged.nodes)
            if sig in t._aot:
                self.counters["aot_hits"] += 1
            elif sig not in self._sigs:
                self._sigs.add(sig)
                self.counters["compile_events"] += 1
            vals = t._call_pred(staged.data, staged.mask, (),
                                staged.nodes)
        # the result materialization is the expensive part of dispatch
        # (wait for device compute + D2H copy) and needs no shared
        # state: it must happen OUTSIDE the lock, or every concurrent
        # dispatcher/library caller convoys behind one device round
        # trip. _call_pred above only *issues* the async dispatch.
        out = np.asarray(vals[0])[:staged.nvalid]  # cxxlint: disable=CXL003 -- boundary D2H: the client consumes host rows; runs lock-free
        # success counters AFTER materialization: a device error
        # surfaces at the D2H copy, and a failed dispatch must not
        # count served rows (the batcher accounts the error separately)
        with self._lock:
            self.counters["dispatches"] += 1
            self.counters["rows"] += staged.nvalid
            self.counters["pad_rows"] += staged.bucket - staged.nvalid
        return out

    # -- one-shot helpers (library path) ---------------------------------

    def run(self, rows: np.ndarray) -> np.ndarray:
        """Score ``rows`` of any count: chunks of ``max_batch`` rows
        dispatch bucket-padded, results concatenate back."""
        rows = np.asarray(rows)
        if rows.shape[0] < 1:
            raise ValueError("run() needs at least one row")
        outs = []
        for i in range(0, rows.shape[0], self.max_batch):
            chunk = rows[i:i + self.max_batch]
            outs.append(self.dispatch(self.stage(chunk)))
        return np.concatenate(outs, axis=0) if len(outs) > 1 else outs[0]

    def predict(self, rows: np.ndarray) -> np.ndarray:
        """Per-row predicted class index (or raw scalar) of the top
        node — ``NetTrainer.predict`` semantics on the bucketed path."""
        return self.trainer.rows_to_prediction(self.run(rows))

    def counters_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.counters)


def build_engine(cfg, model_path: str,
                 buckets: Optional[Sequence[int]] = None,
                 max_batch: int = 0, node: str = "",
                 monitor=None) -> InferenceEngine:
    """Load a snapshot — or a sealed artifact bundle — into a frozen
    engine with a bucket-aligned mesh.

    ``cfg`` is the ordered config-pair stream (netconfig + globals, the
    same stream ``NetTrainer`` takes). The mesh data axis is the
    largest device count that divides every bucket, so any ladder is
    servable on any host (a ladder with bucket 1 runs single-device).

    When ``model_path`` is a bundle (doc/artifacts.md), the serve
    contract the executables were sealed for fills any knob the config
    left at its default: the manifest's bucket ladder replaces
    ``auto``, its serve dtype applies when the config names none, and
    its node likewise — so booting with the export-time config (or no
    serve config at all) requests exactly the sealed keys and warmup
    compiles nothing. Explicit config values still win; mismatched
    keys just re-lower per key.
    """
    import jax

    from ..nnet.quantize import normalize_serve_dtype
    from ..nnet.trainer import NetTrainer
    from ..parallel import make_mesh
    from .bucketing import mesh_align, parse_buckets
    cfg = list(cfg)
    serve_dtype = ""
    if not max_batch:
        for k, v in cfg:
            if k == "batch_size":
                max_batch = int(v)
    for k, v in cfg:
        if k == "serve_dtype":
            serve_dtype = normalize_serve_dtype(v)
    from ..artifact import bundle as _ab
    manifest = None
    if _ab.is_bundle(model_path):
        manifest = _ab.bundle_manifest(model_path)
        if buckets is None or buckets in ("", "auto"):
            buckets = tuple(int(b) for b in manifest["buckets"])
        if not max_batch:
            max_batch = max(manifest["buckets"])
        if not serve_dtype and manifest.get("serve_dtype"):
            serve_dtype = normalize_serve_dtype(
                manifest["serve_dtype"])
            # the trainer must build the SAME graph the executables
            # were sealed from (quantized dtypes change the traced
            # forward); appended last so it wins inside the trainer
            cfg = cfg + [("serve_dtype", serve_dtype)]
        if not node and manifest.get("node"):
            node = manifest["node"]
    serve_dtype = serve_dtype or "float32"
    if not max_batch:
        raise ValueError("serve needs batch_size (or serve_max_batch)")
    spec = buckets if isinstance(buckets, str) else ""
    if isinstance(buckets, str) or buckets is None:
        buckets = parse_buckets(spec, max_batch)
    align = mesh_align(buckets, len(jax.devices()))
    trainer = NetTrainer(cfg, mesh=make_mesh(align, 1))
    if monitor is not None:
        # monitor BEFORE load: a bundle load emits its artifact_load
        # hit/rebuild accounting during load_model
        trainer.set_monitor(monitor)
    trainer.load_model(model_path)
    return InferenceEngine(trainer, buckets=buckets, node=node,
                           monitor=monitor,
                           input_dtype=input_dtype_for(serve_dtype))

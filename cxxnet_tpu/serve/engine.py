"""The frozen inference engine: a snapshot turned into a predictor.

Wraps an eval-mode :class:`~cxxnet_tpu.nnet.trainer.NetTrainer` whose
weights never change again: the forward runs with ``is_train=False``, so
``bn_fold_eval`` folds running-stats scale/shift into the conv weights
and dropout/augment-time randomness is off. ``warmup()`` AOT-compiles
the pred executables at every batch-size bucket (both mask variants)
via ``NetTrainer.precompile_pred`` — after that, a dispatch at any
bucket goes straight to a compiled executable and the engine's
``compile_events`` counter stays at zero.

The engine exposes a two-phase dispatch for the batcher's pipelined
hand-off (stage the H2D transfer for batch N+1 while batch N computes —
the PR 2 prefetch-chain pattern applied to serving):

- :meth:`stage` — pad rows to their bucket and issue the device_put
- :meth:`dispatch` — run the executable and fetch the depadded rows

plus one-shot helpers (:meth:`run`, :meth:`predict`) for library
callers that do not need the concurrent path.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from .bucketing import bucket_ladder, pick_bucket, reachable_variants

# staging ring depth per bucket: must cover every concurrently
# in-flight staged batch of the batcher pipeline (stage_depth staged +
# one dispatching + one being staged); reuse additionally gates on the
# slot's previous H2D having completed, so the depth is a throughput
# knob, not a correctness bound
STAGE_RING_DEPTH = 4


class _StageSlot:
    """One preallocated host staging buffer: the rows written since
    the last zeroing (``high``) and the device array its last H2D
    produced (reuse must wait for that transfer, PR 2's release
    discipline applied to serving)."""

    __slots__ = ("buf", "high", "last_dev", "busy")

    def __init__(self, buf: np.ndarray):
        self.buf = buf
        self.high = 0
        self.last_dev = None
        self.busy = True                 # created for its first caller


def _aliases_host(buf: np.ndarray, dev) -> bool:
    """Does the staged device array still reference the host staging
    buffer? CPU-backend device_put is immutable-zero-copy for aligned
    arrays — reusing the buffer would overwrite an in-flight batch.
    Conservative: any doubt counts as aliasing (the iter_batch
    ``_batch_aliases`` probe, specialized to one array)."""
    try:
        import jax
        if isinstance(dev, jax.Array):
            return any(np.shares_memory(np.asarray(s.data), buf)  # cxxlint: disable=CXL003 -- one-time aliasing probe on the FIRST stage only (self._ring_ok latches); CPU shard views are zero-copy
                       for s in dev.addressable_shards)
        if isinstance(dev, np.ndarray):
            return bool(np.shares_memory(dev, buf))
    except Exception:
        return True
    return True


def input_dtype_for(serve_dtype: str):
    """The staging dtype a ladder warms for a ``serve_dtype``: bf16
    ladders warm and stage bf16 (half the H2D bytes); int8/fp8 graphs
    quantize on device, so their input stays f32. One definition shared
    by :func:`build_engine` and ``tools/serve_bench.py``."""
    import jax.numpy as jnp
    return jnp.bfloat16 if serve_dtype == "bfloat16" else np.float32


class StagedBatch:
    """A micro-batch whose H2D transfer has been issued: device-resident
    data + mask, the valid-row count, and the node set to fetch."""

    __slots__ = ("data", "mask", "nvalid", "bucket", "nodes")

    def __init__(self, data, mask, nvalid: int, bucket: int,
                 nodes: Tuple[int, ...]):
        self.data = data
        self.mask = mask
        self.nvalid = nvalid
        self.bucket = bucket
        self.nodes = nodes


class InferenceEngine:
    """Bucketed AOT predictor over a loaded trainer.

    ``trainer`` must be initialized (init_model/load_model). Buckets
    must split evenly across the trainer's mesh data axis; engines
    built through :func:`build_engine` / ``ServeSession`` choose the
    mesh from the bucket ladder automatically (a ladder containing 1
    forces a single-device data axis).

    Thread safety: :meth:`dispatch` (and the one-shot helpers) hold an
    internal lock — one dispatch at a time, callers from any thread.
    """

    def __init__(self, trainer, buckets: Optional[Sequence[int]] = None,
                 node: str = "", monitor=None,
                 input_dtype=np.float32):
        assert trainer._initialized, \
            "InferenceEngine needs an initialized trainer"
        self.trainer = trainer
        # the dtype the bucket ladder warms (and therefore the ONLY
        # dtype stage() may ship): a bf16-warmed ladder staging f32
        # would recompile-hazard every dispatch
        self.input_dtype = np.dtype(input_dtype)
        mesh_axes = dict(trainer.mesh.shape)
        align = int(mesh_axes.get("data", 1))
        if buckets is None:
            buckets = bucket_ladder(trainer.batch_size, align=align)
        self.buckets = tuple(sorted(int(b) for b in buckets))
        for b in self.buckets:
            if b % align:
                raise ValueError(
                    "bucket %d does not split across the mesh data "
                    "axis %d" % (b, align))
        self.max_batch = self.buckets[-1]
        top = trainer.graph.num_nodes - 1
        self.nodes = (trainer.net.node_index_by_name(node) if node
                      else top,)
        self._mon = monitor
        self._lock = threading.Lock()
        self._sigs = set()               # jit signatures seen (compile
        #                                  detection on the fallback path)
        # preallocated per-bucket staging rings (zero-copy request
        # assembly straight into the H2D source buffer); reuse is
        # probed on the first stage the way BatchAdapter's prefetch
        # chain does — a backend whose device_put aliases host memory
        # (CPU zero-copy) never reuses a slot
        self._stage_lock = threading.Lock()
        self._ring: Dict[int, List[_StageSlot]] = {}
        self._ring_next: Dict[int, int] = {}
        self._ring_ok: Optional[bool] = None
        self.counters: Dict[str, int] = {
            "dispatches": 0, "rows": 0, "pad_rows": 0, "aot_hits": 0,
            "compile_events": 0, "staging_reuse": 0, "staging_alloc": 0,
            "d2h_bytes": 0}

    # -- warmup ----------------------------------------------------------

    def warmup(self, warm_run: bool = True) -> int:
        """Compile every (bucket, mask-variant) pred executable; with
        ``warm_run`` also push one zero batch through each bucket so
        first-request latency pays no lazy-init cost. Resets the
        compile counter: events counted afterwards are real steady-
        state compiles — the number a healthy server keeps at zero."""
        # donate=True: the serve-ladder executables take the staged
        # data/mask buffers as donated arguments (consumed exactly once
        # per dispatch; serve_donate=0 opts out). This is also where
        # the serve weight tree freezes — a serve_device_mem_budget
        # breach surfaces here as the typed ResidencyBudgetError
        compiled = self.trainer.precompile_pred(self.buckets, self.nodes,
                                                dtype=self.input_dtype,
                                                donate=True)
        if warm_run:
            inst = self._inst_shape()
            for _, rows in reachable_variants(self.buckets):
                self.dispatch(self.stage(
                    np.zeros((rows,) + inst, self.input_dtype)))
        with self._lock, self._stage_lock:
            # both counter writers held: dispatch counters live under
            # _lock, staging-ring counters under _stage_lock
            for k in self.counters:
                self.counters[k] = 0
        return compiled

    def _inst_shape(self) -> Tuple[int, ...]:
        from ..io.data import inst_array_shape
        return inst_array_shape(tuple(self.trainer.graph.input_shape))

    # -- two-phase dispatch (the batcher path) ---------------------------

    def stage(self, rows: Union[np.ndarray, Sequence[np.ndarray]]
              ) -> StagedBatch:
        """Assemble ``rows`` (one array, or the batcher's list of
        per-request row arrays) into a preallocated staging buffer and
        issue the H2D transfer. Cheap host work + an async device_put —
        safe to run for batch N+1 while batch N computes.

        Request rows copy ONCE, straight from the caller arrays into a
        bucket-sized slot of the staging ring (cast to the warmed
        ``input_dtype`` during the copy, pad tail zeroed to its
        high-water mark) — no intermediate concatenate/astype/pad
        copies, and steady state allocates nothing. Slot reuse waits
        for the slot's previous transfer and is disabled entirely on
        backends whose device_put aliases host memory (probed on the
        first stage, the BatchAdapter discipline)."""
        if isinstance(rows, (list, tuple)):
            parts = [np.asarray(r) for r in rows]  # cxxlint: disable=CXL003 -- host staging: request rows arrive as host numpy/json, never device values
        else:
            parts = [np.asarray(rows)]  # cxxlint: disable=CXL003 -- host staging (single-request path), same contract as above
        inst = self._inst_shape()
        for p in parts:
            # the copy below would silently BROADCAST a mis-shaped
            # row (e.g. a singleton channel) into the buffer; the
            # replaced device_put path surfaced those as aval errors
            if tuple(p.shape[1:]) != inst:
                raise ValueError(
                    "request row shape %r does not match the served "
                    "instance shape %r" % (p.shape[1:], inst))
        n = sum(p.shape[0] for p in parts)
        bucket = pick_bucket(n, self.buckets)
        if bucket is None:
            raise ValueError(
                "batch of %d rows exceeds the largest bucket %d"
                % (n, self.max_batch))
        slot = self._acquire_slot(bucket, n)
        try:
            buf = slot.buf if slot is not None else np.zeros(
                (bucket,) + inst, self.input_dtype)
            off = 0
            for p in parts:
                buf[off:off + p.shape[0]] = p  # casts during the copy
                off += p.shape[0]
            t = self.trainer
            mask = None
            if n < bucket:
                m = np.ones((bucket,), np.float32)
                m[n:] = 0.0
                mask = t._put_batch_array(m)
            # only self.nodes is servable: warmup compiled exactly
            # that node set, so any other request would jit-compile in
            # the hot path and break the zero-compile-after-warmup
            # contract
            data = t._put_batch_array(buf)
        except BaseException:
            # a failed stage must hand its slot back, or a few
            # transient errors would silently retire the whole ring
            if slot is not None:
                slot.busy = False
            raise
        self._note_staged(slot, buf, data)
        return StagedBatch(data, mask, n, bucket, self.nodes)

    def _acquire_slot(self, bucket: int,
                      n: int) -> Optional[_StageSlot]:
        """A staging-ring slot for ``bucket`` whose buffer is safe to
        overwrite, or None when ring reuse is disabled (aliasing
        backend: every stage gets a fresh buffer, the pre-ring
        behavior)."""
        with self._stage_lock:
            if self._ring_ok is False:
                self.counters["staging_alloc"] += 1
                return None
            ring = self._ring.setdefault(bucket, [])
            slot = None
            start = self._ring_next.get(bucket, 0)
            for k in range(len(ring)):           # oldest-first scan
                cand = ring[(start + k) % len(ring)]
                if not cand.busy:
                    slot = cand
                    self._ring_next[bucket] = (start + k + 1) \
                        % len(ring)
                    self.counters["staging_reuse"] += 1
                    break
            if slot is None:
                if len(ring) >= STAGE_RING_DEPTH:
                    # every slot is being written by a concurrent
                    # caller (library run() fan-in beyond the ring):
                    # fall back to a transient buffer, never block
                    self.counters["staging_alloc"] += 1
                    return None
                slot = _StageSlot(np.zeros(
                    (bucket,) + self._inst_shape(), self.input_dtype))
                ring.append(slot)
                self.counters["staging_alloc"] += 1
            slot.busy = True
        if slot.last_dev is not None:
            # the slot's previous H2D must complete before its host
            # buffer is overwritten (an almost-always-satisfied wait:
            # the slot is STAGE_RING_DEPTH batches old). A DELETED
            # array means the donated serve executable already
            # consumed it — the transfer is long done, overwriting is
            # safe (donation deletes inputs at dispatch; waiting on a
            # deleted jax.Array raises instead of returning)
            import jax
            dev, slot.last_dev = slot.last_dev, None
            try:
                if not dev.is_deleted():
                    jax.block_until_ready(dev)  # cxxlint: disable=CXL003 -- bounded reuse guard: waits only for a DEPTH-batches-old H2D copy, the PR 2 release discipline
            except RuntimeError:
                pass  # cxxlint: disable=CXL006 -- deleted-between-check-and-wait race: deletion IS the proof the transfer completed
        if slot.high > n:
            slot.buf[n:slot.high] = 0        # zero the pad tail once
        slot.high = n
        return slot

    def _note_staged(self, slot: Optional[_StageSlot],
                     buf: np.ndarray, data) -> None:
        """First-stage aliasing probe + per-slot transfer bookkeeping.
        When device_put zero-copy-aliased the host buffer, ring reuse
        would overwrite an in-flight batch — disable it for good and
        orphan the handed-out slots."""
        if self._ring_ok is None:
            with self._stage_lock:
                if self._ring_ok is None:
                    self._ring_ok = not _aliases_host(buf, data)
                    if not self._ring_ok:
                        self._ring.clear()
                        self._ring_next.clear()
        if slot is not None:
            if self._ring_ok:
                slot.last_dev = data
            slot.busy = False

    def dispatch(self, staged: StagedBatch) -> np.ndarray:
        """Run the staged batch and return the valid rows of the first
        requested node as float32 numpy (natural node shape, depadded
        both in channels and batch rows)."""
        t = self.trainer
        with self._lock:
            sig = ("pred",) + t.pred_sig(
                staged.data.shape, staged.data.dtype,
                staged.mask is None, 0, staged.nodes)
            if sig in t._aot:
                self.counters["aot_hits"] += 1
            elif sig not in self._sigs:
                self._sigs.add(sig)
                self.counters["compile_events"] += 1
            vals = t._call_pred(staged.data, staged.mask, (),
                                staged.nodes)
        # the result materialization is the expensive part of dispatch
        # (wait for device compute + D2H copy) and needs no shared
        # state: it must happen OUTSIDE the lock, or every concurrent
        # dispatcher/library caller convoys behind one device round
        # trip. _call_pred above only *issues* the async dispatch.
        out_dev = vals[0]
        if staged.nvalid < staged.bucket:
            # slice the valid rows ON DEVICE before materializing:
            # only nvalid rows cross the D2H (PCIe/host) boundary, the
            # pad tail never does (the slice is a tiny device op,
            # shape-cached by jax after its first use per fill level)
            out_dev = out_dev[:staged.nvalid]
        out = np.asarray(out_dev)  # cxxlint: disable=CXL003 -- boundary D2H: the client consumes host rows; runs lock-free
        # success counters AFTER materialization: a device error
        # surfaces at the D2H copy, and a failed dispatch must not
        # count served rows (the batcher accounts the error separately)
        with self._lock:
            self.counters["dispatches"] += 1
            self.counters["rows"] += staged.nvalid
            self.counters["pad_rows"] += staged.bucket - staged.nvalid
            self.counters["d2h_bytes"] += int(out.nbytes)
        return out

    # -- one-shot helpers (library path) ---------------------------------

    def run(self, rows: np.ndarray) -> np.ndarray:
        """Score ``rows`` of any count: chunks of ``max_batch`` rows
        dispatch bucket-padded, results concatenate back."""
        rows = np.asarray(rows)
        if rows.shape[0] < 1:
            raise ValueError("run() needs at least one row")
        outs = []
        for i in range(0, rows.shape[0], self.max_batch):
            chunk = rows[i:i + self.max_batch]
            outs.append(self.dispatch(self.stage(chunk)))
        return np.concatenate(outs, axis=0) if len(outs) > 1 else outs[0]

    def predict(self, rows: np.ndarray) -> np.ndarray:
        """Per-row predicted class index (or raw scalar) of the top
        node — ``NetTrainer.predict`` semantics on the bucketed path."""
        return self.trainer.rows_to_prediction(self.run(rows))

    def counters_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.counters)


def build_engine(cfg, model_path: str,
                 buckets: Optional[Sequence[int]] = None,
                 max_batch: int = 0, node: str = "",
                 monitor=None) -> InferenceEngine:
    """Load a snapshot — or a sealed artifact bundle — into a frozen
    engine with a bucket-aligned mesh.

    ``cfg`` is the ordered config-pair stream (netconfig + globals, the
    same stream ``NetTrainer`` takes). The mesh data axis is the
    largest device count that divides every bucket, so any ladder is
    servable on any host (a ladder with bucket 1 runs single-device).

    When ``model_path`` is a bundle (doc/artifacts.md), the serve
    contract the executables were sealed for fills any knob the config
    left at its default: the manifest's bucket ladder replaces
    ``auto``, its serve dtype applies when the config names none, and
    its node likewise — so booting with the export-time config (or no
    serve config at all) requests exactly the sealed keys and warmup
    compiles nothing. Explicit config values still win; mismatched
    keys just re-lower per key.
    """
    import jax

    from ..nnet.quantize import normalize_serve_dtype
    from ..nnet.trainer import NetTrainer
    from ..parallel import make_mesh
    from .bucketing import mesh_align, parse_buckets
    cfg = list(cfg)
    serve_dtype = ""
    if not max_batch:
        for k, v in cfg:
            if k == "batch_size":
                max_batch = int(v)
    for k, v in cfg:
        if k == "serve_dtype":
            serve_dtype = normalize_serve_dtype(v)
    from ..artifact import bundle as _ab
    manifest = None
    if _ab.is_bundle(model_path):
        manifest = _ab.bundle_manifest(model_path)
        if buckets is None or buckets in ("", "auto"):
            buckets = tuple(int(b) for b in manifest["buckets"])
        if not max_batch:
            max_batch = max(manifest["buckets"])
        if not serve_dtype and manifest.get("serve_dtype"):
            serve_dtype = normalize_serve_dtype(
                manifest["serve_dtype"])
            # the trainer must build the SAME graph the executables
            # were sealed from (quantized dtypes change the traced
            # forward); appended last so it wins inside the trainer
            cfg = cfg + [("serve_dtype", serve_dtype)]
        if not node and manifest.get("node"):
            node = manifest["node"]
        # the sealed weight calling convention (frozen serve tree vs
        # raw masters as pred arguments) must survive the boot, or the
        # installed executables would re-lower; explicit config wins.
        # A manifest WITHOUT the field predates weight residency — its
        # executables were sealed against the raw masters, so default
        # the boot to the legacy convention instead of discarding
        # every sealed program against the new default
        if not any(k == "serve_weight_residency" for k, _ in cfg):
            cfg = cfg + [("serve_weight_residency",
                          str(int(manifest.get("weight_residency",
                                               0))))]
    serve_dtype = serve_dtype or "float32"
    if not max_batch:
        raise ValueError("serve needs batch_size (or serve_max_batch)")
    spec = buckets if isinstance(buckets, str) else ""
    if isinstance(buckets, str) or buckets is None:
        buckets = parse_buckets(spec, max_batch)
    align = mesh_align(buckets, len(jax.devices()))
    trainer = NetTrainer(cfg, mesh=make_mesh(align, 1))
    if monitor is not None:
        # monitor BEFORE load: a bundle load emits its artifact_load
        # hit/rebuild accounting during load_model
        trainer.set_monitor(monitor)
    trainer.load_model(model_path)
    return InferenceEngine(trainer, buckets=buckets, node=node,
                           monitor=monitor,
                           input_dtype=input_dtype_for(serve_dtype))

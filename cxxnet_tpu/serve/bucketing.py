"""Batch-size buckets: the static-shape vocabulary of the serve path.

XLA compiles one executable per input shape, so a server that dispatched
every request at its natural batch size would recompile constantly. The
serve subsystem instead rounds every micro-batch up to a small ladder of
batch-size *buckets* (e.g. 1/8/32/max_batch), pads the tail rows, and
masks them with the same ``num_batch_padd`` machinery the training tail
batches use — steady-state serving then touches only the executables the
warmup compiled.

The helpers here are shared by the serve engine, ``wrapper.Net``'s
pred-executable cache, and ``tools/serve_bench.py``; keeping them in one
place is what lets the schema guarantee "zero compile events after
warmup" mean the same thing everywhere.
"""

from __future__ import annotations

from math import gcd
from typing import Optional, Sequence, Tuple

import numpy as np

# the default ladder below max_batch; max_batch itself is always a
# bucket. Small buckets keep single-request latency off the full-batch
# pad cost; the jumps are coarse enough that a handful of executables
# covers every fill level.
DEFAULT_LADDER = (1, 2, 4, 8, 16, 32, 64, 128)


def bucket_ladder(max_batch: int, align: int = 1,
                  base: Sequence[int] = DEFAULT_LADDER) -> Tuple[int, ...]:
    """Ascending bucket sizes ending at ``max_batch``.

    ``align`` is the mesh data-axis size: every bucket must split
    evenly across the data axis (jax shardings do not support uneven
    splits), so candidates that are not multiples of it are dropped.
    ``max_batch`` itself must satisfy the alignment.
    """
    max_batch = int(max_batch)
    if max_batch < 1:
        raise ValueError("max_batch must be >= 1, got %d" % max_batch)
    if align < 1 or max_batch % align:
        raise ValueError(
            "max_batch %d must be a multiple of the mesh data axis %d"
            % (max_batch, align))
    out = sorted({b for b in base
                  if 0 < b < max_batch and b % align == 0}
                 | {max_batch})
    return tuple(out)


def parse_buckets(spec: str, max_batch: int,
                  align: int = 1) -> Tuple[int, ...]:
    """Parse the ``serve_buckets`` config value: ``auto`` (the default
    ladder) or an explicit comma list like ``1,8,32``. Explicit buckets
    are validated (ascending after sort, aligned, capped by and always
    including ``max_batch``)."""
    if not spec or spec == "auto":
        return bucket_ladder(max_batch, align)
    sizes = sorted({int(t) for t in spec.split(",") if t.strip()})
    for b in sizes:
        if b < 1 or b > max_batch:
            raise ValueError(
                "serve bucket %d outside [1, max_batch=%d]"
                % (b, max_batch))
        if b % align:
            raise ValueError(
                "serve bucket %d must be a multiple of the mesh data "
                "axis %d" % (b, align))
    if max_batch % align:
        raise ValueError(
            "max_batch %d must be a multiple of the mesh data axis %d"
            % (max_batch, align))
    if not sizes or sizes[-1] != max_batch:
        sizes.append(max_batch)
    return tuple(sizes)


def pick_bucket(n: int, buckets: Sequence[int],
                extend: bool = False) -> Optional[int]:
    """Smallest bucket >= ``n``; None when ``n`` exceeds the ladder and
    ``extend`` is off. With ``extend``, oversized requests round up to
    ``max_bucket * 2**k`` — the library predictor path, where splitting
    is not an option and the compiled-shape count must stay bounded."""
    if n < 1:
        raise ValueError("batch of %d rows" % n)
    for b in buckets:
        if b >= n:
            return b
    if not extend:
        return None
    m = buckets[-1]
    while m < n:
        m *= 2
    return m


def reachable_variants(
        buckets: Sequence[int]) -> Tuple[Tuple[int, int], ...]:
    """The ``(bucket, rows)`` dispatch variants steady-state traffic
    can reach: every bucket exactly full (``rows == bucket``, the
    mask-free program), plus — when some row count actually rounds up
    to this bucket — the smallest such count (``prev_bucket + 1``, the
    padded-mask program). The one definition shared by
    ``NetTrainer.precompile_pred`` and ``InferenceEngine.warmup`` so
    the compiled set and the warm-run set cannot desynchronize."""
    out = []
    prev = 0
    for b in sorted({int(x) for x in buckets}):
        out.append((b, b))
        if prev + 1 < b:
            out.append((b, prev + 1))
        prev = b
    return tuple(out)


def mesh_align(buckets: Sequence[int], max_devices: int) -> int:
    """Largest data-axis size <= ``max_devices`` that divides every
    bucket — the mesh a serve engine built for these buckets can use.
    A ladder containing 1 (the usual case) forces a single-device data
    axis; coarse ladders (8/32/...) can shard across chips."""
    g = 0
    for b in buckets:
        g = gcd(g, int(b))
    d = max(1, min(g, max_devices))
    while g % d:
        d -= 1
    return d


def pad_to_bucket(rows: np.ndarray,
                  bucket: int) -> Tuple[np.ndarray, int]:
    """Pad ``rows`` (leading axis = batch) with zero rows up to
    ``bucket``. Returns (padded, num_batch_padd); a perfectly filled
    bucket passes through without a copy."""
    n = rows.shape[0]
    if n > bucket:
        raise ValueError("cannot pad %d rows into a bucket of %d"
                         % (n, bucket))
    if n == bucket:
        return rows, 0
    pad = np.zeros((bucket - n,) + rows.shape[1:], rows.dtype)
    return np.concatenate([rows, pad], axis=0), bucket - n

"""Loss layers: softmax, Lp regression, elementwise logistic.

Reference loss layers are self-loop layers that (1) transform the node in
Forward and (2) overwrite it with the gradient in Backprop, scaled by
``grad_scale / (batch_size * update_period)``
(loss/loss_layer_base-inl.hpp:37-66). Here each loss layer provides

- ``forward``: the prediction transform (softmax probs / identity /
  sigmoid) — what Predict and Extract observe, and
- ``loss_value``: a scalar whose ``jax.grad`` w.r.t. the *pre-transform*
  input equals the reference gradient including the grad_scale /
  batch_size scaling (the 1/update_period factor is applied by the
  trainer when an accumulation window closes, which is algebraically
  identical to the reference's per-batch pre-scaling).

The ``target`` parameter binds the loss to a named label field
(label_vec ranges, loss_layer_base:27).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from .base import Layer, Shape3


class LossLayer(Layer):
    is_loss = True
    self_loop = True

    def __init__(self, cfg=()):
        self.target = "label"
        self.grad_scale = 1.0
        self.batch_size = 0          # global batch size, set by trainer cfg
        super().__init__(cfg)

    def set_param(self, name, val):
        super().set_param(name, val)
        if name == "target":
            self.target = val
        if name == "grad_scale":
            self.grad_scale = float(val)
        if name == "batch_size":
            self.batch_size = int(val)

    def infer_shape(self, in_shapes: List[Shape3]) -> List[Shape3]:
        s = self._expect_one(in_shapes)
        self.in_shapes = [s]
        self.out_shapes = [s]
        return self.out_shapes

    def _scale(self) -> float:
        assert self.batch_size > 0, "loss layer: batch_size not set"
        return self.grad_scale / self.batch_size

    def loss_value(self, logit: jnp.ndarray, label: jnp.ndarray,
                   mask: jnp.ndarray) -> jnp.ndarray:
        """Scalar loss; mask is 1.0 for real rows, 0.0 for tail
        padding — or None when every row is real (the steady-state
        specialization skips the mask multiply)."""
        raise NotImplementedError


class SoftmaxLayer(LossLayer):
    """Softmax + cross-entropy on an integer class label (1 column).

    Logits are upcast to f32 at this boundary: in mixed-precision nets
    the activations ride bf16 and the loss is where precision returns.
    """

    def forward(self, params, state, inputs, is_train, rng):
        return [jax.nn.softmax(inputs[0].astype(jnp.float32),
                               axis=-1)], state

    def loss_value(self, logit, label, mask):
        lab = label[:, 0].astype(jnp.int32)
        logp = jax.nn.log_softmax(logit.astype(jnp.float32), axis=-1)
        ce = -jnp.take_along_axis(logp, lab[:, None], axis=-1)[:, 0]
        if mask is not None:
            ce = ce * mask
        return self._scale() * jnp.sum(ce)


class LpLossLayer(LossLayer):
    """Lp regression loss against a dense label block (p default 2).

    Reference gradient: p * |x-l|^(p-1) * sign(x-l) * scale
    (lp_loss_layer-inl.hpp:31-40) == grad of |x-l|^p * scale.
    """

    def __init__(self, cfg=()):
        self.p = 2.0
        super().__init__(cfg)

    def set_param(self, name, val):
        super().set_param(name, val)
        if name == "p":
            self.p = float(val)

    def forward(self, params, state, inputs, is_train, rng):
        return [inputs[0].astype(jnp.float32)], state

    def loss_value(self, logit, label, mask):
        d = jnp.abs(logit.astype(jnp.float32) - label)
        if self.p == 2.0:
            lp = d * d
        elif self.p == 1.0:
            lp = d
        else:
            lp = jnp.power(d, self.p)
        row = jnp.sum(lp, axis=-1)
        if mask is not None:
            row = row * mask
        return self._scale() * jnp.sum(row)


class MultiLogisticLayer(LossLayer):
    """Elementwise sigmoid + binary cross-entropy per output (multi-label).

    Reference gradient is sigmoid(x) - label (multi_logistic:25-34) ==
    grad of BCE w.r.t. the logit.
    """

    def forward(self, params, state, inputs, is_train, rng):
        return [jax.nn.sigmoid(inputs[0].astype(jnp.float32))], state

    def loss_value(self, logit, label, mask):
        logit = logit.astype(jnp.float32)
        # numerically stable BCE-with-logits
        bce = jnp.maximum(logit, 0) - logit * label \
            + jnp.log1p(jnp.exp(-jnp.abs(logit)))
        row = jnp.sum(bce, axis=-1)
        if mask is not None:
            row = row * mask
        return self._scale() * jnp.sum(row)

"""Dense / elementwise / structural layers.

TPU-native equivalents of the reference layer zoo (behavior parity with
the cited files; architecture is functional JAX, not a port):

- fullc        — fullc_layer-inl.hpp:14-146
- flatten      — flatten_layer-inl.hpp:11-44
- bias         — bias_layer-inl.hpp:14-120 (self-loop)
- relu/sigmoid/tanh/softplus — activation_layer-inl.hpp:12-41, op.h:15-101
- xelu         — xelu_layer-inl.hpp:15-51   (a>0 ? a : a/b)
- insanity (rrelu) — insanity_layer-inl.hpp:14-102 (random slope + anneal)
- prelu        — prelu_layer-inl.hpp:9-173 (custom vjp to match the
                 reference's slope gradient, which ignores clamp+noise)
- dropout      — dropout_layer-inl.hpp:12-66 (self-loop, inverted)
- concat/ch_concat — concat_layer-inl.hpp:12-79
- split        — split_layer-inl.hpp:12-45
- fixconn      — fixconn_layer-inl.hpp:14-93 (fixed sparse weights)
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .base import Layer, LayerParam, Shape3, as_mat
from ..utils.stream import open_stream


class FullConnectLayer(Layer):
    """y = x @ W + b.

    Weights are stored (in_features, num_hidden) — the natural layout for
    ``jnp.dot`` on the MXU. The reference stores the transpose
    (num_hidden, in) (fullc_layer-inl.hpp:37); the weight get/set API
    (trainer.get_weight) transposes to reference convention at the edge.
    """

    def infer_shape(self, in_shapes: List[Shape3]) -> List[Shape3]:
        s = self._expect_one(in_shapes)
        if not s.is_mat:
            raise ValueError("fullc: input must be a matrix (flatten first)")
        if self.param.num_hidden <= 0:
            raise ValueError("fullc: must set nhidden correctly")
        if self.param.num_input_node == 0:
            self.param.num_input_node = s.x
        elif self.param.num_input_node != s.x:
            raise ValueError("fullc: input hidden nodes not consistent")
        self.in_shapes = [s]
        self.out_shapes = [Shape3(1, 1, self.param.num_hidden)]
        return self.out_shapes

    def init_params(self, key: jax.Array) -> Dict[str, jnp.ndarray]:
        p = self.param
        k1, _ = jax.random.split(key)
        # reference inits (num_hidden, num_input) with fan (in, out) —
        # same fan sum, so xavier bounds agree.
        wmat = p.rand_init_weight(k1, (p.num_input_node, p.num_hidden),
                                  p.num_input_node, p.num_hidden)
        out = {"wmat": wmat}
        if p.no_bias == 0:
            out["bias"] = jnp.full((p.num_hidden,), p.init_bias, jnp.float32)
        return out

    def forward(self, params, state, inputs, is_train, rng):
        x = inputs[0]
        w = params["wmat"]
        # serve_dtype quantization spec (nnet/quantize.attach): eval
        # forwards only — the int8/fp8 matmul contracts the quantized
        # operands and the per-out-channel dequant rides the epilogue
        q = None if is_train else getattr(self, "_quant", None)
        if q is not None and q.is_affine:
            # device-resident serve weights: ``_r_dequant`` in the tree
            # means the weight arrived pre-quantized at freeze — the
            # per-dispatch weight round/clip/cast disappears and the
            # dequant vector rides as an argument instead of a closure
            # constant baked into every bucket executable
            dq = params.get("_r_dequant")
            if dq is not None:
                y = jnp.dot(q.quantize_x(x), w,
                            preferred_element_type=q.acc_dtype())
                y = y.astype(jnp.float32) * dq
            else:
                y = jnp.dot(q.quantize_x(x), q.quantize_w(w),
                            preferred_element_type=q.acc_dtype())
                y = y.astype(jnp.float32) * q.dequant_vec()
            if self.param.no_bias == 0:
                y = y + params["bias"]
            return [y], state
        bf16 = (self.param.compute_dtype == "bfloat16"
                or (q is not None and q.dtype == "bfloat16"))
        if bf16:
            x = x.astype(jnp.bfloat16)
            w = w.astype(jnp.bfloat16)
        y = jnp.dot(x, w,
                    preferred_element_type=None if bf16 else jnp.float32)
        if self.param.no_bias == 0:
            y = y + params["bias"].astype(y.dtype)
        return [y], state


class FlattenLayer(Layer):
    """Reshape (b,y,x,ch) -> (b, ch*y*x) in reference NCHW c-order."""

    def infer_shape(self, in_shapes: List[Shape3]) -> List[Shape3]:
        s = self._expect_one(in_shapes)
        self.in_shapes = [s]
        self.out_shapes = [Shape3(1, 1, s.flat_size)]
        return self.out_shapes

    def forward(self, params, state, inputs, is_train, rng):
        return [as_mat(inputs[0])], state


class BiasLayer(Layer):
    """Self-loop learned bias add on a matrix node."""

    self_loop = True

    def infer_shape(self, in_shapes: List[Shape3]) -> List[Shape3]:
        s = self._expect_one(in_shapes)
        if not s.is_mat:
            raise ValueError("bias: only works on flattened nodes")
        if self.param.num_input_node == 0:
            self.param.num_input_node = s.x
        elif self.param.num_input_node != s.x:
            raise ValueError("bias: input hidden nodes not consistent")
        self.in_shapes = [s]
        self.out_shapes = [s]
        return self.out_shapes

    def init_params(self, key: jax.Array) -> Dict[str, jnp.ndarray]:
        return {"bias": jnp.full((self.param.num_input_node,),
                                 self.param.init_bias, jnp.float32)}

    def forward(self, params, state, inputs, is_train, rng):
        return [inputs[0] + params["bias"]], state


class ActivationLayer(Layer):
    """Elementwise activation; gradient follows from autodiff, which
    matches the reference's output-based grads (op.h:15-101)."""

    _FNS = {
        "relu": jax.nn.relu,
        "sigmoid": jax.nn.sigmoid,
        "tanh": jnp.tanh,
        "softplus": jax.nn.softplus,
    }

    def __init__(self, kind: str, cfg=()):
        self.kind = kind
        super().__init__(cfg)

    def infer_shape(self, in_shapes: List[Shape3]) -> List[Shape3]:
        s = self._expect_one(in_shapes)
        self.in_shapes = [s]
        self.out_shapes = [s]
        return self.out_shapes

    def forward(self, params, state, inputs, is_train, rng):
        return [self._FNS[self.kind](inputs[0])], state


def _xelu(x: jnp.ndarray, b) -> jnp.ndarray:
    # op.h:51-55 — a>0 ? a : a/b  (division, not multiplication)
    return jnp.where(x > 0, x, x / b)


class XeluLayer(Layer):
    """Leaky relu with divisor b (default 5)."""

    def __init__(self, cfg=()):
        self.b = 5.0
        super().__init__(cfg)

    def set_param(self, name, val):
        super().set_param(name, val)
        if name == "b":
            self.b = float(val)

    def infer_shape(self, in_shapes: List[Shape3]) -> List[Shape3]:
        s = self._expect_one(in_shapes)
        self.in_shapes = [s]
        self.out_shapes = [s]
        return self.out_shapes

    def forward(self, params, state, inputs, is_train, rng):
        return [_xelu(inputs[0], self.b)], state


class InsanityLayer(Layer):
    """Randomized leaky relu (RReLU): slope divisor ~ U[lb, ub] during
    training, (lb+ub)/2 at inference, with the reference's cumulative
    bound-annealing between calm_start and calm_end steps
    (insanity_layer-inl.hpp:49-77). Annealed bounds live in layer state
    so the update stays functional under jit."""

    def __init__(self, cfg=()):
        self.lb = 5.0
        self.ub = 10.0
        self.calm_start = 0
        self.calm_end = 0
        super().__init__(cfg)

    def set_param(self, name, val):
        super().set_param(name, val)
        if name == "lb":
            self.lb = float(val)
        if name == "ub":
            self.ub = float(val)
        if name == "calm_start":
            self.calm_start = int(val)
        if name == "calm_end":
            self.calm_end = int(val)

    def infer_shape(self, in_shapes: List[Shape3]) -> List[Shape3]:
        s = self._expect_one(in_shapes)
        self.in_shapes = [s]
        self.out_shapes = [s]
        return self.out_shapes

    def init_state(self) -> Dict[str, jnp.ndarray]:
        return {
            "lb": jnp.float32(self.lb),
            "ub": jnp.float32(self.ub),
            "step": jnp.int32(0),
        }

    def forward(self, params, state, inputs, is_train, rng):
        x = inputs[0]
        lb, ub, step = state["lb"], state["ub"], state["step"]
        if self.calm_end > self.calm_start:
            # delta computed from *initial* bounds (insanity:57-60)
            delta = jnp.float32(
                (self.ub - (self.ub + self.lb) / 2.0)
                / (self.calm_end - self.calm_start))
            active = jnp.logical_and(step > self.calm_start,
                                     step < self.calm_end)
            ub = jnp.where(active, ub - delta * step, ub)
            lb = jnp.where(active, lb + delta * step, lb)
            step = jnp.where(active, step + 1, step)
        if is_train:
            assert rng is not None, "insanity layer needs an rng in training"
            mask = jax.random.uniform(rng, x.shape) * (ub - lb) + lb
            out = _xelu(x, jax.lax.stop_gradient(mask))
        else:
            out = _xelu(x, (lb + ub) / 2.0)
        new_state = dict(state, lb=lb, ub=ub, step=step)
        return [out], new_state


@jax.custom_vjp
def _prelu(x, mask):
    return jnp.where(x > 0, x, x * mask)


def _prelu_fwd(x, mask):
    return _prelu(x, mask), (x, mask)


def _prelu_bwd(res, g):
    x, mask = res
    dx = jnp.where(x > 0, g, mask * g)
    # reference gslope = sum(prelu_grad(in) * dout) with prelu_grad(a)=
    # a if a<0 else 0 — deliberately ignores the clamp and train noise
    # (prelu_layer-inl.hpp:139-158); keep that exact behavior.
    dmask = jnp.where(x < 0, x, 0.0) * g
    return dx, dmask


_prelu.defvjp(_prelu_fwd, _prelu_bwd)


class PReluLayer(Layer):
    """Learned per-channel (or per-feature) negative slope + train noise."""

    def __init__(self, cfg=()):
        self.init_slope = 0.25
        self.init_random = 0
        self.random = 0.0
        self.channel = 0
        super().__init__(cfg)

    def set_param(self, name, val):
        super().set_param(name, val)
        if name == "init_slope":
            self.init_slope = float(val)
        if name == "random_slope":
            self.init_random = int(val)
        if name == "random":
            self.random = float(val)

    def infer_shape(self, in_shapes: List[Shape3]) -> List[Shape3]:
        s = self._expect_one(in_shapes)
        self.channel = s.x if s.is_mat else s.ch
        self.in_shapes = [s]
        self.out_shapes = [s]
        return self.out_shapes

    def init_params(self, key: jax.Array) -> Dict[str, jnp.ndarray]:
        if self.init_random == 0:
            slope = jnp.full((self.channel,), self.init_slope, jnp.float32)
        else:
            slope = jax.random.uniform(key, (self.channel,)) * self.init_slope
        # tag 'bias' mirrors the reference visitor tag (prelu:61-63) so
        # bias-scoped updater params apply to the slope.
        return {"bias": slope}

    def forward(self, params, state, inputs, is_train, rng):
        x = inputs[0]
        slope = params["bias"]          # broadcasts over trailing dim
        mask = jnp.broadcast_to(slope, x.shape)
        if is_train and self.random > 0:
            assert rng is not None
            noise = jax.random.uniform(rng, x.shape) * self.random * 2.0 \
                - self.random
            mask = mask * (1.0 + noise)
        mask = jnp.clip(mask, 0.0, 1.0)
        return [_prelu(x, mask)], state


class DropoutLayer(Layer):
    """Inverted dropout; identity at inference. Self-loop layer."""

    self_loop = True

    def __init__(self, cfg=()):
        self.threshold = 0.0
        super().__init__(cfg)

    def set_param(self, name, val):
        super().set_param(name, val)
        if name == "threshold":
            self.threshold = float(val)

    def infer_shape(self, in_shapes: List[Shape3]) -> List[Shape3]:
        s = self._expect_one(in_shapes)
        if not (0.0 <= self.threshold < 1.0):
            raise ValueError("dropout: invalid threshold")
        self.in_shapes = [s]
        self.out_shapes = [s]
        return self.out_shapes

    def forward(self, params, state, inputs, is_train, rng):
        x = inputs[0]
        if not is_train or self.threshold == 0.0:
            return [x], state
        assert rng is not None, "dropout needs an rng in training"
        pkeep = 1.0 - self.threshold
        mask = (jax.random.uniform(rng, x.shape) < pkeep).astype(x.dtype) \
            / x.dtype.type(pkeep)
        return [x * mask], state


class ConcatLayer(Layer):
    """n-to-1 concat. dim=3 ('concat') joins features (x); dim=1
    ('ch_concat') joins channels — reference NCHW dims."""

    def __init__(self, dim: int, cfg=()):
        self.dim = dim
        super().__init__(cfg)

    def infer_shape(self, in_shapes: List[Shape3]) -> List[Shape3]:
        if len(in_shapes) < 2:
            raise ValueError("concat: needs more than one input")
        base = in_shapes[0]
        total = 0
        for s in in_shapes:
            # ref checks all non-concat dims equal (concat_layer:22-30)
            ref = (s.ch, s.y, s.x)
            b0 = (base.ch, base.y, base.x)
            for j, (a, b) in enumerate(zip(ref, b0)):
                nchw_dim = j + 1
                if nchw_dim != self.dim and a != b:
                    raise ValueError("concat: shape mismatch")
            total += ref[self.dim - 1]
        out = list(base)
        out[self.dim - 1] = total
        self.in_shapes = list(in_shapes)
        self.out_shapes = [Shape3(*out)]
        return self.out_shapes

    def forward(self, params, state, inputs, is_train, rng):
        if inputs[0].ndim == 2:
            if self.dim != 3:
                raise ValueError("ch_concat on matrix nodes is unsupported")
            return [jnp.concatenate(inputs, axis=1)], state
        # Inception tower tail fusion (net-level pool_concat_pallas
        # pass, nnet/net.py): the pool-branch input arrives UN-pooled
        # and one Pallas pass reduces its window while writing every
        # branch into its channel segment
        fused = getattr(self, "_fused_pool", None)
        if fused is not None and self.dim == 1:
            from .pallas_kernels import pool_concat
            pos, k, mode = fused
            return [pool_concat(tuple(inputs), pos, k, mode)], state
        axis = {1: 3, 2: 1, 3: 2}[self.dim]   # NCHW dim -> NHWC axis
        return [jnp.concatenate(inputs, axis=axis)], state


class SplitLayer(Layer):
    """1-to-n duplicate; autodiff sums the gradients (split_layer:33-44)."""

    def __init__(self, n_out: int = 2, cfg=()):
        self.n_out = n_out
        super().__init__(cfg)

    def infer_shape(self, in_shapes: List[Shape3]) -> List[Shape3]:
        s = self._expect_one(in_shapes)
        self.in_shapes = [s]
        self.out_shapes = [s] * self.n_out
        return self.out_shapes

    def forward(self, params, state, inputs, is_train, rng):
        return [inputs[0]] * self.n_out, state


class FixConnectLayer(Layer):
    """Fixed (non-learned) sparse connection matrix from a text file:
    header 'nrow ncol nnz' then 'row col value' triples, where the matrix
    is (num_hidden, num_input) in reference convention."""

    def __init__(self, cfg=()):
        self.fname_weight = ""
        super().__init__(cfg)

    def set_param(self, name, val):
        super().set_param(name, val)
        if name == "fixconn_weight":
            self.fname_weight = val

    def infer_shape(self, in_shapes: List[Shape3]) -> List[Shape3]:
        s = self._expect_one(in_shapes)
        if not s.is_mat:
            raise ValueError("fixconn: input must be a matrix")
        if self.param.num_hidden <= 0:
            raise ValueError("fixconn: must set nhidden correctly")
        if not self.fname_weight:
            raise ValueError("fixconn: must specify fixconn_weight")
        self.in_shapes = [s]
        self.out_shapes = [Shape3(1, 1, self.param.num_hidden)]
        w = np.zeros((self.param.num_hidden, s.x), np.float32)
        with open_stream(self.fname_weight, "r") as f:
            toks = f.read().split()
        nrow, ncol, nnz = int(toks[0]), int(toks[1]), int(toks[2])
        if (nrow, ncol) != w.shape:
            raise ValueError("fixconn: weight shape does not match")
        vals = toks[3:3 + 3 * nnz]
        for t in range(nnz):
            r, c = int(vals[3 * t]), int(vals[3 * t + 1])
            w[r, c] = float(vals[3 * t + 2])
        self._w = jnp.asarray(w.T)      # store (in, out) like fullc
        return self.out_shapes

    def forward(self, params, state, inputs, is_train, rng):
        return [jnp.dot(inputs[0], jax.lax.stop_gradient(self._w),
                        preferred_element_type=jnp.float32)], state

"""Pairwise layer testing: ``pairtest-<master>-<slave>``.

The reference's built-in layer correctness harness
(``/root/reference/src/layer/pairtest_layer-inl.hpp:15-203``): one
connection runs a *master* and a *slave* implementation of the same
layer on identical inputs and compares their outputs every Forward.

Functional re-design: the master's output is what flows on; the slave is
tied in with ``m + s - stop_gradient(s)`` so its value cancels exactly
while autodiff routes the *same* output-gradient to both — the
equivalent of the reference feeding both implementations the same
out-node gradient in Backprop. Both sides are initialized from the same
PRNG key and receive the same per-step RNG, so after identical updates
their weights must track each other; the running forward divergence is
recorded in layer state under ``pairtest:max_diff`` (the reference
printed/asserted it inline).

Config routing matches the reference's prefix passthrough
(``master:xxx`` / ``slave:xxx``; everything else goes to both).

This is how Pallas kernels are validated against their XLA reference
formulation (the reference used it for hand CUDA vs cuDNN vs Caffe).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .base import Layer, Shape3


class PairTestLayer(Layer):
    """Runs master + slave implementations side by side."""

    def __init__(self, master: Layer, slave: Layer,
                 cfg: Sequence[Tuple[str, str]] = ()) -> None:
        self.master = master
        self.slave = slave
        super().__init__(cfg)
        # mirror loss-ness of the wrapped layer so the net treats a
        # pairtested loss layer correctly
        self.is_loss = master.is_loss
        self.self_loop = master.self_loop

    def set_param(self, name: str, val: str) -> None:
        if name.startswith("master:"):
            self.master.set_param(name[len("master:"):], val)
        elif name.startswith("slave:"):
            self.slave.set_param(name[len("slave:"):], val)
        else:
            self.master.set_param(name, val)
            self.slave.set_param(name, val)

    def infer_shape(self, in_shapes: List[Shape3]) -> List[Shape3]:
        mo = self.master.infer_shape(list(in_shapes))
        so = self.slave.infer_shape(list(in_shapes))
        if mo != so:
            raise ValueError(
                "pairtest: master/slave output shapes disagree: %s vs %s"
                % (mo, so))
        self.in_shapes = list(in_shapes)
        self.out_shapes = mo
        return mo

    def init_params(self, key: jax.Array) -> Dict[str, jnp.ndarray]:
        # same key on both sides -> identical initial weights whenever
        # the two implementations use the same parameter shapes
        p = dict(self.master.init_params(key))
        for tag, v in self.slave.init_params(key).items():
            p["slave:" + tag] = v
        return p

    def init_state(self) -> Dict[str, jnp.ndarray]:
        s = dict(self.master.init_state())
        for tag, v in self.slave.init_state().items():
            s["slave:" + tag] = v
        s["pairtest:max_diff"] = jnp.float32(0.0)
        return s

    def _split(self, d: Dict[str, jnp.ndarray]):
        m = {k: v for k, v in d.items()
             if not k.startswith(("slave:", "pairtest:"))}
        s = {k[len("slave:"):]: v for k, v in d.items()
             if k.startswith("slave:")}
        return m, s

    @property
    def needs_mask(self):
        return self.master.needs_mask or self.slave.needs_mask

    def forward(self, params, state, inputs, is_train, rng, mask=None):
        mp, sp = self._split(params)
        ms, ss = self._split(state)

        def run(layer, p, s):
            if layer.needs_mask:
                return layer.forward(p, s, list(inputs), is_train, rng,
                                     mask=mask)
            return layer.forward(p, s, list(inputs), is_train, rng)

        mouts, ms2 = run(self.master, mp, ms)
        souts, ss2 = run(self.slave, sp, ss)
        diff = jnp.float32(0.0)
        outs = []
        for m, s in zip(mouts, souts):
            diff = jnp.maximum(diff, jnp.max(jnp.abs(m - s)))
            # value == m exactly; gradient flows identically to both
            outs.append(m + s - jax.lax.stop_gradient(s))
        new_state = dict(ms2 or ms)
        for tag, v in (ss2 or ss).items():
            new_state["slave:" + tag] = v
        new_state["pairtest:max_diff"] = jnp.maximum(
            state.get("pairtest:max_diff", jnp.float32(0.0)), diff)
        return outs, new_state

    # loss-layer protocol passthrough (when pairtesting a loss layer)

    @property
    def target(self):
        return self.master.target

    @property
    def batch_size(self):
        return self.master.batch_size

    @batch_size.setter
    def batch_size(self, v):
        self.master.batch_size = v
        self.slave.batch_size = v

    def loss_value(self, logit, labels, mask):
        return self.master.loss_value(logit, labels, mask)

"""Layer base types for the TPU-native layer zoo.

Layers are *pure functions over pytrees* — no in-place node mutation, no
device threads. The reference's hand-written backprop per layer
(``/root/reference/src/layer/layer.h:163-280``) is replaced by ``jax.grad``
through the forward computation, with ``jax.custom_vjp`` only where the
reference's gradient deliberately differs from the true gradient of its
forward (e.g. PReLU's slope gradient ignoring the clamp, see common.py).

Tensor layout is TPU-first: spatial nodes are NHWC ``(batch, y, x, ch)``
so convolutions feed the MXU without transposes; flattened nodes are 2-D
``(batch, features)`` so the feature dim is the TPU lane dim. Logical
node shapes keep the reference's ``(ch, y, x)`` convention
(``layer.h:32-72``) so config files and shape messages stay compatible:
a logical shape with ch==1 and y==1 is a "matrix" node stored 2-D.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class Shape3(NamedTuple):
    """Logical node shape without batch: (ch, y, x) — reference convention."""
    ch: int
    y: int
    x: int

    @property
    def is_mat(self) -> bool:
        # reference Node::is_mat(): size(1)==1 && size(2)==1 (layer.h:60-63)
        return self.ch == 1 and self.y == 1

    @property
    def flat_size(self) -> int:
        return self.ch * self.y * self.x


def array_shape(batch: int, s: Shape3) -> Tuple[int, ...]:
    """Concrete array shape for a logical node shape."""
    if s.is_mat:
        return (batch, s.x)
    return (batch, s.y, s.x, s.ch)


def as_mat(x: jnp.ndarray) -> jnp.ndarray:
    """View a node value as (batch, features), reference Node::mat() order.

    Reference mat() flattens NCHW c-order (ch major, then y, then x); our
    spatial arrays are NHWC so we transpose before reshaping to keep
    weight layouts interchangeable with the reference convention.
    """
    if x.ndim == 2:
        return x
    b = x.shape[0]
    return jnp.transpose(x, (0, 3, 1, 2)).reshape(b, -1)


@dataclass
class LayerParam:
    """Common layer hyper-parameters (reference param.h:15-139)."""
    num_hidden: int = 0
    init_sigma: float = 0.01
    init_uniform: float = -1.0
    init_sparse: int = 10
    init_bias: float = 0.0
    num_channel: int = 0
    random_type: int = 0        # 0 gaussian, 1 uniform/xavier, 2 kaiming
    num_group: int = 1
    kernel_height: int = 0
    kernel_width: int = 0
    stride: int = 1
    pad_y: int = 0
    pad_x: int = 0
    no_bias: int = 0
    temp_col_max: int = 64 << 18
    silent: int = 0
    num_input_channel: int = 0
    num_input_node: int = 0
    # TPU mixed precision: 'bfloat16' casts matmul/conv operands to
    # bf16 with f32 accumulation (MXU-native); weights/state stay f32.
    # New knob, no reference equivalent (2015-era f32-only).
    compute_dtype: str = "float32"
    # perf toggles (measurements in doc/perf_profile.md round 4):
    # conv_1x1_matmul lowers pointwise convs to dot_general (measured
    # neutral; off). bn_fold_affine folds BN's normalize+affine into
    # one per-channel scale/shift so the full-tensor math stays in the
    # compute dtype (+2.5% Inception-BN; DEFAULT — same math as the
    # eval path's folded form, reassociation-level rounding only)
    conv_1x1_matmul: int = 0
    bn_fold_affine: int = 1
    # route relu_max_pooling through the fused Pallas kernel where
    # applicable (stride-1 VALID square max pools that fit VMEM)
    pallas_pool: int = 0
    # run the conv's per-channel epilogue (bn_fold_eval scale/shift +
    # relu, and the quantized path's dequant) as ONE Pallas pass
    # (pallas_kernels.conv_epilogue) instead of folding the scale into
    # the weights — same math, reassociation-level rounding only
    conv_pallas_epilogue: int = 0

    def set_param(self, name: str, val: str) -> None:
        if name == "init_sigma":
            self.init_sigma = float(val)
        if name == "init_uniform":
            self.init_uniform = float(val)
        if name == "init_bias":
            self.init_bias = float(val)
        if name == "init_sparse":
            self.init_sparse = int(val)
        if name == "random_type":
            if val == "gaussian":
                self.random_type = 0
            elif val in ("uniform", "xavier"):
                self.random_type = 1
            elif val == "kaiming":
                self.random_type = 2
            else:
                raise ValueError("invalid random_type %r" % val)
        if name == "nhidden":
            self.num_hidden = int(val)
        if name == "nchannel":
            self.num_channel = int(val)
        if name == "ngroup":
            self.num_group = int(val)
        if name == "kernel_size":
            self.kernel_width = self.kernel_height = int(val)
        if name == "kernel_height":
            self.kernel_height = int(val)
        if name == "kernel_width":
            self.kernel_width = int(val)
        if name == "stride":
            self.stride = int(val)
        if name == "pad":
            self.pad_y = self.pad_x = int(val)
        if name == "pad_y":
            self.pad_y = int(val)
        if name == "pad_x":
            self.pad_x = int(val)
        if name == "no_bias":
            self.no_bias = int(val)
        if name == "silent":
            self.silent = int(val)
        if name == "temp_col_max":
            self.temp_col_max = int(val) << 18
        if name == "dtype":
            if val not in ("float32", "bfloat16"):
                raise ValueError("dtype must be float32 or bfloat16")
            self.compute_dtype = val
        if name == "conv_1x1_matmul":
            self.conv_1x1_matmul = int(val)
        if name == "bn_fold_affine":
            self.bn_fold_affine = int(val)
        if name == "pallas_pool":
            self.pallas_pool = int(val)
        if name == "conv_pallas_epilogue":
            self.conv_pallas_epilogue = int(val)

    def rand_init_weight(self, key: jax.Array, shape: Tuple[int, ...],
                         in_num: int, out_num: int) -> jnp.ndarray:
        """Weight init matching reference RandInitWeight (param.h:113-138)."""
        if self.random_type == 0:
            return self.init_sigma * jax.random.normal(key, shape, jnp.float32)
        if self.random_type == 1:
            a = float(np.sqrt(3.0 / (in_num + out_num)))
            if self.init_uniform > 0:
                a = self.init_uniform
            return jax.random.uniform(key, shape, jnp.float32, -a, a)
        if self.random_type == 2:
            if self.num_hidden > 0:
                sigma = float(np.sqrt(2.0 / self.num_hidden))
            else:
                sigma = float(np.sqrt(
                    2.0 / (self.num_channel * self.kernel_width
                           * self.kernel_height)))
            return sigma * jax.random.normal(key, shape, jnp.float32)
        raise ValueError("unsupported random_type %d" % self.random_type)


class Layer:
    """Base class: a declarative spec + pure forward.

    Lifecycle: construct with merged config -> ``infer_shape`` (records
    input shapes, returns output shapes; raises on inconsistency, like
    the reference's InitConnection checks) -> ``init_params`` /
    ``init_state`` -> ``forward``.
    """

    # class-level flags
    is_loss = False
    self_loop = False           # must be a self-loop connection

    def __init__(self, cfg: Sequence[Tuple[str, str]] = ()) -> None:
        self.param = LayerParam()
        self.in_shapes: List[Shape3] = []
        self.out_shapes: List[Shape3] = []
        for name, val in cfg:
            self.set_param(name, val)

    # -- config --------------------------------------------------------

    def set_param(self, name: str, val: str) -> None:
        self.param.set_param(name, val)

    # -- shape inference ------------------------------------------------

    def infer_shape(self, in_shapes: List[Shape3]) -> List[Shape3]:
        raise NotImplementedError

    def _expect_one(self, in_shapes: List[Shape3]) -> Shape3:
        if len(in_shapes) != 1:
            raise ValueError("%s: only supports 1-1 connection"
                             % type(self).__name__)
        return in_shapes[0]

    # -- parameters / state ---------------------------------------------

    def init_params(self, key: jax.Array) -> Dict[str, jnp.ndarray]:
        """Learnable parameters; keys 'wmat'/'bias' mirror the reference
        visitor tags (visitor.h:26-165) so tag-scoped updater params and
        weight get/set keep working."""
        return {}

    def init_state(self) -> Dict[str, jnp.ndarray]:
        """Non-learnable persistent state (BN running stats, annealing)."""
        return {}

    # -- compute ---------------------------------------------------------

    #: layers that reduce over the batch dimension (batch norm) set this
    #: so FuncNet passes them the padded-row mask as a keyword
    needs_mask = False

    def forward(self, params: Dict[str, jnp.ndarray],
                state: Dict[str, jnp.ndarray],
                inputs: List[jnp.ndarray],
                is_train: bool,
                rng: Optional[jax.Array]) -> Tuple[List[jnp.ndarray],
                                                   Dict[str, jnp.ndarray]]:
        raise NotImplementedError

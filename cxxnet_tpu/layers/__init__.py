"""Layer registry: string type -> layer factory.

Mirrors the reference's string->type registry + factory
(``/root/reference/src/layer/layer.h:324-365``,
``layer_impl-inl.hpp:36-77``), including the vestigial types that the
reference registers but cannot construct (``maxout``, ``softplus`` maps
via the enum but has no factory case — configuring them errors, matching
``layer_impl-inl.hpp``; we support softplus since our factory covers it).

``pairtest-A-B`` is a real layer type (layer.h:316-317,358-362 encodes
master*1024+slave; we parse the string directly): master and slave run
side by side, divergence is tracked in layer state — see pairtest.py.
The NumPy-reference comparisons in ``tests/test_layers.py`` complement
it for gradient checks.
"""

from __future__ import annotations

from typing import Callable, Dict, Sequence, Tuple

from .base import Layer, LayerParam, Shape3, array_shape, as_mat
from .common import (ActivationLayer, BiasLayer, ConcatLayer, DropoutLayer,
                     FixConnectLayer, FlattenLayer, FullConnectLayer,
                     InsanityLayer, PReluLayer, SplitLayer, XeluLayer)
from .conv import (BatchNormLayer, ConvolutionLayer, InsanityPoolingLayer,
                   LRNLayer, PoolingLayer)
from .loss import LossLayer, LpLossLayer, MultiLogisticLayer, SoftmaxLayer
from .pairtest import PairTestLayer
from .pallas_kernels import PallasFullConnectLayer
from .torch_adapter import TorchLayer

_FACTORY: Dict[str, Callable[..., Layer]] = {
    "fullc": lambda cfg, **kw: FullConnectLayer(cfg),
    "pallas_fullc": lambda cfg, **kw: PallasFullConnectLayer(cfg),
    "fixconn": lambda cfg, **kw: FixConnectLayer(cfg),
    "bias": lambda cfg, **kw: BiasLayer(cfg),
    "softmax": lambda cfg, **kw: SoftmaxLayer(cfg),
    "relu": lambda cfg, **kw: ActivationLayer("relu", cfg),
    "sigmoid": lambda cfg, **kw: ActivationLayer("sigmoid", cfg),
    "tanh": lambda cfg, **kw: ActivationLayer("tanh", cfg),
    "softplus": lambda cfg, **kw: ActivationLayer("softplus", cfg),
    "flatten": lambda cfg, **kw: FlattenLayer(cfg),
    "dropout": lambda cfg, **kw: DropoutLayer(cfg),
    "conv": lambda cfg, **kw: ConvolutionLayer(cfg),
    "max_pooling": lambda cfg, **kw: PoolingLayer("max", cfg),
    "sum_pooling": lambda cfg, **kw: PoolingLayer("sum", cfg),
    "avg_pooling": lambda cfg, **kw: PoolingLayer("avg", cfg),
    "relu_max_pooling": lambda cfg, **kw: PoolingLayer("max", cfg,
                                                       pre_relu=True),
    "pallas_relu_max_pooling": lambda cfg, **kw: PoolingLayer(
        "max", cfg, pre_relu=True, use_pallas=True),
    "lrn": lambda cfg, **kw: LRNLayer(cfg),
    "concat": lambda cfg, **kw: ConcatLayer(3, cfg),
    "ch_concat": lambda cfg, **kw: ConcatLayer(1, cfg),
    "xelu": lambda cfg, **kw: XeluLayer(cfg),
    "split": lambda cfg, n_out=2, **kw: SplitLayer(n_out, cfg),
    "insanity": lambda cfg, **kw: InsanityLayer(cfg),
    "rrelu": lambda cfg, **kw: InsanityLayer(cfg),
    "insanity_max_pooling": lambda cfg, **kw: InsanityPoolingLayer("max", cfg),
    "lp_loss": lambda cfg, **kw: LpLossLayer(cfg),
    "l2_loss": lambda cfg, **kw: LpLossLayer(cfg),
    "multi_logistic": lambda cfg, **kw: MultiLogisticLayer(cfg),
    "prelu": lambda cfg, **kw: PReluLayer(cfg),
    "batch_norm": lambda cfg, **kw: BatchNormLayer(True, cfg),
    "batch_norm_no_ma": lambda cfg, **kw: BatchNormLayer(False, cfg),
    # fused-epilogue variant: the folded scale/shift(+relu) runs as one
    # Pallas pass (pallas_kernels.bn_apply); numerically identical to
    # batch_norm with bn_fold_affine — pairtest-validated
    "pallas_batch_norm": lambda cfg, **kw: BatchNormLayer(
        True, cfg, use_pallas=True),
    # cross-framework oracle (the caffe adapter equivalent): a torch-
    # backed fullc/conv for pairtest-conv-torch style in-net A/B checks
    "torch": lambda cfg, **kw: TorchLayer(cfg),
}

# registered in the reference enum but rejected by its factory
_VESTIGIAL = ("maxout",)


def known_layer_type(type_str: str) -> bool:
    if type_str.startswith("pairtest-"):
        a, _, b = type_str[len("pairtest-"):].partition("-")
        return known_layer_type(a) and known_layer_type(b)
    return type_str in _FACTORY or type_str in _VESTIGIAL


def create_layer(type_str: str, cfg: Sequence[Tuple[str, str]] = (),
                 **kwargs) -> Layer:
    """Create a layer from its config-file type string."""
    if type_str.startswith("pairtest-"):
        a, _, b = type_str[len("pairtest-"):].partition("-")
        if not a or not b:
            raise ValueError("pairtest type must be pairtest-<master>-<slave>")
        cfg = list(cfg)
        shared = [(n, v) for n, v in cfg
                  if not n.startswith(("master:", "slave:"))]
        master = create_layer(a, shared + [
            (n[len("master:"):], v) for n, v in cfg
            if n.startswith("master:")], **kwargs)
        slave = create_layer(b, shared + [
            (n[len("slave:"):], v) for n, v in cfg
            if n.startswith("slave:")], **kwargs)
        return PairTestLayer(master, slave)
    if type_str in _VESTIGIAL:
        raise ValueError(
            "layer type %r is registered but has no implementation "
            "(matches reference factory behavior)" % type_str)
    if type_str not in _FACTORY:
        raise ValueError("unknown layer type: %r" % type_str)
    return _FACTORY[type_str](list(cfg), **kwargs)


__all__ = [
    "Layer", "LayerParam", "Shape3", "array_shape", "as_mat",
    "create_layer", "known_layer_type", "LossLayer",
]

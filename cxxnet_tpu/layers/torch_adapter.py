"""Foreign-framework oracle layer: torch inside the net.

The reference embeds Caffe layers in-net for cross-framework A/B
validation (``/root/reference/src/plugin/caffe_adapter-inl.hpp:27-231``,
enabled via CXXNET_USE_CAFFE_ADAPTOR) — the third corner of its
validation triangle: hand kernel vs library vs foreign framework.  Here
the foreign framework is torch (CPU), embedded the TPU-native way:

- forward runs through ``jax.pure_callback`` (a host call inside the
  jitted program — shapes are static, so XLA treats it as an opaque op);
- backward is a ``jax.custom_vjp`` whose bwd rule calls torch autograd
  on the host, so ``jax.grad`` through a torch layer yields torch's
  gradients.

Config type ``torch``: infers the op from the same keys the native
layers use (``nhidden`` -> linear, ``nchannel``/``kernel_size`` ->
conv2d), so ``pairtest-conv-torch`` / ``pairtest-fullc-torch`` need no
extra parameters and share one weight init with the master.  Parameter
layouts match the native layers exactly (fullc wmat (in,out); conv wmat
HWIO); conversion to torch's (out,in) / OIHW happens inside the
callback.

This is a validation oracle, not a production path: the callback
round-trips device->host per call and is deliberately unsharded.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from .base import Layer, Shape3
from .conv import _conv_out_dim


def _torch():
    import torch
    return torch


# ---------------------------------------------------------------------------
# host-side compute (numpy in / numpy out)

def _host_forward(op, stride, pad, groups, x, w, b):
    torch = _torch()
    with torch.no_grad():
        # copies keep torch off jax's read-only callback buffers
        tx = torch.from_numpy(np.array(x, copy=True))
        tw = torch.from_numpy(np.array(w, copy=True))
        tb = torch.from_numpy(np.array(b, copy=True)) \
            if b is not None else None
        if op == "fullc":
            y = torch.nn.functional.linear(tx, tw.t(), tb)
        else:
            # NHWC -> NCHW, HWIO -> OIHW
            y = torch.nn.functional.conv2d(
                tx.permute(0, 3, 1, 2),
                tw.permute(3, 2, 0, 1), tb,
                stride=stride, padding=pad, groups=groups)
            y = y.permute(0, 2, 3, 1).contiguous()
        return y.numpy().astype(np.float32)


def _host_backward(op, stride, pad, groups, has_bias, x, w, b, gy):
    torch = _torch()
    tx = torch.from_numpy(np.array(x, copy=True)).requires_grad_(True)
    tw = torch.from_numpy(np.array(w, copy=True)).requires_grad_(True)
    tb = torch.from_numpy(np.array(b, copy=True)).requires_grad_(True) \
        if has_bias else None
    if op == "fullc":
        y = torch.nn.functional.linear(tx, tw.t(), tb)
        gy_t = torch.from_numpy(np.array(gy, copy=True))
    else:
        y = torch.nn.functional.conv2d(
            tx.permute(0, 3, 1, 2), tw.permute(3, 2, 0, 1), tb,
            stride=stride, padding=pad, groups=groups)
        gy_t = torch.from_numpy(
            np.array(gy, copy=True)).permute(0, 3, 1, 2)
    y.backward(gy_t)
    gx = tx.grad.numpy().astype(np.float32)
    gw = tw.grad.numpy().astype(np.float32)
    if has_bias:
        return gx, gw, tb.grad.numpy().astype(np.float32)
    return gx, gw


# ---------------------------------------------------------------------------
# jax-side wrappers (custom_vjp around pure_callback)

@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4))
def _torch_apply(op, stride, pad, groups, out_shape, x, w, b):
    return jax.pure_callback(
        partial(_host_forward, op, stride, pad, groups),
        jax.ShapeDtypeStruct(out_shape, jnp.float32), x, w, b,
        vmap_method="sequential")


def _torch_apply_fwd(op, stride, pad, groups, out_shape, x, w, b):
    y = _torch_apply(op, stride, pad, groups, out_shape, x, w, b)
    return y, (x, w, b)


def _torch_apply_bwd(op, stride, pad, groups, out_shape, res, gy):
    x, w, b = res
    has_bias = b is not None
    shapes = [jax.ShapeDtypeStruct(x.shape, jnp.float32),
              jax.ShapeDtypeStruct(w.shape, jnp.float32)]
    if has_bias:
        shapes.append(jax.ShapeDtypeStruct(b.shape, jnp.float32))
    grads = jax.pure_callback(
        partial(_host_backward, op, stride, pad, groups, has_bias),
        tuple(shapes), x, w, b if has_bias else jnp.zeros((0,)), gy,
        vmap_method="sequential")
    if has_bias:
        return tuple(grads)
    return grads[0], grads[1], None


_torch_apply.defvjp(_torch_apply_fwd, _torch_apply_bwd)


# ---------------------------------------------------------------------------

class TorchLayer(Layer):
    """The 'torch' config layer: torch-backed fullc or conv."""

    def __init__(self, cfg=()):
        self.op = ""            # "" = infer from config keys
        super().__init__(cfg)

    def set_param(self, name, val):
        super().set_param(name, val)
        if name == "op":
            self.op = val

    def _resolve_op(self) -> str:
        if self.op:
            return self.op
        if self.param.num_channel > 0:
            return "conv"
        if self.param.num_hidden > 0:
            return "fullc"
        raise ValueError(
            "torch layer: set nhidden (linear) or nchannel (conv)")

    def infer_shape(self, in_shapes: List[Shape3]) -> List[Shape3]:
        s = self._expect_one(in_shapes)
        p = self.param
        op = self._resolve_op()
        self.in_shapes = [s]
        if op == "fullc":
            if not s.is_mat:
                raise ValueError("torch fullc: input must be a matrix")
            if p.num_input_node == 0:
                p.num_input_node = s.x
            self.out_shapes = [Shape3(1, 1, p.num_hidden)]
        else:
            if p.pad_y != p.pad_x:
                raise ValueError("torch conv: asymmetric pad unsupported")
            if p.num_input_channel == 0:
                p.num_input_channel = s.ch
            oy = _conv_out_dim(s.y, p.pad_y, p.kernel_height, p.stride)
            ox = _conv_out_dim(s.x, p.pad_x, p.kernel_width, p.stride)
            self.out_shapes = [Shape3(p.num_channel, oy, ox)]
        return self.out_shapes

    def init_params(self, key: jax.Array) -> Dict[str, jnp.ndarray]:
        # identical layouts + init path as the native layers, so a
        # pairtest master/slave pair starts from the same weights
        p = self.param
        if self._resolve_op() == "fullc":
            k1, _ = jax.random.split(key)
            wmat = p.rand_init_weight(
                k1, (p.num_input_node, p.num_hidden),
                p.num_input_node, p.num_hidden)
            out = {"wmat": wmat}
            if p.no_bias == 0:
                out["bias"] = jnp.full((p.num_hidden,), p.init_bias,
                                       jnp.float32)
            return out
        in_pg = p.num_input_channel // p.num_group
        shape = (p.kernel_height, p.kernel_width, in_pg, p.num_channel)
        fan_in = in_pg * p.kernel_height * p.kernel_width
        fan_out = p.num_channel // p.num_group
        out = {"wmat": p.rand_init_weight(key, shape, fan_in, fan_out)}
        if p.no_bias == 0:
            out["bias"] = jnp.full((p.num_channel,), p.init_bias,
                                   jnp.float32)
        return out

    def forward(self, params, state, inputs, is_train, rng):
        p = self.param
        op = self._resolve_op()
        x = inputs[0]
        b = params.get("bias")
        out3 = self.out_shapes[0]
        if op == "fullc":
            out_shape = (x.shape[0], out3.x)
        else:
            out_shape = (x.shape[0], out3.y, out3.x, out3.ch)
        y = _torch_apply(op, p.stride, p.pad_y, p.num_group,
                         out_shape, x, params["wmat"], b)
        return [y], state
